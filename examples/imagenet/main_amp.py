"""ImageNet ResNet AMP trainer — BASELINE configs[1]
(ref: examples/imagenet/main_amp.py:95-543: opt-level flags, apex DDP,
CUDA-stream data prefetcher, nvtx ranges, checkpoint resume).

TPU re-design: the mesh replaces DDP + the launcher; the host->device
prefetch stream is ``jax.device_put`` overlapped by dispatch-ahead (the
train step is async until the loss read); nvtx becomes
``jax.profiler.StepTraceAnnotation``; checkpointing is a flat npz of
the param/optimizer pytrees. Runs on synthetic data unless
``--data-dir`` points at npz shards (the reference's DALI/folder
pipeline is out of scope for the example).

Run (CPU mesh smoke):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python main_amp.py --arch tiny --steps 10 --batch-size 16
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet, ResNetConfig, cross_entropy_logits
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.transformer import parallel_state as ps


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="apex_tpu imagenet trainer")
    ap.add_argument("--arch", default="resnet50",
                    choices=["resnet50", "tiny"])
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=256,
                    help="global batch size")
    ap.add_argument("--image-size", type=int, default=None)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--weight-decay", type=float, default=1e-4)
    ap.add_argument("--opt-level", default="O5",
                    help="O0..O5; O5 = bf16 + fp32 master (TPU default)")
    ap.add_argument("--sync-bn", action="store_true",
                    help="SyncBatchNorm over the data axis")
    ap.add_argument("--print-freq", type=int, default=10)
    ap.add_argument("--resume", default=None)
    ap.add_argument("--save", default=None)
    ap.add_argument("--profile-dir", default=None)
    return ap.parse_args(argv)


def save_checkpoint(path, params, opt_state_masters, step):
    leaves, _ = jax.tree_util.tree_flatten((params, opt_state_masters))
    np.savez(path, step=step,
             **{f"l{i}": np.asarray(l) for i, l in enumerate(leaves)})


def load_checkpoint(path, params, opt_state_masters):
    data = np.load(path)
    leaves, treedef = jax.tree_util.tree_flatten(
        (params, opt_state_masters))
    new = [jnp.asarray(data[f"l{i}"]) for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, new), int(data["step"])


def main(argv=None):
    args = parse_args(argv)
    mesh = ps.initialize_model_parallel()
    dp = ps.get_data_parallel_world_size()
    if args.batch_size % dp:
        raise ValueError(f"batch size {args.batch_size} % dp {dp} != 0")

    if args.arch == "tiny":
        cfg = ResNetConfig.resnet18ish(
            num_classes=100,
            bn_axis_name=ps.DATA_AXIS if args.sync_bn else None,
            dtype=jnp.float32)
        size = args.image_size or 32
    else:
        cfg = ResNetConfig.resnet50(
            bn_axis_name=ps.DATA_AXIS if args.sync_bn else None)
        size = args.image_size or 224
    model = ResNet(cfg)

    # synthetic imagenet-shaped data (the reference's folder pipeline
    # feeds the same shapes), staged through the native prefetch
    # pipeline (ref main_amp.py data_prefetcher)
    from apex_tpu.runtime import PrefetchLoader

    rng = np.random.RandomState(0)

    def batches():
        while True:
            yield {
                "x": rng.rand(args.batch_size, size, size, 3).astype(
                    np.float32),
                "y": rng.randint(0, cfg.num_classes,
                                 args.batch_size).astype(np.int32),
            }

    loader = iter(PrefetchLoader(batches(), depth=2))
    first = next(loader)
    x, y = first["x"], first["y"]

    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables.get(
        "batch_stats", {})
    opt = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay, impl="xla")
    params, opt_state, amp_state = amp.initialize(
        params, opt, opt_level=args.opt_level)
    scaler = amp.make_scaler(amp_state.properties)
    sstate = amp_state.scalers[0]
    ddp = DistributedDataParallel()
    start_step = 0
    if args.resume and os.path.exists(args.resume):
        (params, _), start_step = load_checkpoint(
            args.resume, params, None)
        print(f"resumed from {args.resume} at step {start_step}")

    spec_x = P(ps.DATA_AXIS)

    @jax.jit
    def train_step(params, batch_stats, opt_state, sstate, x, y):
        def local(p, bs, x, y):
            def loss_fn(p):
                logits, mut = model.apply(
                    {"params": p, "batch_stats": bs}, x, train=True,
                    mutable=["batch_stats"])
                return scaler.scale_loss(
                    cross_entropy_logits(logits, y), sstate), mut
            (sloss, mut), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            return sloss, ddp.allreduce_grads(g), mut["batch_stats"]

        sloss, grads, batch_stats = shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), spec_x, spec_x),
            out_specs=(P(), P(), P()), check_vma=False,
        )(params, batch_stats, x, y)
        new_params, opt_state = opt.step(
            opt_state, grads, grad_scale=sstate.loss_scale,
            skip_if_nonfinite=True)
        sstate = scaler.update(sstate, opt_state.found_inf)
        return new_params, batch_stats, opt_state, sstate, sloss

    t0 = time.perf_counter()
    for i in range(start_step, args.steps):
        batch = next(loader)
        x, y = batch["x"], batch["y"]
        ctx = (jax.profiler.StepTraceAnnotation("train", step_num=i)
               if args.profile_dir else _null())
        with ctx:
            params, batch_stats, opt_state, sstate, sloss = train_step(
                params, batch_stats, opt_state, sstate, x, y)
        if i % args.print_freq == 0 or i == args.steps - 1:
            loss = float(sloss) / float(sstate.loss_scale)
            dt = time.perf_counter() - t0
            ips = args.batch_size * (i - start_step + 1) / dt
            print(f"step {i:5d}  loss {loss:.4f}  {ips:8.1f} img/s")

    # end-of-run artifact line (ref main_amp.py's epoch summary): one
    # JSON record with wall-clock throughput, persisted to
    # bench_records/ when this ran on real hardware so example runs are
    # load-bearing evidence, not just demos
    jax.block_until_ready(sloss)
    total_dt = time.perf_counter() - t0
    n_run = args.steps - start_step
    summary = {
        "example": "imagenet_main_amp",
        "arch": args.arch,
        "opt_level": args.opt_level,
        "steps": n_run,
        "global_batch": args.batch_size,
        "imgs_per_sec": round(args.batch_size * n_run / total_dt, 1),
        "final_loss": round(float(sloss) / float(sstate.loss_scale), 4),
        "backend": str(jax.default_backend()),
        "n_devices": len(jax.devices()),
    }
    import json as _json

    print(_json.dumps(summary))
    if summary["backend"] == "tpu":
        from apex_tpu.records import write_record

        write_record("example_imagenet", summary, backend="tpu")

    if args.save:
        save_checkpoint(args.save, params, None, args.steps)
        print(f"saved {args.save}")
    ps.destroy_model_parallel()
    return float(sloss) / float(sstate.loss_scale)


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


if __name__ == "__main__":
    main()

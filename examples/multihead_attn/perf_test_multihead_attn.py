"""Multihead-attention standalone perf sweep
(ref: apex/contrib/examples/multihead_attn/perf_test_multihead_attn.py).

Sweeps batch size for a stack of Self/Encdec multihead-attention layers
and reports per-layer step time, comparing the fused Pallas path
(impl='fast') against the score-materializing reference path
(--ref -> impl='default'). CUDA events become the chained-iteration
timing protocol (queue all trials inside one jitted loop, fence once).

    python examples/multihead_attn/perf_test_multihead_attn.py \
        --seq-length 64 --num-seqs-start 10 --num-seqs-stop 120
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)


def build_layer(args, impl):
    cls = EncdecMultiheadAttn if args.encdec_attn else SelfMultiheadAttn
    return cls(
        embed_dim=args.hidden_dim, num_heads=args.heads, dropout=0.1,
        bias=args.biases, include_norm_add=args.norm_add, impl=impl,
        dtype=jnp.bfloat16 if jax.default_backend() != "cpu"
        else jnp.float32,
    )


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Multihead Attention Standalone Test")
    p.add_argument("--seq-length", default=64, type=int)
    p.add_argument("--num-seqs-start", default=10, type=int)
    p.add_argument("--num-seqs-stop", default=120, type=int)
    p.add_argument("--num-seqs-inc", default=5, type=int)
    p.add_argument("--trials", default=20, type=int)
    p.add_argument("--warmup-trials", default=5, type=int)
    p.add_argument("--layers", default=18, type=int)
    p.add_argument("--hidden-dim", default=1024, type=int)
    p.add_argument("--heads", default=16, type=int)
    p.add_argument("--encdec-attn", action="store_true")
    p.add_argument("--norm-add", action="store_true")
    p.add_argument("--ref", action="store_true",
                   help="reference (score-materializing) implementation")
    p.add_argument("--fwd", action="store_true",
                   help="only execute the forward pass")
    p.add_argument("--biases", action="store_true")
    args = p.parse_args(argv)
    if args.trials < 1:
        p.error("--trials must be >= 1")

    impl = "default" if args.ref else (
        "fast" if jax.default_backend() not in ("cpu",) else "interpret")
    layer = build_layer(args, impl)
    rng = np.random.RandomState(111)
    rows = []

    for seqs in range(args.num_seqs_start, args.num_seqs_stop + 1,
                      args.num_seqs_inc):
        x = jnp.asarray(
            rng.randn(args.seq_length, seqs, args.hidden_dim)
            .astype(np.float32) * 0.5, layer.dtype)
        kv = x
        init_args = (x,) if not args.encdec_attn else (x, kv)
        params = layer.init(jax.random.PRNGKey(0), *init_args,
                            is_training=False)

        def stack(p, x):
            h = x
            for i in range(args.layers):
                call = (h,) if not args.encdec_attn else (h, kv)
                out, _ = layer.apply(
                    p, *call, is_training=True,
                    rngs={"dropout": jax.random.PRNGKey(i)})
                h = out
            return jnp.sum(h.astype(jnp.float32) ** 2)

        if args.fwd:
            fn = jax.jit(stack)
        else:
            fn = jax.jit(jax.value_and_grad(stack))

        out = None
        for _ in range(args.warmup_trials):
            out = fn(params, x)
        if out is not None:     # fence the warmup (if any)
            jax.device_get(jax.tree.leaves(out)[0])
        t0 = time.perf_counter()
        for _ in range(args.trials):
            out = fn(params, x)
        jax.device_get(jax.tree.leaves(out)[0])
        elapsed = (time.perf_counter() - t0) / args.trials
        per_layer_ms = elapsed * 1e3 / args.layers
        rows.append((seqs, per_layer_ms))
        mode = "fwd" if args.fwd else "fwd+bwd"
        print(f"[{'encdec' if args.encdec_attn else 'self'} {impl:9s} "
              f"{mode}] seqs={seqs:4d} x seq={args.seq_length} "
              f"-> {per_layer_ms:8.3f} ms/layer")
    return rows


if __name__ == "__main__":
    sys.exit(0 if main() else 1)

"""Minimal DDP + amp example — BASELINE configs[0] (MNIST-MLP parity run)
(ref: examples/simple/distributed/distributed_data_parallel.py, 65 LoC:
torch.distributed.launch + apex.parallel.DistributedDataParallel +
amp O1).

TPU version: one process, one mesh — the "launcher" is the device mesh
itself (``initialize_model_parallel``), DDP is grad-psum over the data
axis inside ``shard_map``, and amp O1 is a precision policy + loss
scaler carried functionally.

Run (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python distributed_data_parallel.py --steps 50
"""

from __future__ import annotations

import argparse
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.transformer import parallel_state as ps


def mnist_mlp_params(key, hidden=128):
    k1, k2, k3 = jax.random.split(key, 3)
    init = jax.nn.initializers.he_normal()
    return {
        "fc1": {"w": init(k1, (784, hidden), jnp.float32),
                "b": jnp.zeros((hidden,), jnp.float32)},
        "fc2": {"w": init(k2, (hidden, hidden), jnp.float32),
                "b": jnp.zeros((hidden,), jnp.float32)},
        "out": {"w": init(k3, (hidden, 10), jnp.float32),
                "b": jnp.zeros((10,), jnp.float32)},
    }


def mlp_apply(p, x):
    x = jax.nn.relu(x @ p["fc1"]["w"] + p["fc1"]["b"])
    x = jax.nn.relu(x @ p["fc2"]["w"] + p["fc2"]["b"])
    return x @ p["out"]["w"] + p["out"]["b"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=64,
                    help="global batch (split over the data axis)")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--opt-level", default="O1")
    args = ap.parse_args(argv)

    mesh = ps.initialize_model_parallel()   # all devices on the data axis
    dp = ps.get_data_parallel_world_size()
    print(f"mesh: data={dp}")

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(args.batch_size, 784), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, args.batch_size), jnp.int32)

    params = mnist_mlp_params(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=args.lr, momentum=0.9, impl="xla")
    # amp.initialize: casts params per opt-level, builds scaler state,
    # inits the optimizer from the fp32 masters (ref amp O1/O2 flow)
    params, opt_state, amp_state = amp.initialize(
        params, opt, opt_level=args.opt_level)
    scaler = amp.make_scaler(amp_state.properties)
    sstate = amp_state.scalers[0]
    ddp = DistributedDataParallel()

    def local_loss(p, x, y):
        logits = mlp_apply(p, x).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        return jnp.mean(lse - jnp.take_along_axis(
            logits, y[:, None], -1)[:, 0])

    @jax.jit
    def step(params, opt_state, sstate, x, y):
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P(ps.DATA_AXIS), P(ps.DATA_AXIS)),
            out_specs=(P(), P()), check_vma=False)
        def grads_fn(p, x, y):
            loss, g = jax.value_and_grad(
                lambda p: scaler.scale_loss(local_loss(p, x, y), sstate))(p)
            return loss, ddp.allreduce_grads(g)   # psum-mean over "data"

        scaled_loss, grads = grads_fn(params, x, y)
        new_params, opt_state = opt.step(
            opt_state, grads, grad_scale=sstate.loss_scale,
            skip_if_nonfinite=True)
        sstate2 = scaler.update(sstate, opt_state.found_inf)
        return new_params, opt_state, sstate2, scaled_loss

    for i in range(args.steps):
        params, opt_state, sstate, sloss = step(
            params, opt_state, sstate, x, y)
        if i % 10 == 0 or i == args.steps - 1:
            loss = float(sloss) / float(sstate.loss_scale)
            print(f"step {i:4d}  loss {loss:.4f}  "
                  f"scale {float(sstate.loss_scale):.0f}")

    ps.destroy_model_parallel()
    return float(sloss) / float(sstate.loss_scale)


if __name__ == "__main__":
    main()

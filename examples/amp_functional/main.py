"""Porting a reference amp O1 model with zero registration.

The reference flow (ref: apex amp docs, examples/dcgan/main_amp.py):

    model, optimizer = amp.initialize(model, optimizer, opt_level="O1")
    ...
    with amp.scale_loss(loss, optimizer) as scaled_loss:
        scaled_loss.backward()

where every ``torch.nn.functional`` call inside the model is patched to
the shipped classification (convs/linears fp16, softmax/losses fp32,
ref apex/amp/lists/functional_overrides.py:18-92). The apex_tpu
equivalent: write the model against ``amp.F`` — the same shipped
classification as a policy-aware functional namespace — and let
``amp.initialize`` activate the policy. Nothing else to register.

The training loop runs the fused train-step path
(``optimizers.make_train_step``): everything the reference's
``scale_loss`` block does imperatively — unscale, overflow check,
skip-step, scale schedule — plus the optimizer update compiles into
ONE jitted, donation-aware program, and the gradients are taken
straight into the flat master buffer (``space.grad_fn``) so the hot
loop never packs a per-leaf tree.

Run (CPU ok): python examples/amp_functional/main.py
"""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD, make_train_step

F = amp.F


def model(params, x):
    # whitelist ops run in the policy compute dtype (fp16 under O1,
    # bf16 under O4); blacklist ops compute fp32 — exactly the
    # reference's patched-namespace behavior, visible in the dtypes
    h = F.conv2d(x, params["conv_w"], params["conv_b"], padding=1)
    h = F.relu(h)                                   # matches input dtype
    h = h.reshape(h.shape[0], -1)
    h = F.linear(h, params["fc1_w"], params["fc1_b"])
    h = F.layer_norm(h, h.shape[-1])                # fp32 always
    h = F.gelu(h)
    return F.linear(h, params["fc2_w"], params["fc2_b"])


def main():
    rng = np.random.RandomState(0)
    n, c, s, classes = 64, 3, 8, 10
    X = jnp.asarray(rng.randn(n, c, s, s).astype(np.float32))
    Y = jnp.asarray(rng.randint(0, classes, (n,)))

    params = {
        "conv_w": jnp.asarray(rng.randn(8, c, 3, 3).astype(np.float32) * 0.2),
        "conv_b": jnp.zeros((8,)),
        "fc1_w": jnp.asarray(
            rng.randn(32, 8 * s * s).astype(np.float32) * 0.05),
        "fc1_b": jnp.zeros((32,)),
        "fc2_w": jnp.asarray(rng.randn(classes, 32).astype(np.float32) * 0.1),
        "fc2_b": jnp.zeros((classes,)),
    }

    opt = FusedSGD(lr=0.05, momentum=0.9)
    # O1: fp16 compute via amp.F, fp32 masters, dynamic loss scaling
    params, opt_state, amp_state = amp.initialize(
        params, opt, opt_level="O1")

    def loss_fn(p):
        return F.cross_entropy(model(p, X), Y)     # fp32 loss (blacklist)

    # ONE compiled program per step: unscale (1/loss_scale) folded into
    # the fused update sweep, overflow-gated skip, scaler schedule
    # advanced — the whole `with amp.scale_loss(...)` flow. The state
    # and scaler-state arguments are DONATED: rebind both every step.
    scaler = amp.make_scaler(amp_state.properties)
    step = make_train_step(opt, scaler=scaler)
    scaler_state = amp_state.scalers[0]

    # grads of the SCALED loss, taken straight into the flat master
    # buffer — the ".backward()" line, with no per-leaf pack after it
    flat_vg = jax.jit(opt_state.space.grad_fn(
        lambda p, scale: loss_fn(p) * scale, with_value=True))

    l0 = loss = None
    for _ in range(30):
        scale = scaler_state.loss_scale
        scaled_loss, g = flat_vg(opt_state.master, scale)
        loss = float(scaled_loss) / float(scale)
        opt_state, scaler_state, _aux = step(opt_state, g, scaler_state)
        if l0 is None:
            l0 = loss
    print(f"O1 training: loss {l0:.4f} -> {loss:.4f} "
          f"(scale {float(scaler_state.loss_scale):.0f})")
    assert loss < l0, "loss did not improve"


if __name__ == "__main__":
    main()

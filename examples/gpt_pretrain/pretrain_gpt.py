"""GPT pretraining on the GSPMD mesh — the full L5 stack.

The reference exercises this workload class through its transformer test
harness (ref: tests/L0/run_transformer/run_gpt_minimal_test.py,
gpt_scaling_test.py: parallel_state groups + Megatron layers + 1F1B
schedule); this example is the runnable equivalent on the ONE mesh
substrate: `initialize_mesh(batch, pipe, model)`, a pipeline schedule
on the ``pipe`` axis (1F1B by default; ``--schedule interleaved_1f1b``
with ``--model-chunks 2`` for the interleaved variant), tensor
parallelism from the plan's NamedShardings, fused Adam on the flat
master buffer inside the same donated program, and orbax checkpoint +
exact resume.

Run (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python pretrain_gpt.py --steps 20 --tp 2 --pp 2

Run (TPU slice): drop the env vars; pick tp/pp to match the topology.
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as gmesh
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.models.pretrain import (
    init_gpt_pretrain_params,
    make_gpt_pretrain_step,
)
from apex_tpu.optimizers import FusedAdam


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--schedule", type=str, default="1f1b",
                   choices=("gpipe", "1f1b", "interleaved_1f1b",
                            "async_1f1b"))
    p.add_argument("--model-chunks", type=int, default=1,
                   help="model chunks per stage (>1 selects the "
                        "interleaved schedule)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--micro-batches", type=int, default=2)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--bf16", action="store_true",
                   help="bf16 compute (O5-style: fp32 master in the "
                        "fused optimizer state)")
    p.add_argument("--save", type=str, default="",
                   help="orbax checkpoint dir; if it already holds a "
                        "checkpoint, training resumes from it exactly")
    return p.parse_args(argv)


def synthetic_batch(rng, n, seq, vocab):
    """Deterministic token stream (the reference's minimal tests build
    synthetic text in-process the same way, run_gpt_minimal_test.py)."""
    toks = rng.randint(0, vocab, (n, seq + 1)).astype(np.int32)
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


def main(argv=None):
    args = parse_args(argv)
    gmesh.initialize_mesh(model=args.tp, pipe=args.pp)
    sizes = gmesh.axis_sizes()
    print(f"mesh: dp={sizes['batch']} tp={args.tp} pp={args.pp} "
          f"devices={len(jax.devices())}")

    cfg = GPTConfig(
        vocab_size=args.vocab, max_seq_len=args.seq,
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, attention_backend="flash",
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
    )
    params = init_gpt_pretrain_params(cfg, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=args.lr, weight_decay=0.01)
    build = make_gpt_pretrain_step(
        cfg, opt, schedule=args.schedule,
        num_microbatches=args.micro_batches,
        num_model_chunks=args.model_chunks)
    try:
        step, state = build(params)

        # checkpoint/resume: the fused optimizer's state_dict (flat
        # master, slots, step count) round-trips through orbax as plain
        # pytrees — the bitwise-resume recipe pinned by
        # tests/test_checkpoint.py. The master buffer IS the params, so
        # one state_dict covers both.
        start = 0
        ckptr = ckpt_path = None
        if args.save:
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            ckpt_path = os.path.join(os.path.abspath(args.save), "latest")
            if os.path.isdir(ckpt_path):
                target = {"opt": opt.state_dict(state),
                          "step": jnp.zeros((), jnp.int32)}
                restored = ckptr.restore(ckpt_path, target)
                state = opt.load_state_dict(state, restored["opt"])
                start = int(restored["step"])
                print(f"resumed from {ckpt_path} at step {start}")

        rng = np.random.RandomState(0)
        loss = None
        t0 = time.perf_counter()
        for i in range(start, args.steps):
            inputs, labels = synthetic_batch(
                rng, args.global_batch, args.seq, args.vocab)
            state, loss = step(state, inputs, labels)
            if i % 5 == 0 or i == args.steps - 1:
                jax.block_until_ready(loss)
                dt = time.perf_counter() - t0
                tok_s = args.global_batch * args.seq * (i - start + 1) / dt
                bubble = getattr(step, "last_bubble_fraction", None)
                extra = (f"  bubble {bubble:.3f}"
                         if bubble is not None else "")
                print(f"step {i:4d}  loss {float(np.ravel(loss)[0]):.4f}"
                      f"  {tok_s:,.0f} tok/s{extra}")
        if ckptr is not None:
            ckptr.save(ckpt_path,
                       {"opt": opt.state_dict(state),
                        "step": jnp.asarray(args.steps, jnp.int32)},
                       force=True)
            ckptr.wait_until_finished()
            print(f"saved checkpoint to {ckpt_path}")
    finally:
        gmesh.destroy_mesh()
    return float(np.ravel(loss)[0]) if loss is not None else float("nan")


if __name__ == "__main__":
    main()

"""DCGAN amp example — multiple models / optimizers / losses
(ref: examples/dcgan/main_amp.py, 274 LoC: amp.initialize with two
models+optimizers and num_losses=3, separate scale_loss per loss).

The TPU point of this example is the multi-scaler choreography: G and D
keep independent loss-scaler states (``num_losses=2``) and each
backward uses its own scale, exactly the reference's
``amp.scale_loss(errD, optimizerD, loss_id=0/1)`` pattern, expressed
functionally.

Run (CPU smoke):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python main_amp.py --steps 5 --image-size 16
"""

from __future__ import annotations

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


class Generator(nn.Module):
    feat: int = 16
    channels: int = 3

    @nn.compact
    def __call__(self, z):        # z (b, nz) -> (b, s, s, c)
        b = z.shape[0]
        x = nn.Dense(4 * 4 * self.feat * 2)(z)
        x = x.reshape(b, 4, 4, self.feat * 2)
        x = nn.relu(nn.GroupNorm(num_groups=4)(x))
        x = nn.ConvTranspose(self.feat, (4, 4), strides=(2, 2))(x)
        x = nn.relu(nn.GroupNorm(num_groups=4)(x))
        x = nn.ConvTranspose(self.channels, (4, 4), strides=(2, 2))(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    feat: int = 16

    @nn.compact
    def __call__(self, x):        # (b, s, s, c) -> (b,)
        x = nn.Conv(self.feat, (4, 4), strides=(2, 2))(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(self.feat * 2, (4, 4), strides=(2, 2))(x)
        x = nn.leaky_relu(x, 0.2)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1)(x)[:, 0]


def bce_logits(logits, target):
    # stable BCE-with-logits (the reference uses BCELoss on sigmoid)
    return jnp.mean(jnp.maximum(logits, 0) - logits * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--nz", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--opt-level", default="O1")
    args = ap.parse_args(argv)

    rng = np.random.RandomState(0)
    real = jnp.asarray(
        rng.rand(args.batch_size, args.image_size, args.image_size, 3) * 2
        - 1, jnp.float32)

    netG, netD = Generator(), Discriminator()
    z0 = jnp.asarray(rng.randn(args.batch_size, args.nz), jnp.float32)
    pG = netG.init(jax.random.PRNGKey(0), z0)
    pD = netD.init(jax.random.PRNGKey(1), real)

    optG = FusedAdam(lr=args.lr, betas=(0.5, 0.999), impl="xla")
    optD = FusedAdam(lr=args.lr, betas=(0.5, 0.999), impl="xla")
    # two models, two optimizers, two loss scalers — the functional form
    # of ref main_amp.py's amp.initialize([netD, netG],
    # [optimizerD, optimizerG], num_losses=3): each (model, optimizer)
    # pair is initialized against its own params, and the D/G losses
    # carry independent scaler states
    pD, sD, ampD = amp.initialize(pD, optD, opt_level=args.opt_level)
    pG, sG, ampG = amp.initialize(pG, optG, opt_level=args.opt_level)
    scaler = amp.make_scaler(ampD.properties)
    ssD, ssG = ampD.scalers[0], ampG.scalers[0]

    @jax.jit
    def stepD(pD, pG, sD, ssD, z, key):
        def lossD(p):
            fake = netG.apply(pG, z)
            out_real = netD.apply(p, real)
            out_fake = netD.apply(p, fake)
            return bce_logits(out_real, 1.0) + bce_logits(out_fake, 0.0)
        sloss, g = jax.value_and_grad(
            lambda p: scaler.scale_loss(lossD(p), ssD))(pD)
        pD2, sD = optD.step(sD, g, grad_scale=ssD.loss_scale,
                            skip_if_nonfinite=True)
        return pD2, sD, scaler.update(ssD, sD.found_inf), sloss

    @jax.jit
    def stepG(pG, pD, sG, ssG, z):
        def lossG(p):
            fake = netG.apply(p, z)
            return bce_logits(netD.apply(pD, fake), 1.0)
        sloss, g = jax.value_and_grad(
            lambda p: scaler.scale_loss(lossG(p), ssG))(pG)
        pG2, sG = optG.step(sG, g, grad_scale=ssG.loss_scale,
                            skip_if_nonfinite=True)
        return pG2, sG, scaler.update(ssG, sG.found_inf), sloss

    key = jax.random.PRNGKey(2)
    for i in range(args.steps):
        key, kz = jax.random.split(key)
        z = jax.random.normal(kz, (args.batch_size, args.nz))
        pD, sD, ssD, lD = stepD(pD, pG, sD, ssD, z, kz)
        pG, sG, ssG, lG = stepG(pG, pD, sG, ssG, z)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  lossD {float(lD)/float(ssD.loss_scale):.4f}"
                  f"  lossG {float(lG)/float(ssG.loss_scale):.4f}")
    return (float(lD) / float(ssD.loss_scale),
            float(lG) / float(ssG.loss_scale))


if __name__ == "__main__":
    main()

"""Long-context training with context parallelism (ring attention).

The reference tops out at Megatron sequence parallelism (activations
seq-sharded between TP matmuls) and a 512-token fmha; this example shows
the beyond-reference long-context path: the sequence dim sharded over
the mesh's context axis, causal attention computed exactly by
``ring_attention_sharded`` (zig-zag balanced KV rotation via ppermute,
recompute backward, O(s_local) per-device memory), or by Ulysses
all-to-all when heads divide the cp size.

A tiny copy-task transformer trains end to end with the sequence split
across 4 simulated devices; per-device attention never materializes more
than its local shard's scores:

    python examples/long_context/train_long_context.py \
        --seq 512 --cp 4 --steps 30 --attn ring
"""

import argparse
import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.context_parallel import (
    ring_attention_sharded,
    ulysses_attention_sharded,
)


def init_params(key, vocab, hidden, heads, layers):
    ks = jax.random.split(key, 2 * layers + 2)
    params = {
        "embed": jax.random.normal(ks[0], (vocab, hidden)) * 0.02,
        "layers": [],
    }
    for i in range(layers):
        params["layers"].append({
            "qkv": jax.random.normal(ks[2 * i + 1],
                                     (hidden, 3 * hidden)) * 0.02,
            "out": jax.random.normal(ks[2 * i + 2], (hidden, hidden)) * 0.02,
            "norm": jnp.ones((hidden,)),
        })
    return params


def forward(params, tokens, mesh, heads, attn):
    """(batch, S) tokens -> (batch, S, vocab) logits; attention runs
    sequence-sharded over the context axis."""
    h = params["embed"][tokens]                      # (b, S, hidden)
    hidden = h.shape[-1]
    hd = hidden // heads
    for lp in params["layers"]:
        x = FusedRMSNorm(hidden).apply(
            {"params": {"scale": lp["norm"]}}, h)
        qkv = x @ lp["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # (b, S, hidden) -> (b, heads, S, hd)
        split = lambda t: t.reshape(  # noqa: E731
            t.shape[0], t.shape[1], heads, hd).transpose(0, 2, 1, 3)
        if attn == "ring":
            o = ring_attention_sharded(
                split(q), split(k), split(v), mesh, causal=True,
                zigzag=True, batch_axis=None)
        else:
            o = ulysses_attention_sharded(
                split(q), split(k), split(v), mesh, causal=True,
                batch_axis=None, impl=None)
        o = o.transpose(0, 2, 1, 3).reshape(h.shape)
        h = h + o @ lp["out"]
    return h @ params["embed"].T


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--cp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--attn", choices=("ring", "ulysses"), default="ring")
    args = ap.parse_args(argv)

    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=args.cp)

    # copy task: predict token shifted by one (learnable with attention)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(
        rng.randint(2, args.vocab, (args.batch_size, args.seq + 1)),
        jnp.int32)
    x, y = toks[:, :-1], toks[:, 1:]

    params = init_params(jax.random.PRNGKey(0), args.vocab, args.hidden,
                         args.heads, args.layers)
    opt = FusedAdam(lr=args.lr, impl="xla")
    state = opt.init(params)

    @jax.jit
    def step(state, x, y):
        def loss_fn(p):
            logits = forward(p, x, mesh, args.heads, args.attn)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.mean(
                jnp.take_along_axis(logp, y[..., None], -1))

        p = state.space.unpack(state.master)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        _, state2 = opt.step(state, grads)
        return state2, loss

    loss = None
    for i in range(args.steps):
        state, loss = step(state, x, y)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    ps.destroy_model_parallel()
    return float(loss)


if __name__ == "__main__":
    sys.exit(0 if np.isfinite(main()) else 1)

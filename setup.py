"""Package build (ref: the reference's setup.py, 871 LoC of CUDA
extension wiring — setup.py:247-855).

The TPU build needs none of that: the compute kernels are Pallas
(compiled by XLA at trace time) and the only native artifact is the
host-runtime shared library, which apex_tpu.runtime compiles lazily
with g++ on first use and caches under apex_tpu/_build/. ``--cpp_ext``
is accepted for reference-CLI parity and pre-builds that library
eagerly."""

import sys

from setuptools import find_packages, setup

if "--cpp_ext" in sys.argv:
    sys.argv.remove("--cpp_ext")
    sys.path.insert(0, ".")
    from apex_tpu.runtime import native_available

    if not native_available():
        raise RuntimeError("failed to build the host runtime (needs g++)")
    print("apex_tpu host runtime built")

setup(
    name="apex_tpu",
    version="0.1.0",
    description=(
        "TPU-native training acceleration: mixed precision, fused "
        "kernels, and a full mesh-parallelism stack (JAX/XLA/Pallas)"
    ),
    packages=find_packages(include=["apex_tpu", "apex_tpu.*"]),
    # ship the source and any pre-built library; read-only installs
    # fall back to compiling into ~/.cache/apex_tpu (runtime._build_dir)
    package_data={"apex_tpu": ["csrc/*.cpp", "_build/*.so"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "numpy"],
    extras_require={"test": ["pytest", "optax", "orbax-checkpoint", "torch"]},
)

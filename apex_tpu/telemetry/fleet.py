"""Fleet telemetry: cross-host snapshot aggregation + straggler
detection.

PR 4's telemetry spine (registry, StepTimeline, cost model) is
process-local: every host holds its own registry, and when a host dies
its snapshot dies with it. But since the distributed guard (PR 3) the
interesting failures are fleet-level — divergence repair, quorum
checkpoints, and preemption all happen ACROSS hosts. The reference's
distributed wrapper only ever offered per-rank NVTX ranges (ref
apex/parallel/distributed.py:360-561); the production-stack answer
(TorchTitan, PAPERS.md) is one fleet view. This module is that view:

- :func:`gather_snapshots` collects every host's
  ``telemetry.snapshot_detail()`` over the SAME 4-method
  :class:`~apex_tpu.resilience.guard.Collective` abstraction the guard
  rides (ProcessCollective on a real ``jax.distributed`` cluster, the
  threaded LocalCollective sim in tests and ``bench.py fleet``,
  NullCollective for one host). Snapshots are variable-length JSON, so
  the gather is two fixed-shape collectives: lengths first, then the
  right-padded utf-8 payloads.
- :func:`merge_snapshots` folds the per-host snapshots into ONE fleet
  snapshot: counters summed across hosts, gauges kept per-host plus
  min/max/mean, histograms bucket-merged (same fixed ``le`` grid on
  every host, so cumulative counts add), and the per-host step-phase
  summaries side by side — a dead host's phase breakdown next to its
  survivors'.
- :class:`FleetAggregator` derives **straggler detection** on top: a
  per-host EWMA of each watched phase's mean step time (``step`` and
  ``data_wait`` by default), the slowest/fastest spread, and a
  ``fleet_straggler`` event + gauges whenever one host's EWMA exceeds
  a configurable multiple of the fleet median — the host that is
  quietly gating every collective gets named while it is still alive.
- :func:`estimate_clock_offsets` measures per-host clock skew over
  the collective itself (barrier round-trip midpoints: each barrier
  release is one shared fleet instant, so gathered midpoints read
  every host's clock at the same moment), and
  :func:`export_fleet_trace` merges every host's ``export_trace()``
  onto ONE perfetto timeline — one process track per host, every
  host's ``ts`` shifted to the shared barrier instant (so cross-host
  causality reads correctly), with ``fleet_straggler`` /
  ``collective_slow`` events from the flight ring annotated as
  instants.

Gather hardening: host snapshots ride two fixed-shape gathers, so one
host with a pathologically fat registry would make EVERY host allocate
its padded buffer. ``gather_snapshots`` caps the payload
(``max_bytes``, default 4 MiB) and replaces an oversized snapshot with
a structured stub + a ``fleet_snapshot_truncated`` event — no silent
caps (the no-silent-caps discipline of docs/observability.md).

Every collective here must be called by ALL replicas (the Collective
contract); single-replica collectives short-circuit to the local
snapshot so the same loop runs unchanged at both scales.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# phases watched for stragglers by default: the fused-step dispatch and
# the input-pipeline wait — the two that gate a lockstep fleet
DEFAULT_STRAGGLER_PHASES: Tuple[str, ...] = ("step", "data_wait")

# one host's snapshot payload past this rides as a stub + a
# fleet_snapshot_truncated event — every host allocates the padded
# gather buffer at the fleet MAX, so one fat registry taxes them all
DEFAULT_SNAPSHOT_CAP_BYTES = 4 << 20

# flight-ring events annotated as perfetto instants on merged traces
TRACE_INSTANT_EVENTS = ("fleet_straggler", "collective_slow",
                        "collective_payload_corrupt")


def local_snapshot() -> Dict[str, Any]:
    """This process's ``telemetry.snapshot_detail()`` (one JSON-able
    dict: registry + step-timeline summary + mfu-or-null)."""
    from apex_tpu import telemetry

    return telemetry.snapshot_detail()


def _gather_blobs(collective, data: bytes) -> List[bytes]:
    """Every replica's variable-length payload, on every replica: two
    fixed-shape gathers (the Collective contract wants identical
    shapes everywhere), lengths first, then the payloads right-padded
    to the fleet max."""
    lens = collective.all_gather(np.asarray([len(data)], np.int64))
    max_len = max(int(lens.max()), 1)
    buf = np.zeros((max_len,), np.uint8)
    buf[: len(data)] = np.frombuffer(data, np.uint8)
    gathered = collective.all_gather(buf)
    out = []
    for r in range(collective.n_replicas):
        n = int(np.asarray(lens)[r, 0])
        out.append(bytes(bytearray(np.asarray(gathered)[r, :n])))
    return out


def _truncation_stub(n_bytes: int, max_bytes: int,
                     replica_id: int) -> Dict[str, Any]:
    """The structured stand-in an oversized snapshot gathers as: still
    a valid snapshot_detail shape (empty registry), explicitly marked
    so the merge and its consumers see the cap, not a quiet gap."""
    return {
        "truncated": True,
        "original_bytes": int(n_bytes),
        "max_bytes": int(max_bytes),
        "replica_id": int(replica_id),
        "registry": {"counters": {}, "gauges": {}, "histograms": {}},
        "step_timeline": None,
        "mfu": None,
    }


def gather_snapshots(collective,
                     snapshot: Optional[Dict[str, Any]] = None, *,
                     max_bytes: Optional[int] = DEFAULT_SNAPSHOT_CAP_BYTES,
                     registry=None) -> List[Dict[str, Any]]:
    """Every host's telemetry snapshot, by replica id, on EVERY host.

    ``snapshot`` overrides the local ``telemetry.snapshot_detail()``
    (the LocalCollective sim passes one per simulated host — the
    process-global registry can't be three hosts at once). A collective
    op: all replicas must call it; with no collective (or one replica)
    it degrades to ``[snapshot]`` with zero collectives issued.

    A snapshot past ``max_bytes`` (None disables the cap) is replaced
    by a structured stub and announced with ONE
    ``fleet_snapshot_truncated`` event + counter on the oversized host
    — the fleet still gathers (the other hosts' views are intact), and
    nothing is silently dropped.
    """
    if snapshot is None:
        snapshot = local_snapshot()
    if collective is None or collective.n_replicas <= 1:
        return [dict(snapshot)]
    data = json.dumps(snapshot, sort_keys=True).encode("utf-8")
    if max_bytes is not None and len(data) > max_bytes:
        from apex_tpu.telemetry import metrics as _metrics

        reg = registry if registry is not None else _metrics.registry()
        rid = getattr(collective, "replica_id", 0)
        reg.counter("fleet_snapshot_truncated_total",
                    "snapshots replaced by a stub at the gather cap"
                    ).inc()
        reg.event("fleet_snapshot_truncated",
                  original_bytes=len(data), max_bytes=int(max_bytes),
                  replica=int(rid))
        data = json.dumps(
            _truncation_stub(len(data), max_bytes, rid),
            sort_keys=True).encode("utf-8")
    return [json.loads(b.decode("utf-8"))
            for b in _gather_blobs(collective, data)]


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------


def _merge_histograms(series: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Bucket-merge one histogram series across hosts. Buckets are the
    fixed ``le`` grids from metrics.Histogram — cumulative counts at
    the same upper bound simply add; a bound only some hosts carry
    (different bucket config) sums over the hosts that have it."""
    buckets: Dict[str, float] = {}
    total_sum = 0.0
    total_count = 0
    for s in series:
        for le, c in (s.get("buckets") or {}).items():
            buckets[le] = buckets.get(le, 0) + c
        total_sum += s.get("sum", 0.0)
        total_count += s.get("count", 0)

    def _le_key(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    return {"buckets": {le: buckets[le]
                        for le in sorted(buckets, key=_le_key)},
            "sum": total_sum, "count": total_count}


def merge_snapshots(per_host: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-host ``snapshot_detail`` dicts into one fleet snapshot.

    Counters sum (a fleet-total event count is meaningful); gauges are
    last-write-wins per host so summing would lie — they stay per-host
    with min/max/mean derived; histograms bucket-merge; the step-phase
    summaries (and mfu) sit side by side keyed by replica id. Hosts
    whose timeline was disabled contribute ``None`` — the merge never
    demands telemetry a host didn't collect.
    """
    counters: Dict[str, float] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    hist_series: Dict[str, List[Dict[str, Any]]] = {}
    timelines: Dict[str, Any] = {}
    mfu: Dict[str, Any] = {}
    info: Dict[str, Any] = {}
    goodputs: Dict[str, Dict[str, Any]] = {}
    for r, snap in enumerate(per_host):
        reg = snap.get("registry") or {}
        for name, v in (reg.get("counters") or {}).items():
            counters[name] = counters.get(name, 0.0) + v
        for name, v in (reg.get("gauges") or {}).items():
            gauges.setdefault(name, {"per_host": {}})["per_host"][
                str(r)] = v
        for name, v in (reg.get("histograms") or {}).items():
            hist_series.setdefault(name, []).append(v)
        if reg.get("info"):
            info[str(r)] = reg["info"]
        timelines[str(r)] = snap.get("step_timeline")
        mfu[str(r)] = snap.get("mfu")
        gp = snap.get("goodput")
        if isinstance(gp, dict) and gp.get("enabled"):
            goodputs[str(r)] = gp
    for g in gauges.values():
        vals = list(g["per_host"].values())
        g["min"] = min(vals)
        g["max"] = max(vals)
        g["mean"] = sum(vals) / len(vals)
    return {
        "n_hosts": len(per_host),
        "counters": counters,
        "gauges": gauges,
        "histograms": {name: _merge_histograms(s)
                       for name, s in hist_series.items()},
        "step_timelines": timelines,
        "mfu": mfu,
        **({"info": info} if info else {}),
        **({"goodput": _merge_goodput(goodputs)} if goodputs else {}),
    }


def _merge_goodput(per_host: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """The fleet-merged run ledger: per-host goodput with min/max/mean
    fraction, cause-bucket seconds summed fleet-wide, and the
    straggler seconds each ledger attributed. Hosts whose ledger was
    disarmed simply drop out (the merge never demands telemetry a host
    didn't collect)."""
    fractions = [float(g.get("goodput_fraction") or 0.0)
                 for g in per_host.values()]
    seconds_total: Dict[str, float] = {}
    tokens = 0.0
    for g in per_host.values():
        tokens += float(g.get("tokens_trained_total") or 0.0)
        for c, v in (g.get("seconds") or {}).items():
            seconds_total[c] = round(
                seconds_total.get(c, 0.0) + float(v), 6)
    return {
        "n_hosts": len(per_host),
        "per_host": {
            r: {"goodput_fraction": g.get("goodput_fraction"),
                "wall_seconds": g.get("wall_seconds"),
                "straggler_wait_seconds":
                    (g.get("seconds") or {}).get("straggler_wait", 0.0),
                "restarts": g.get("restarts")}
            for r, g in per_host.items()},
        "fraction_min": min(fractions),
        "fraction_max": max(fractions),
        "fraction_mean": round(sum(fractions) / len(fractions), 6),
        "seconds_total": seconds_total,
        "straggler_wait_seconds_total": seconds_total.get(
            "straggler_wait", 0.0),
        "tokens_trained_total": tokens,
    }


# ---------------------------------------------------------------------------
# Straggler detection
# ---------------------------------------------------------------------------


def phase_means_by_host(per_host: Sequence[Dict[str, Any]],
                        phase: str) -> Dict[int, float]:
    """``{replica_id: mean_ms}`` of one timeline phase, over the hosts
    that actually timed it (disabled timelines drop out silently)."""
    out: Dict[int, float] = {}
    for r, snap in enumerate(per_host):
        tl = snap.get("step_timeline")
        if not tl:
            continue
        p = (tl.get("phases") or {}).get(phase)
        if p and p.get("count"):
            out[r] = float(p["mean_ms"])
    return out


class FleetAggregator:
    """Gather + merge + straggler detection, one call per aggregation
    boundary (``aggregate()``), over a guard-style collective.

    Per watched phase the aggregator keeps a per-host EWMA of the
    phase's windowed mean (``ewma_alpha`` — one noisy window doesn't
    flag a host; a persistently slow one converges fast). A host whose
    EWMA exceeds ``straggler_factor`` x the fleet MEDIAN EWMA is a
    straggler: reported in the returned fleet snapshot's
    ``straggler`` section, published as gauges
    (``fleet_phase_ms{phase=,host=}``, ``fleet_straggler_spread``
    slowest/fastest ratio, ``fleet_stragglers`` count) and as one
    ``fleet_straggler`` event per flagged (host, phase). The median —
    not the mean — anchors the test so one dying host cannot drag the
    reference toward itself.
    """

    def __init__(self, collective=None, *, straggler_factor: float = 2.0,
                 ewma_alpha: float = 0.25,
                 phases: Sequence[str] = DEFAULT_STRAGGLER_PHASES,
                 registry=None):
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {straggler_factor}")
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.collective = collective
        self.straggler_factor = float(straggler_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.phases = tuple(phases)
        self._registry = registry
        self._ewma: Dict[Tuple[str, int], float] = {}
        self.last_fleet: Optional[Dict[str, Any]] = None

    # -- ewma --------------------------------------------------------------

    def _ewma_update(self, phase: str, host: int, value: float) -> float:
        key = (phase, host)
        prev = self._ewma.get(key)
        cur = (value if prev is None
               else self.ewma_alpha * value
               + (1.0 - self.ewma_alpha) * prev)
        self._ewma[key] = cur
        return cur

    def straggler_report(self, per_host: Sequence[Dict[str, Any]]
                         ) -> Dict[str, Any]:
        """Pure derivation (plus EWMA state update): per-phase EWMAs,
        median, slowest/fastest spread, and the flagged hosts."""
        phases: Dict[str, Any] = {}
        n_stragglers = 0
        for phase in self.phases:
            means = phase_means_by_host(per_host, phase)
            ewmas = {h: self._ewma_update(phase, h, v)
                     for h, v in sorted(means.items())}
            entry: Dict[str, Any] = {
                "per_host_ewma_ms": {str(h): round(v, 4)
                                     for h, v in ewmas.items()},
            }
            if ewmas:
                vals = list(ewmas.values())
                median = float(np.median(vals))
                lo, hi = min(vals), max(vals)
                entry["median_ms"] = round(median, 4)
                entry["spread"] = round(hi / lo, 4) if lo > 0 else None
                flagged = []
                if median > 0 and len(ewmas) > 1:
                    for h, v in ewmas.items():
                        if v > self.straggler_factor * median:
                            flagged.append({"host": str(h),
                                            "ewma_ms": round(v, 4),
                                            "ratio_to_median":
                                                round(v / median, 4)})
                entry["stragglers"] = flagged
                n_stragglers += len(flagged)
            phases[phase] = entry
        return {"factor": self.straggler_factor,
                "ewma_alpha": self.ewma_alpha,
                "n_stragglers": n_stragglers,
                "phases": phases}

    # -- publish -----------------------------------------------------------

    def _publish(self, straggler: Dict[str, Any]) -> None:
        from apex_tpu.telemetry import metrics as _metrics

        reg = (self._registry if self._registry is not None
               else _metrics.registry())
        phase_g = reg.gauge("fleet_phase_ms",
                            "per-host EWMA of a watched phase's mean "
                            "duration over the fleet")
        spread_g = reg.gauge("fleet_straggler_spread",
                             "slowest/fastest per-host EWMA ratio of a "
                             "watched phase")
        count_g = reg.gauge("fleet_stragglers",
                            "hosts currently past the straggler "
                            "threshold, all watched phases")
        for phase, entry in straggler["phases"].items():
            for h, v in entry.get("per_host_ewma_ms", {}).items():
                phase_g.set(v, phase=phase, host=h)
            spread_g.set(entry.get("spread") or 1.0, phase=phase)
            for s in entry.get("stragglers", ()):
                reg.event("fleet_straggler", phase=phase, host=s["host"],
                          ewma_ms=s["ewma_ms"],
                          ratio_to_median=s["ratio_to_median"],
                          factor=self.straggler_factor)
        count_g.set(straggler["n_stragglers"])

    # -- the boundary ------------------------------------------------------

    def aggregate(self, snapshot: Optional[Dict[str, Any]] = None, *,
                  publish: bool = True) -> Dict[str, Any]:
        """One aggregation boundary: gather every host's snapshot,
        merge, update straggler EWMAs, publish the fleet gauges/events
        into the LOCAL registry (every host derives the identical
        report from the identical gather, so any host can alert), and
        return the fleet snapshot. Collective: all replicas call it."""
        t0 = time.perf_counter()
        per_host = gather_snapshots(self.collective, snapshot)
        fleet = merge_snapshots(per_host)
        fleet["straggler"] = self.straggler_report(per_host)
        fleet["aggregation_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 4)
        if publish:
            self._publish(fleet["straggler"])
        self._feed_goodput(fleet["straggler"])
        self.last_fleet = fleet
        return fleet

    @staticmethod
    def _feed_goodput(straggler: Dict[str, Any]) -> None:
        """Attribute the straggler spread to the armed goodput ledger:
        one (slowest EWMA − median) sample per watched phase per
        aggregate call — an approximation of the seconds the median
        host spends waiting on the slowest one, documented as such in
        docs/observability.md. No-op when the ledger is disarmed."""
        from apex_tpu.telemetry import goodput as _goodput

        led = _goodput.get_ledger()
        if led is None:
            return
        wait_s = 0.0
        for entry in (straggler.get("phases") or {}).values():
            ew = entry.get("per_host_ewma_ms") or {}
            med = entry.get("median_ms")
            if ew and med is not None:
                wait_s += max(0.0, max(ew.values()) - med) / 1e3
        if wait_s > 0.0:
            led.note_straggler_wait(wait_s)


# ---------------------------------------------------------------------------
# Clock offsets + the fleet-merged trace
# ---------------------------------------------------------------------------


def estimate_clock_offsets(collective, *, rounds: int = 5,
                           clock=time.perf_counter,
                           registry=None) -> Dict[str, Any]:
    """Per-host clock offsets measured over the collective itself.

    Each round every host brackets one ``barrier()`` with its local
    clock and takes the midpoint: the barrier RELEASE is one shared
    fleet instant, so the midpoints are every host's clock read at
    (approximately) the same moment, and arrival skew cancels to first
    order. The per-round midpoints are gathered (one fixed-shape
    float64 collective) and host ``r``'s offset vs host 0 is the
    median over rounds of ``mid[r] - mid[0]`` — the median absorbs the
    occasional round where one host's barrier wake-up was late.

    Returns (and publishes as ``fleet_clock_offset_ms{host=}`` /
    ``fleet_clock_offset_spread_ms`` gauges, and deposits into the
    armed comms tracer)::

        {"n_hosts", "rounds", "anchor", "anchor_wall",
         "offsets_ms": {host: ms vs host 0}, "local_offset_ms",
         "spread_ms", "rtt_ms"}

    ``anchor`` is THIS host's local clock at the (median) shared
    instant — what :func:`export_fleet_trace` shifts this host's spans
    against; ``anchor_wall`` is the matching ``time.time()`` reading
    (dates the flight ring's wall-clock events onto the same axis).
    ``rtt_ms`` (the median barrier round-trip) bounds the estimate's
    uncertainty. A collective op: all replicas must call it; a single
    replica short-circuits with zero collectives issued.
    """
    n = getattr(collective, "n_replicas", 1) if collective else 1
    if collective is None or n <= 1:
        return {"n_hosts": 1, "rounds": 0, "anchor": clock(),
                "anchor_wall": time.time(), "offsets_ms": {"0": 0.0},
                "local_offset_ms": 0.0, "spread_ms": 0.0, "rtt_ms": 0.0}
    collective.barrier()          # align arrival before measuring
    mids, rtts = [], []
    for _ in range(int(rounds)):
        t0 = clock()
        collective.barrier()
        t1 = clock()
        mids.append((t0 + t1) / 2.0)
        rtts.append(t1 - t0)
    anchor_wall = time.time()
    gathered = np.asarray(collective.all_gather(
        np.asarray(mids, np.float64)))            # (n_hosts, rounds)
    deltas = gathered - gathered[0:1, :]          # vs host 0, per round
    med = np.median(deltas, axis=1)               # (n_hosts,)
    offsets_ms = {str(r): round(float(med[r]) * 1e3, 6)
                  for r in range(n)}
    rid = int(getattr(collective, "replica_id", 0))
    out = {
        "n_hosts": n,
        "rounds": int(rounds),
        "anchor": float(np.median(np.asarray(mids))),
        "anchor_wall": anchor_wall,
        "offsets_ms": offsets_ms,
        "local_offset_ms": offsets_ms[str(rid)],
        "spread_ms": round(float(med.max() - med.min()) * 1e3, 6),
        "rtt_ms": round(float(np.median(np.asarray(rtts))) * 1e3, 6),
    }
    from apex_tpu.telemetry import comms as _comms
    from apex_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.registry()
    g = reg.gauge("fleet_clock_offset_ms",
                  "per-host clock offset vs host 0 (barrier midpoint)")
    for h, v in offsets_ms.items():
        g.set(v, host=h)
    reg.gauge("fleet_clock_offset_spread_ms",
              "max-min per-host clock offset").set(out["spread_ms"])
    tracer = _comms.get_tracer()
    if tracer is not None:
        tracer.note_clock_offsets(out)
    return out


def export_fleet_trace(collective, path: Optional[str] = None, *,
                       timeline=None, offsets: Optional[Dict] = None,
                       rounds: int = 5, clock=time.perf_counter,
                       instant_events=None) -> Dict[str, Any]:
    """Every host's ``export_trace()`` merged onto ONE perfetto
    timeline, offset-corrected — the fleet's "where did the step go"
    view on a single time axis.

    Each host shifts its events so ``ts`` is relative to the shared
    barrier instant from :func:`estimate_clock_offsets` (pass a
    pre-computed ``offsets`` to reuse one estimation across exports —
    it must be THIS host's result, the anchor is host-local), the
    shifted traces ride the same two-fixed-shape-gather transport as
    snapshots, and the merge gives each host its own ``pid`` (replica
    id) with a ``process_name`` metadata track — so ui.perfetto.dev
    shows one process track per host, aligned. Flight-ring events in
    :data:`TRACE_INSTANT_EVENTS` (straggler flags, slow collectives)
    land as ``"ph": "i"`` instants on an ``events`` track, dated via
    the wall-clock anchor. All ``ts`` are normalized so the earliest
    event sits at 0 (``otherData.ts_shift_us`` records the shift).

    A collective op: all replicas must call it (every host gets the
    full merged dict back; ``path`` writes it tmp→rename — pass it on
    one host or give each host its own path). Hosts whose timeline is
    disabled contribute only their metadata track.
    """
    from apex_tpu.telemetry import flight as _flight
    from apex_tpu.telemetry import timeline as _timeline

    tl = timeline if timeline is not None else _timeline.get_timeline()
    if offsets is None:
        offsets = estimate_clock_offsets(collective, rounds=rounds,
                                         clock=clock)
    anchor, anchor_wall = offsets["anchor"], offsets["anchor_wall"]
    events: List[Dict[str, Any]] = []
    tids_used = 0
    if tl is not None and tl.enabled:
        local = tl.export_trace()
        shift_us = (tl.origin - anchor) * 1e6
        for e in local["traceEvents"]:
            e = dict(e)
            e.pop("pid", None)              # the merge owns pids
            if "ts" in e:
                e["ts"] = round(e["ts"] + shift_us, 3)
            events.append(e)
            tids_used = max(tids_used, int(e.get("tid", 0)) + 1)
    src = instant_events
    if src is None:
        rec = _flight.get_recorder()
        src = list(rec.events) if rec is not None else []
    instant_tid = None
    for ev in src:
        if ev.get("event") not in TRACE_INSTANT_EVENTS:
            continue
        wall = ev.get("wall_time")
        if wall is None:
            continue
        if instant_tid is None:
            instant_tid = tids_used
            events.append({"name": "thread_name", "ph": "M",
                           "tid": instant_tid,
                           "args": {"name": "events"}})
        args = {k: v for k, v in ev.items()
                if k not in ("event", "wall_time")
                and isinstance(v, (str, int, float, bool, type(None)))}
        events.append({
            "name": ev["event"], "cat": "events", "ph": "i", "s": "p",
            "ts": round((wall - anchor_wall) * 1e6, 3),
            "tid": instant_tid, "args": args,
        })
    rid = int(getattr(collective, "replica_id", 0)) if collective else 0
    payload = {"host": rid,
               "offset_ms": offsets["offsets_ms"].get(str(rid), 0.0),
               "events": events}
    data = json.dumps(payload, sort_keys=True).encode("utf-8")
    if collective is not None and \
            getattr(collective, "n_replicas", 1) > 1:
        per_host = [json.loads(b.decode("utf-8"))
                    for b in _gather_blobs(collective, data)]
    else:
        per_host = [payload]
    merged: List[Dict[str, Any]] = []
    for r, host in enumerate(per_host):
        for e in host["events"]:
            e = dict(e)
            e["pid"] = r
            merged.append(e)
        merged.append({"name": "process_name", "ph": "M", "pid": r,
                       "args": {"name": f"host {host.get('host', r)}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": r, "args": {"sort_index": r}})
    # perfetto dislikes negative ts: slide everything so min ts == 0
    ts_values = [e["ts"] for e in merged if "ts" in e]
    ts_shift = -min(ts_values) if ts_values and min(ts_values) < 0 \
        else 0.0
    if ts_shift:
        for e in merged:
            if "ts" in e:
                e["ts"] = round(e["ts"] + ts_shift, 3)
    trace = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "n_hosts": len(per_host),
            "clock_offsets_ms": offsets["offsets_ms"],
            "clock_offset_spread_ms": offsets["spread_ms"],
            "clock_offset_rounds": offsets["rounds"],
            "ts_shift_us": round(ts_shift, 3),
        },
    }
    if path is not None:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, path)
    return trace


__all__ = [
    "DEFAULT_SNAPSHOT_CAP_BYTES",
    "DEFAULT_STRAGGLER_PHASES",
    "FleetAggregator",
    "TRACE_INSTANT_EVENTS",
    "estimate_clock_offsets",
    "export_fleet_trace",
    "gather_snapshots",
    "local_snapshot",
    "merge_snapshots",
    "phase_means_by_host",
]

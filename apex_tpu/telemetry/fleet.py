"""Fleet telemetry: cross-host snapshot aggregation + straggler
detection.

PR 4's telemetry spine (registry, StepTimeline, cost model) is
process-local: every host holds its own registry, and when a host dies
its snapshot dies with it. But since the distributed guard (PR 3) the
interesting failures are fleet-level — divergence repair, quorum
checkpoints, and preemption all happen ACROSS hosts. The reference's
distributed wrapper only ever offered per-rank NVTX ranges (ref
apex/parallel/distributed.py:360-561); the production-stack answer
(TorchTitan, PAPERS.md) is one fleet view. This module is that view:

- :func:`gather_snapshots` collects every host's
  ``telemetry.snapshot_detail()`` over the SAME 4-method
  :class:`~apex_tpu.resilience.guard.Collective` abstraction the guard
  rides (ProcessCollective on a real ``jax.distributed`` cluster, the
  threaded LocalCollective sim in tests and ``bench.py fleet``,
  NullCollective for one host). Snapshots are variable-length JSON, so
  the gather is two fixed-shape collectives: lengths first, then the
  right-padded utf-8 payloads.
- :func:`merge_snapshots` folds the per-host snapshots into ONE fleet
  snapshot: counters summed across hosts, gauges kept per-host plus
  min/max/mean, histograms bucket-merged (same fixed ``le`` grid on
  every host, so cumulative counts add), and the per-host step-phase
  summaries side by side — a dead host's phase breakdown next to its
  survivors'.
- :class:`FleetAggregator` derives **straggler detection** on top: a
  per-host EWMA of each watched phase's mean step time (``step`` and
  ``data_wait`` by default), the slowest/fastest spread, and a
  ``fleet_straggler`` event + gauges whenever one host's EWMA exceeds
  a configurable multiple of the fleet median — the host that is
  quietly gating every collective gets named while it is still alive.

Every collective here must be called by ALL replicas (the Collective
contract); single-replica collectives short-circuit to the local
snapshot so the same loop runs unchanged at both scales.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# phases watched for stragglers by default: the fused-step dispatch and
# the input-pipeline wait — the two that gate a lockstep fleet
DEFAULT_STRAGGLER_PHASES: Tuple[str, ...] = ("step", "data_wait")


def local_snapshot() -> Dict[str, Any]:
    """This process's ``telemetry.snapshot_detail()`` (one JSON-able
    dict: registry + step-timeline summary + mfu-or-null)."""
    from apex_tpu import telemetry

    return telemetry.snapshot_detail()


def gather_snapshots(collective,
                     snapshot: Optional[Dict[str, Any]] = None
                     ) -> List[Dict[str, Any]]:
    """Every host's telemetry snapshot, by replica id, on EVERY host.

    ``snapshot`` overrides the local ``telemetry.snapshot_detail()``
    (the LocalCollective sim passes one per simulated host — the
    process-global registry can't be three hosts at once). A collective
    op: all replicas must call it; with no collective (or one replica)
    it degrades to ``[snapshot]`` with zero collectives issued.
    """
    if snapshot is None:
        snapshot = local_snapshot()
    if collective is None or collective.n_replicas <= 1:
        return [dict(snapshot)]
    data = json.dumps(snapshot, sort_keys=True).encode("utf-8")
    # two fixed-shape gathers carry the variable-length payloads:
    # every replica must present the same array shape, so lengths go
    # first and the payloads ride right-padded to the fleet max
    lens = collective.all_gather(np.asarray([len(data)], np.int64))
    max_len = int(lens.max())
    buf = np.zeros((max_len,), np.uint8)
    buf[: len(data)] = np.frombuffer(data, np.uint8)
    gathered = collective.all_gather(buf)
    out = []
    for r in range(collective.n_replicas):
        n = int(np.asarray(lens)[r, 0])
        out.append(json.loads(bytes(bytearray(
            np.asarray(gathered)[r, :n])).decode("utf-8")))
    return out


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------


def _merge_histograms(series: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Bucket-merge one histogram series across hosts. Buckets are the
    fixed ``le`` grids from metrics.Histogram — cumulative counts at
    the same upper bound simply add; a bound only some hosts carry
    (different bucket config) sums over the hosts that have it."""
    buckets: Dict[str, float] = {}
    total_sum = 0.0
    total_count = 0
    for s in series:
        for le, c in (s.get("buckets") or {}).items():
            buckets[le] = buckets.get(le, 0) + c
        total_sum += s.get("sum", 0.0)
        total_count += s.get("count", 0)

    def _le_key(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    return {"buckets": {le: buckets[le]
                        for le in sorted(buckets, key=_le_key)},
            "sum": total_sum, "count": total_count}


def merge_snapshots(per_host: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-host ``snapshot_detail`` dicts into one fleet snapshot.

    Counters sum (a fleet-total event count is meaningful); gauges are
    last-write-wins per host so summing would lie — they stay per-host
    with min/max/mean derived; histograms bucket-merge; the step-phase
    summaries (and mfu) sit side by side keyed by replica id. Hosts
    whose timeline was disabled contribute ``None`` — the merge never
    demands telemetry a host didn't collect.
    """
    counters: Dict[str, float] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    hist_series: Dict[str, List[Dict[str, Any]]] = {}
    timelines: Dict[str, Any] = {}
    mfu: Dict[str, Any] = {}
    info: Dict[str, Any] = {}
    for r, snap in enumerate(per_host):
        reg = snap.get("registry") or {}
        for name, v in (reg.get("counters") or {}).items():
            counters[name] = counters.get(name, 0.0) + v
        for name, v in (reg.get("gauges") or {}).items():
            gauges.setdefault(name, {"per_host": {}})["per_host"][
                str(r)] = v
        for name, v in (reg.get("histograms") or {}).items():
            hist_series.setdefault(name, []).append(v)
        if reg.get("info"):
            info[str(r)] = reg["info"]
        timelines[str(r)] = snap.get("step_timeline")
        mfu[str(r)] = snap.get("mfu")
    for g in gauges.values():
        vals = list(g["per_host"].values())
        g["min"] = min(vals)
        g["max"] = max(vals)
        g["mean"] = sum(vals) / len(vals)
    return {
        "n_hosts": len(per_host),
        "counters": counters,
        "gauges": gauges,
        "histograms": {name: _merge_histograms(s)
                       for name, s in hist_series.items()},
        "step_timelines": timelines,
        "mfu": mfu,
        **({"info": info} if info else {}),
    }


# ---------------------------------------------------------------------------
# Straggler detection
# ---------------------------------------------------------------------------


def phase_means_by_host(per_host: Sequence[Dict[str, Any]],
                        phase: str) -> Dict[int, float]:
    """``{replica_id: mean_ms}`` of one timeline phase, over the hosts
    that actually timed it (disabled timelines drop out silently)."""
    out: Dict[int, float] = {}
    for r, snap in enumerate(per_host):
        tl = snap.get("step_timeline")
        if not tl:
            continue
        p = (tl.get("phases") or {}).get(phase)
        if p and p.get("count"):
            out[r] = float(p["mean_ms"])
    return out


class FleetAggregator:
    """Gather + merge + straggler detection, one call per aggregation
    boundary (``aggregate()``), over a guard-style collective.

    Per watched phase the aggregator keeps a per-host EWMA of the
    phase's windowed mean (``ewma_alpha`` — one noisy window doesn't
    flag a host; a persistently slow one converges fast). A host whose
    EWMA exceeds ``straggler_factor`` x the fleet MEDIAN EWMA is a
    straggler: reported in the returned fleet snapshot's
    ``straggler`` section, published as gauges
    (``fleet_phase_ms{phase=,host=}``, ``fleet_straggler_spread``
    slowest/fastest ratio, ``fleet_stragglers`` count) and as one
    ``fleet_straggler`` event per flagged (host, phase). The median —
    not the mean — anchors the test so one dying host cannot drag the
    reference toward itself.
    """

    def __init__(self, collective=None, *, straggler_factor: float = 2.0,
                 ewma_alpha: float = 0.25,
                 phases: Sequence[str] = DEFAULT_STRAGGLER_PHASES,
                 registry=None):
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {straggler_factor}")
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.collective = collective
        self.straggler_factor = float(straggler_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.phases = tuple(phases)
        self._registry = registry
        self._ewma: Dict[Tuple[str, int], float] = {}
        self.last_fleet: Optional[Dict[str, Any]] = None

    # -- ewma --------------------------------------------------------------

    def _ewma_update(self, phase: str, host: int, value: float) -> float:
        key = (phase, host)
        prev = self._ewma.get(key)
        cur = (value if prev is None
               else self.ewma_alpha * value
               + (1.0 - self.ewma_alpha) * prev)
        self._ewma[key] = cur
        return cur

    def straggler_report(self, per_host: Sequence[Dict[str, Any]]
                         ) -> Dict[str, Any]:
        """Pure derivation (plus EWMA state update): per-phase EWMAs,
        median, slowest/fastest spread, and the flagged hosts."""
        phases: Dict[str, Any] = {}
        n_stragglers = 0
        for phase in self.phases:
            means = phase_means_by_host(per_host, phase)
            ewmas = {h: self._ewma_update(phase, h, v)
                     for h, v in sorted(means.items())}
            entry: Dict[str, Any] = {
                "per_host_ewma_ms": {str(h): round(v, 4)
                                     for h, v in ewmas.items()},
            }
            if ewmas:
                vals = list(ewmas.values())
                median = float(np.median(vals))
                lo, hi = min(vals), max(vals)
                entry["median_ms"] = round(median, 4)
                entry["spread"] = round(hi / lo, 4) if lo > 0 else None
                flagged = []
                if median > 0 and len(ewmas) > 1:
                    for h, v in ewmas.items():
                        if v > self.straggler_factor * median:
                            flagged.append({"host": str(h),
                                            "ewma_ms": round(v, 4),
                                            "ratio_to_median":
                                                round(v / median, 4)})
                entry["stragglers"] = flagged
                n_stragglers += len(flagged)
            phases[phase] = entry
        return {"factor": self.straggler_factor,
                "ewma_alpha": self.ewma_alpha,
                "n_stragglers": n_stragglers,
                "phases": phases}

    # -- publish -----------------------------------------------------------

    def _publish(self, straggler: Dict[str, Any]) -> None:
        from apex_tpu.telemetry import metrics as _metrics

        reg = (self._registry if self._registry is not None
               else _metrics.registry())
        phase_g = reg.gauge("fleet_phase_ms",
                            "per-host EWMA of a watched phase's mean "
                            "duration over the fleet")
        spread_g = reg.gauge("fleet_straggler_spread",
                             "slowest/fastest per-host EWMA ratio of a "
                             "watched phase")
        count_g = reg.gauge("fleet_stragglers",
                            "hosts currently past the straggler "
                            "threshold, all watched phases")
        for phase, entry in straggler["phases"].items():
            for h, v in entry.get("per_host_ewma_ms", {}).items():
                phase_g.set(v, phase=phase, host=h)
            spread_g.set(entry.get("spread") or 1.0, phase=phase)
            for s in entry.get("stragglers", ()):
                reg.event("fleet_straggler", phase=phase, host=s["host"],
                          ewma_ms=s["ewma_ms"],
                          ratio_to_median=s["ratio_to_median"],
                          factor=self.straggler_factor)
        count_g.set(straggler["n_stragglers"])

    # -- the boundary ------------------------------------------------------

    def aggregate(self, snapshot: Optional[Dict[str, Any]] = None, *,
                  publish: bool = True) -> Dict[str, Any]:
        """One aggregation boundary: gather every host's snapshot,
        merge, update straggler EWMAs, publish the fleet gauges/events
        into the LOCAL registry (every host derives the identical
        report from the identical gather, so any host can alert), and
        return the fleet snapshot. Collective: all replicas call it."""
        t0 = time.perf_counter()
        per_host = gather_snapshots(self.collective, snapshot)
        fleet = merge_snapshots(per_host)
        fleet["straggler"] = self.straggler_report(per_host)
        fleet["aggregation_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 4)
        if publish:
            self._publish(fleet["straggler"])
        self.last_fleet = fleet
        return fleet


__all__ = [
    "DEFAULT_STRAGGLER_PHASES",
    "FleetAggregator",
    "gather_snapshots",
    "local_snapshot",
    "merge_snapshots",
    "phase_means_by_host",
]

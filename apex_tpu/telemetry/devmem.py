"""Device-memory ledger: XLA ``memory_analysis()`` normalization and
polled device-memory gauges with explicit-null-with-reason degradation.

The cost model (:mod:`~apex_tpu.telemetry.cost`) accounts for a
compiled program's TRAFFIC (flops, HBM bytes accessed); this module
accounts for its FOOTPRINT and for the device's live occupancy:

- :func:`compiled_memory` normalizes
  ``jit(...).lower(...).compile().memory_analysis()`` — argument /
  output / temp / alias / generated-code bytes plus the peak when the
  backend reports one — into one dict with a fixed key set, the exact
  sibling of ``cost.compiled_cost``. :func:`train_step_memory` is the
  fused-train-step convenience (the step's ``lower`` passthrough:
  nothing executes, nothing is donated).
- :func:`device_memory_stats` reads ``device.memory_stats()`` (bytes
  in use, device-reported peak, limit). Backends without stats (CPU,
  some plugins) degrade to the SAME contract as ``mfu_reason``
  (docs/observability.md): every key present, values null, and
  ``devmem_reason`` naming exactly why — a record never silently
  drops the section.
- :class:`DeviceMemoryLedger` is the polled gauge set: each
  :meth:`~DeviceMemoryLedger.poll` publishes ``devmem_bytes_in_use`` /
  ``devmem_peak_bytes`` / ``devmem_bytes_limit`` and tracks its own
  high-water ``devmem_watermark_bytes`` (the max bytes-in-use THIS
  ledger has seen — survives a backend whose peak counter resets).
  ``telemetry.snapshot_detail()`` folds the gauges into every bench
  record as a ``devmem`` value-or-null-with-reason block, and the
  flight recorder folds :meth:`~DeviceMemoryLedger.summary` into each
  ``flightrec_*.json`` bundle.

Everything is host-side; polling costs one runtime call per poll and
nothing at all between polls.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

# (CompiledMemoryStats attribute, normalized key) — getattr-based so
# older/newer jaxlibs that drop or add fields degrade to null, not raise
_MEM_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("peak_memory_in_bytes", "peak_bytes"),
)


def normalize_memory_analysis(ma: Any) -> Optional[Dict[str, Any]]:
    """A ``CompiledMemoryStats`` (or anything shaped like one) as one
    dict with the fixed key set of ``_MEM_ATTRS`` plus
    ``total_footprint_bytes`` (args + outputs + temps + generated
    code — the compiled program's resident claim when the backend
    reports no peak). None when nothing useful is present."""
    if ma is None:
        return None
    out: Dict[str, Any] = {}
    for attr, key in _MEM_ATTRS:
        v = getattr(ma, attr, None)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    if all(v is None for v in out.values()):
        return None
    out["total_footprint_bytes"] = sum(
        out[k] or 0 for k in ("argument_bytes", "output_bytes",
                              "temp_bytes", "generated_code_bytes"))
    return out


def compiled_memory(compiled) -> Optional[Dict[str, Any]]:
    """``memory_analysis()`` of a compiled computation, normalized;
    None when the backend exposes none — the sibling of
    ``cost.compiled_cost`` (traffic there, footprint here)."""
    try:
        return normalize_memory_analysis(compiled.memory_analysis())
    except Exception:  # noqa: BLE001 — "no memory analysis" raises on some backends
        return None


def jitted_memory(fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """Lower+compile ``fn`` (a ``jax.jit`` result) on the given
    arguments and return its static memory footprint; None on any
    failure — accounting never takes down the loop it describes."""
    from apex_tpu.telemetry import compiled as _compiled

    try:
        with _compiled.label("jitted_memory"):
            return compiled_memory(fn.lower(*args, **kwargs).compile())
    except Exception:  # noqa: BLE001
        return None


def train_step_memory(step, state, flat_grads, scaler_state=None,
                      lr=None) -> Optional[Dict[str, Any]]:
    """Static memory footprint of one fused train step
    (:class:`~apex_tpu.optimizers.train_step.TrainStep`), via the
    step's ``lower`` passthrough — nothing executes, no buffer is
    donated; safe right before the timed run."""
    from apex_tpu.telemetry import compiled as _compiled

    try:
        with _compiled.label("train_step_memory"):
            return compiled_memory(
                step.lower(state, flat_grads, scaler_state,
                           lr=lr).compile())
    except Exception:  # noqa: BLE001
        return None


def publish_memory(mem: Optional[Dict[str, Any]], registry=None,
                   fn: str = "train_step") -> None:
    """Mirror a :func:`compiled_memory` dict into the registry as the
    labeled ``devmem_compiled_bytes{part=,fn=}`` gauge set (absent
    parts publish nothing — the dict is the null-carrying record)."""
    if not mem:
        return
    from apex_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.registry()
    g = reg.gauge("devmem_compiled_bytes",
                  "memory_analysis() of a compiled program, by part")
    for key, v in mem.items():
        if v is None:
            continue
        g.set(v, part=key.replace("_bytes", ""), fn=fn)


# ---------------------------------------------------------------------------
# Live device-memory stats
# ---------------------------------------------------------------------------

# device.memory_stats() key -> normalized key
_STATS_KEYS = (
    ("bytes_in_use", "bytes_in_use"),
    ("peak_bytes_in_use", "peak_bytes_in_use"),
    ("bytes_limit", "bytes_limit"),
    ("largest_alloc_size", "largest_alloc_bytes"),
    ("num_allocs", "num_allocs"),
)


def device_memory_stats(device=None) -> Dict[str, Any]:
    """Live allocator stats of ``device`` (default: the first jax
    device). ALWAYS returns the full key set: values, or nulls with
    ``devmem_reason`` naming exactly why (no device, no stats on this
    backend) — the ``mfu_reason`` contract, applied to memory."""
    out: Dict[str, Any] = {key: None for _, key in _STATS_KEYS}
    out["device_kind"] = None
    out["devmem_reason"] = None
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception as e:  # noqa: BLE001
            out["devmem_reason"] = (f"no jax device available "
                                    f"({type(e).__name__}: {e})")
            return out
    kind = str(getattr(device, "device_kind", "unknown"))
    out["device_kind"] = kind
    try:
        stats = device.memory_stats()
    except Exception as e:  # noqa: BLE001
        out["devmem_reason"] = (f"device.memory_stats() raised "
                                f"{type(e).__name__} on {kind!r}")
        return out
    if not stats:
        out["devmem_reason"] = (f"backend exposes no device "
                                f"memory_stats (device_kind={kind!r})")
        return out
    for src, key in _STATS_KEYS:
        v = stats.get(src)
        if v is not None:
            out[key] = int(v)
    if out["bytes_in_use"] is None:
        out["devmem_reason"] = (f"memory_stats() on {kind!r} reports no "
                                f"bytes_in_use (keys: "
                                f"{sorted(stats)[:8]})")
    return out


class DeviceMemoryLedger:
    """Polled device-memory gauge set with high-water tracking.

    Each :meth:`poll` reads :func:`device_memory_stats` and publishes
    the ``devmem_*`` gauges; on backends without stats it records the
    reason (``info.devmem_reason``) instead — ``snapshot_detail()``
    then carries ``devmem: null`` WITH the reason, never a silently
    missing section. ``watermark_bytes`` is the ledger's own maximum
    of ``bytes_in_use`` across polls (a high-water mark that survives
    allocators whose peak counter resets between runs).
    """

    def __init__(self, device=None, registry=None):
        self.device = device
        self._registry = registry
        self.watermark_bytes: Optional[int] = None
        self.polls = 0
        self.last: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.telemetry import metrics as _metrics

        return _metrics.registry()

    def poll(self) -> Dict[str, Any]:
        """One read -> gauges (or the null reason); returns the stats
        dict either way."""
        st = device_memory_stats(self.device)
        reg = self._reg()
        with self._lock:
            self.polls += 1
            self.last = st
            if st["bytes_in_use"] is None:
                reg.set_info("devmem_reason", st["devmem_reason"])
                return st
            self.watermark_bytes = max(self.watermark_bytes or 0,
                                       st["bytes_in_use"])
            watermark = self.watermark_bytes
        reg.set_info("devmem_reason", None)
        reg.gauge("devmem_bytes_in_use",
                  "device allocator bytes in use at the last poll").set(
            st["bytes_in_use"])
        if st["peak_bytes_in_use"] is not None:
            reg.gauge("devmem_peak_bytes",
                      "device-reported peak bytes in use").set(
                st["peak_bytes_in_use"])
        if st["bytes_limit"] is not None:
            reg.gauge("devmem_bytes_limit",
                      "device allocator capacity").set(st["bytes_limit"])
        reg.gauge("devmem_watermark_bytes",
                  "ledger high-water mark of bytes in use across "
                  "polls").set(watermark)
        return st

    def summary(self) -> Dict[str, Any]:
        """JSON-able ledger state for bundles/dashboards: poll count,
        the watermark, and the last stats read (incl. its reason when
        the backend has none)."""
        with self._lock:
            return {"polls": self.polls,
                    "watermark_bytes": self.watermark_bytes,
                    "last": dict(self.last) if self.last else None}


# ---------------------------------------------------------------------------
# The process-global ledger (what the flight recorder folds into bundles)
# ---------------------------------------------------------------------------

_LEDGER: Optional[DeviceMemoryLedger] = None


def enable(device=None, registry=None) -> DeviceMemoryLedger:
    """Arm the process-global ledger (replacing any previous one)."""
    global _LEDGER
    _LEDGER = DeviceMemoryLedger(device=device, registry=registry)
    return _LEDGER


def disable() -> None:
    global _LEDGER
    _LEDGER = None


def get_ledger() -> Optional[DeviceMemoryLedger]:
    return _LEDGER


__all__ = [
    "DeviceMemoryLedger",
    "compiled_memory",
    "device_memory_stats",
    "disable",
    "enable",
    "get_ledger",
    "jitted_memory",
    "normalize_memory_analysis",
    "publish_memory",
    "train_step_memory",
]

"""MoE observability: per-expert load gauges + the imbalance latch.

The MoE workload plane's telemetry (docs/moe.md). The training step
computes the per-step expert histogram IN the jitted program (the
``moe_expert_load`` intermediate — one (E,) reduction, no host-side
re-derivation) and hands it here through the step's aux:

- :func:`publish_moe_step` lands one step's stats on the registry —
  ``moe_aux_loss`` / ``moe_dropped_tokens`` gauges, a cumulative
  ``moe_dropped_tokens_total`` counter, and one
  ``moe_expert_load{expert=}`` gauge per expert (what the fleet
  aggregator merges per-host and ``tools/telemetry_dump.py``'s ``moe``
  section reads) — then runs the imbalance detector.
- :class:`MoEImbalanceDetector` rides the straggler-detector idiom
  (:class:`~apex_tpu.telemetry.fleet.FleetAggregator`): an EWMA of the
  load histogram's max/mean ratio (1.0 = perfectly balanced), flagged
  when it exceeds ``factor`` after ``min_samples`` warm steps, latched
  once per excursion — a persistently collapsed router raises ONE
  ``moe_imbalance`` event + flight trigger per episode, not one per
  step. The flight bundle's ``extra`` embeds the offending load
  histogram, so the postmortem carries WHICH experts went hot without
  any dashboard round trip.
- :func:`fleet_expert_load` folds a
  :func:`~apex_tpu.telemetry.fleet.merge_snapshots` result's per-host
  ``moe_expert_load`` gauges into fleet-total per-expert counts.

Host-side only; nothing here adds one byte to a jitted program.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from apex_tpu.telemetry import metrics as _metrics


class MoEImbalanceDetector:
    """EWMA latch over the expert-load histogram's max/mean ratio.

    Same knobs and validation as the fleet straggler detector
    (``factor`` > 1, ``ewma_alpha`` in (0, 1]); ``min_samples`` warm
    steps gate the first flag so one noisy init step cannot fire it.
    ``observe(load)`` returns True on the step an episode LATCHES —
    the event/flight bundle fire exactly once per excursion, and the
    latch re-arms when the EWMA recovers below ``factor``.
    """

    def __init__(self, *, factor: float = 2.0, ewma_alpha: float = 0.25,
                 min_samples: int = 5, registry=None):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.factor = float(factor)
        self.ewma_alpha = float(ewma_alpha)
        self.min_samples = int(min_samples)
        self._registry = registry
        self.ewma: Optional[float] = None
        self.samples = 0
        self.latched = False

    def observe(self, load) -> bool:
        """Fold one step's (E,) load histogram; True iff the imbalance
        episode latched ON THIS step (event + flight bundle fired)."""
        load = np.asarray(load, dtype=float)
        if load.size == 0:
            return False
        mean = float(load.mean())
        if mean <= 0.0:
            return False
        ratio = float(load.max()) / mean
        self.ewma = (ratio if self.ewma is None
                     else self.ewma_alpha * ratio
                     + (1.0 - self.ewma_alpha) * self.ewma)
        self.samples += 1
        reg = (self._registry if self._registry is not None
               else _metrics.registry())
        reg.gauge("moe_imbalance_ratio",
                  "EWMA of max/mean expert load (1.0 = balanced)"
                  ).set(self.ewma)
        if self.samples < self.min_samples:
            return False
        if self.ewma <= self.factor:
            self.latched = False            # excursion over: re-arm
            return False
        if self.latched:
            return False
        self.latched = True
        hot = int(np.argmax(load))
        detail = {"ratio": round(ratio, 4),
                  "ewma": round(self.ewma, 4),
                  "factor": self.factor,
                  "hot_expert": hot,
                  "expert_load": [round(float(v), 2) for v in load]}
        reg.event("moe_imbalance", **detail)
        from apex_tpu.telemetry import flight as _flight

        # host-local trigger: every host sees its own shard's routing,
        # so a fleet barrier here would hang single-host drills
        _flight.notify("moe_imbalance", fleet=False, extra=detail)
        return True


_DETECTOR: Optional[MoEImbalanceDetector] = None


def get_detector() -> MoEImbalanceDetector:
    """The process-global imbalance detector (created on first use)."""
    global _DETECTOR
    if _DETECTOR is None:
        _DETECTOR = MoEImbalanceDetector()
    return _DETECTOR


def reset() -> None:
    """Drop the process-global detector (telemetry.reset())."""
    global _DETECTOR
    _DETECTOR = None


def publish_moe_step(aux: Dict[str, Any], *, registry=None,
                     detector: Optional[MoEImbalanceDetector] = None
                     ) -> None:
    """Land one training step's MoE aux stats on the metrics plane and
    run the imbalance latch. ``aux`` is the step's aux dict
    (``aux_loss`` scalar, ``expert_load`` (E,), ``dropped`` scalar —
    what ``make_gpt_pretrain_step``'s MoE loss returns); device arrays
    are fine (this is the one host sync point of MoE observability).
    Unknown keys are ignored, missing ones skipped — a partial aux
    never raises out of the training loop."""
    if not isinstance(aux, dict):
        return
    reg = registry if registry is not None else _metrics.registry()
    if aux.get("aux_loss") is not None:
        reg.gauge("moe_aux_loss",
                  "Switch load-balancing aux loss of the last step"
                  ).set(float(np.asarray(aux["aux_loss"])))
    if aux.get("dropped") is not None:
        dropped = float(np.asarray(aux["dropped"]))
        reg.gauge("moe_dropped_tokens",
                  "token copies dropped to capacity overflow, last step"
                  ).set(dropped)
        if dropped > 0:
            reg.counter("moe_dropped_tokens_total",
                        "cumulative capacity-overflow drops").inc(dropped)
    load = aux.get("expert_load")
    if load is None:
        return
    load = np.asarray(load, dtype=float)
    g = reg.gauge("moe_expert_load",
                  "per-expert (token, choice) assignments of the last "
                  "step")
    for e in range(load.size):
        g.set(float(load[e]), expert=str(e))
    det = detector if detector is not None else get_detector()
    det.observe(load)


def fleet_expert_load(merged: Dict[str, Any]) -> Dict[str, float]:
    """Fleet-total per-expert load from a
    :func:`~apex_tpu.telemetry.fleet.merge_snapshots` result: the
    per-host ``moe_expert_load{expert=}`` gauges summed across hosts
    (each host's gauge is ITS shard's routing counts, so the sum — not
    the per-host mean — is the fleet histogram). ``{}`` when no host
    published MoE gauges."""
    out: Dict[str, float] = {}
    for series, entry in (merged.get("gauges") or {}).items():
        if not series.startswith("moe_expert_load{"):
            continue
        expert = series.split('expert="', 1)[-1].rstrip('"}')
        out[expert] = (out.get(expert, 0.0)
                       + sum(float(v) for v in
                             (entry.get("per_host") or {}).values()))
    return out


__all__ = [
    "MoEImbalanceDetector",
    "fleet_expert_load",
    "get_detector",
    "publish_moe_step",
    "reset",
]

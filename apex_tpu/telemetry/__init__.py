"""Telemetry subsystem: metrics registry + step timeline + cost model
+ compile tracker + device-memory ledger + fleet aggregation + crash
flight recorder.

The observability layer the rest of the runtime reports through
(docs/observability.md). The parts:

- :mod:`~apex_tpu.telemetry.metrics` — process-global registry of
  counters / gauges / fixed-bucket histograms with labeled series,
  ``snapshot()`` as one JSON-able dict, structured events, and
  pluggable sinks (in-memory, JSONL riding the records atomic-claim
  writer, stdout line protocol).
- :mod:`~apex_tpu.telemetry.timeline` — :class:`StepTimeline`: ring-
  buffered per-phase host-loop spans (data wait, H2D, step,
  checkpoint, collective) with Chrome-trace/perfetto export; the one
  spine the legacy ``pipeline_parallel.Timers`` and
  ``profiler.annotate`` now publish into.
- :mod:`~apex_tpu.telemetry.cost` — static FLOPs/bytes from
  ``jit(...).lower().compile().cost_analysis()`` and the MFU / HBM-
  bandwidth estimates bench records carry (``None`` **with a reason**
  when the backend has no cost model or the chip no peak entry).
- :mod:`~apex_tpu.telemetry.compiled` — the compile plane: XLA
  backend-compile timing via the ``jax.monitoring`` bridge
  (``compile_ms``/``compile_count{fn=}`` + ``compile`` spans),
  re-trace detection (``recompile`` events carrying a signature diff),
  and recompile-storm escalation.
- :mod:`~apex_tpu.telemetry.devmem` — the memory plane: normalized
  ``compiled.memory_analysis()`` next to the cost model, plus a polled
  ``devmem_*`` gauge set with watermark tracking that degrades to an
  explicit null WITH ``devmem_reason`` on backends without stats.
- :mod:`~apex_tpu.telemetry.fleet` — cross-host snapshot aggregation
  over the guard's ``Collective`` abstraction (counters summed, gauges
  per-host, histograms bucket-merged, timelines side by side) with
  EWMA straggler detection (``fleet_straggler`` events + gauges),
  barrier-midpoint clock-offset estimation, and
  ``export_fleet_trace`` — every host's timeline merged onto one
  offset-corrected perfetto trace, one process track per host.
- :mod:`~apex_tpu.telemetry.comms` — the comms plane:
  ``instrument(collective)`` traces every ``Collective`` op
  (``collective_ops/bytes/ms``, timeline spans, the measured-vs-
  analytic wire bandwidth ledger, ``collective_slow`` EWMA
  escalation); disabled means the raw collective object, untouched.
- :mod:`~apex_tpu.telemetry.sharding` — compiled executables'
  input/output shardings, mesh axes, and per-device buffer bytes
  normalized to a fixed-key dict (``sharding_reason`` nulls on
  meshless backends) + ``sharding_devices{fn=}`` gauges.
- :mod:`~apex_tpu.telemetry.moe` — the MoE workload plane:
  ``publish_moe_step`` lands each training step's in-jit expert
  histogram as ``moe_expert_load{expert=}`` / ``moe_aux_loss`` /
  ``moe_dropped_tokens`` gauges and runs the ``moe_imbalance`` EWMA
  latch (event + flight bundle embedding the load histogram);
  ``fleet_expert_load`` folds merged snapshots into fleet totals.
- :mod:`~apex_tpu.telemetry.goodput` — the run ledger:
  :class:`GoodputLedger` attributes every second of run wall-clock to
  a cause bucket (productive / compile / checkpoint / data_wait /
  rollback / rework / drain / straggler_wait + published
  ``unattributed`` residual), survives restarts by riding the
  checkpoint ``extra`` payload, and runs the :class:`StepSeries`
  anomaly plane (``loss_spike`` / ``throughput_regression`` flight
  triggers).
- :mod:`~apex_tpu.telemetry.flight` — the crash flight recorder:
  bounded rings of recent events / timeline spans / state digests,
  dumped as a self-contained ``flightrec_*.json`` postmortem bundle on
  watchdog escalation, replica divergence, preemption shutdown, or an
  exception escaping the fused step (keep-last-k pruned).

Who publishes here (the instrumentation pass):

- ``optimizers.train_step.make_train_step(..., telemetry=tl)`` — the
  host-side ``"step"`` phase; zero overhead (same object) when None.
- ``resilience``: watchdog skip/escalation counters, guard divergence
  repairs, checkpoint save/restore latency histograms.
- ``runtime.PrefetchLoader``: queue depth, device_put retries, worker
  deaths, degrade flag (+ ``data_wait`` spans when the global
  timeline is on).
- ``backend_guard``: probe verdicts and cache hits — what
  ``bench.py`` reads instead of an ad-hoc module global.
- ``records.latest_record``: corrupt/unreadable record files skipped.

Everything is host-side; nothing here adds arguments to, or changes
one byte of, a jitted program.
"""

from __future__ import annotations

from typing import Any, Dict

from apex_tpu.telemetry import (
    comms,
    compiled,
    cost,
    devmem,
    fleet,
    flight,
    goodput,
    metrics,
    moe,
    sharding,
    slo,
    timeline,
)
from apex_tpu.telemetry.comms import CommsTracer, InstrumentedCollective
from apex_tpu.telemetry.compiled import CompileTracker
from apex_tpu.telemetry.devmem import DeviceMemoryLedger
from apex_tpu.telemetry.fleet import (
    FleetAggregator,
    gather_snapshots,
    merge_snapshots,
)
from apex_tpu.telemetry.flight import FlightRecorder
from apex_tpu.telemetry.goodput import GoodputLedger, StepSeries
from apex_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    InMemorySink,
    JsonlSink,
    LATENCY_MS_BUCKETS,
    MetricsRegistry,
    PAYLOAD_BYTES_BUCKETS,
    StdoutSink,
    TOKEN_COUNT_BUCKETS,
    registry,
    to_prometheus_text,
)
from apex_tpu.telemetry.slo import (
    SLOMonitor,
    SLOTarget,
    SlidingWindowQuantile,
)
from apex_tpu.telemetry.timeline import (
    PHASES,
    Span,
    StepTimeline,
    disable,
    enable,
    get_timeline,
    global_enabled,
)


def snapshot() -> Dict[str, Any]:
    """The process-global registry's snapshot (one JSON-able dict)."""
    return metrics.registry().snapshot()


def snapshot_detail() -> Dict[str, Any]:
    """The standard ``detail.telemetry`` block bench records carry:
    the registry snapshot, the global timeline's per-phase breakdown,
    and an ``mfu`` field that is a value or an explicit null with a
    reason — never absent, never silently null."""
    reg = metrics.registry()
    snap = reg.snapshot()
    tl = timeline.get_timeline()
    mfu = snap.get("gauges", {}).get("mfu")
    out: Dict[str, Any] = {
        "registry": snap,
        "step_timeline": tl.summary() if tl.enabled else None,
        "mfu": mfu,
    }
    if mfu is None:
        out["mfu_reason"] = (reg.get_info("mfu_reason")
                             or "no step cost published in this process")
    # devmem rides the same value-or-null-WITH-reason contract as mfu:
    # a poll on a stats-bearing backend filled the gauges; anything
    # else carries the reason the section is null
    gauges = snap.get("gauges", {})
    if gauges.get("devmem_bytes_in_use") is not None:
        out["devmem"] = {
            k: gauges.get(f"devmem_{k}")
            for k in ("bytes_in_use", "peak_bytes", "bytes_limit",
                      "watermark_bytes")}
    else:
        out["devmem"] = None
        out["devmem_reason"] = (
            reg.get_info("devmem_reason")
            or "no device-memory poll in this process")
    # sharding rides the same contract: the per-fn introspection blobs
    # publish_shardings deposited, or an explicit null with the reason
    shardings = reg.get_info("sharding")
    if shardings:
        out["sharding"] = shardings
    else:
        out["sharding"] = None
        out["sharding_reason"] = (
            "no sharding introspection published in this process "
            "(telemetry.sharding.publish_shardings)")
    # the planner's chosen layout, when one was published
    plan = reg.get_info("layout_plan")
    if plan:
        out["layout_plan"] = plan
    else:
        out["layout_plan"] = None
        out["layout_plan_reason"] = (
            "no layout plan published in this process "
            "(mesh.planner.publish_plan)")
    # the run ledger: full attribution table when armed, an explicit
    # null with the reason when not (same contract as mfu/devmem)
    led = goodput.get_ledger()
    if led is not None:
        out["goodput"] = led.summary()
    else:
        out["goodput"] = None
        out["goodput_reason"] = (
            "goodput ledger not armed in this process "
            "(telemetry.goodput.enable)")
    return out


def reset() -> None:
    """Fresh registry + disabled global timeline + disarmed flight
    recorder / compile tracker / devmem ledger / comms tracer
    (tests)."""
    flight.disable()
    compiled.disable()
    devmem.disable()
    comms.disable()
    goodput.disable()
    moe.reset()
    metrics.reset()
    timeline.disable()


__all__ = [
    "CommsTracer",
    "CompileTracker",
    "Counter",
    "DeviceMemoryLedger",
    "FleetAggregator",
    "FlightRecorder",
    "Gauge",
    "GoodputLedger",
    "Histogram",
    "InMemorySink",
    "InstrumentedCollective",
    "JsonlSink",
    "LATENCY_MS_BUCKETS",
    "MetricsRegistry",
    "PAYLOAD_BYTES_BUCKETS",
    "PHASES",
    "SLOMonitor",
    "SLOTarget",
    "SlidingWindowQuantile",
    "Span",
    "StdoutSink",
    "StepSeries",
    "StepTimeline",
    "TOKEN_COUNT_BUCKETS",
    "comms",
    "compiled",
    "cost",
    "devmem",
    "disable",
    "enable",
    "fleet",
    "flight",
    "gather_snapshots",
    "get_timeline",
    "global_enabled",
    "goodput",
    "merge_snapshots",
    "metrics",
    "moe",
    "registry",
    "reset",
    "sharding",
    "slo",
    "snapshot",
    "snapshot_detail",
    "timeline",
    "to_prometheus_text",
]

"""SLO plane: sliding-window percentiles + multi-window burn-rate
alerting for the serving tier.

The metrics registry's histograms are fixed-bucket cumulative counts —
deliberately (O(buckets) observe, mergeable across hosts) — which
means they cannot answer "what is p99 TTFT over the last minute", and
nothing watched the latency objectives the ROADMAP's multi-engine
front door (item 2c) must shed load against. This module is that
watcher, in two layers:

- :class:`SlidingWindowQuantile` — an exact windowed quantile
  estimator: a time-pruned deque of (t, value) samples, quantiles by
  sort-on-read (the window is bounded, reads are per-check, not
  per-observe). This is the piece histograms structurally lack.
- :class:`SLOMonitor` — named :class:`SLOTarget` objectives (TTFT p99,
  TPOT p99, per-request goodput, queue depth — or any caller-defined
  target) with **multi-window burn-rate alerting** (the SRE-workbook
  idiom): per window pair ``(long_s, short_s, threshold)``, the burn
  rate is ``bad_fraction(window) / error_budget``; an alert fires only
  when BOTH windows burn past the threshold — the long window proves
  the violation is sustained, the short window proves it is still
  happening — and latches until the short window recovers, so one
  violation episode produces exactly one alert.

On alert: one ``slo_alert`` event, the ``slo_alert_active{slo=}``
gauge flips, and the flight recorder dumps an ``slo_violation`` bundle
whose ``extra`` embeds the OFFENDING requests' traces (the scheduler
attaches its :class:`~apex_tpu.serving.tracing.RequestTracer` and
``introspect()`` via :meth:`SLOMonitor.attach`) — a latency postmortem
opens with the slow requests' timelines in hand. On recovery: one
``slo_recovered`` event and the gauge drops. Every ``check()``
publishes ``slo_burn_rate{slo=,window=}`` and
``slo_window_value{slo=}`` (the current windowed percentile /
fraction) and mirrors :meth:`summary` into ``info["slo_window"]`` so
flight bundles, bench records, and ``tools/telemetry_dump.py`` carry
the SLO window without touching the monitor.

:meth:`should_shed` is the admission hook: True while any target is
alerting. ``ContinuousBatcher`` consults it at the top of admission
(``serving_slo_shed`` counter + event) — the exact signal the item-2c
router will route on, already load-shedding on one engine today.

Host-side Python only; a monitor nobody observes into costs one
attribute check per engine step.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# (long_s, short_s, burn_threshold) pairs — the SRE-workbook fast/slow
# pages scaled to serving-loop timescales; tests and smokes pass their
# own (seconds-scale) windows
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (60.0, 5.0, 14.4), (300.0, 30.0, 6.0))


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One objective: samples observed under ``name`` are GOOD when
    they sit on the right side of ``objective`` (``kind="le"``: at or
    below — latencies, queue depths; ``kind="ge"``: at or above —
    goodput/success indicators). ``budget`` is the allowed bad
    fraction (burn rate 1.0 = consuming exactly the budget);
    ``percentile`` is what :meth:`SLOMonitor.summary` reports for
    latency-style targets."""

    name: str
    objective: float
    budget: float = 0.01
    kind: str = "le"
    percentile: float = 0.99

    def __post_init__(self):
        if self.kind not in ("le", "ge"):
            raise ValueError(f"slo {self.name!r}: kind must be 'le' or "
                             f"'ge', got {self.kind!r}")
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"slo {self.name!r}: budget must be in "
                             f"(0, 1], got {self.budget}")

    def ok(self, value: float) -> bool:
        return (value <= self.objective if self.kind == "le"
                else value >= self.objective)


class SlidingWindowQuantile:
    """Exact quantiles over a trailing time window.

    A deque of ``(t, value)`` pruned on both observe and read;
    ``capacity`` bounds memory under sample floods (oldest drop first
    — the window is then effectively shorter, reported via
    :meth:`count` vs what the caller expected). Quantile reads sort a
    snapshot of the window — O(n log n) per read, and reads happen per
    monitor ``check()``, not per observation."""

    def __init__(self, window_s: float, *, capacity: int = 8192):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._samples: "deque[Tuple[float, float]]" = deque(
            maxlen=int(capacity))
        self._lock = threading.Lock()

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        s = self._samples
        while s and s[0][0] < cutoff:
            s.popleft()

    def observe(self, value: float, t: float) -> None:
        with self._lock:
            self._samples.append((float(t), float(value)))
            self._prune(t)

    def count(self, now: float) -> int:
        with self._lock:
            self._prune(now)
            return len(self._samples)

    def quantile(self, q: float, now: float) -> Optional[float]:
        """The ``q``-quantile (0..1) of the window at ``now``; None on
        an empty window. Linear interpolation between order
        statistics (numpy's default), so small windows don't step."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            self._prune(now)
            vals = sorted(v for _, v in self._samples)
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        pos = q * (len(vals) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac


class _TargetState:
    """Per-target sample ring + alert latch (monitor-internal)."""

    __slots__ = ("target", "samples", "est", "violators", "alerting",
                 "alerts")

    def __init__(self, target: SLOTarget, window_s: float,
                 capacity: int):
        self.target = target
        # (t, value, ok) — one ring serves every window (pruned to the
        # longest; shorter windows bisect into it)
        self.samples: "deque[Tuple[float, float, bool]]" = deque(
            maxlen=capacity)
        self.est = SlidingWindowQuantile(window_s, capacity=capacity)
        # newest offending request ids (what the violation bundle
        # names and embeds traces for)
        self.violators: "deque[Tuple[str, float]]" = deque(maxlen=16)
        self.alerting = False
        self.alerts = 0


class SLOMonitor:
    """Windowed SLO targets with burn-rate alerting (module
    docstring).

    - ``targets``: :class:`SLOTarget` list; observations under
      unconfigured names are dropped (publishers need no knowledge of
      which objectives are armed).
    - ``windows``: ``(long_s, short_s, burn_threshold)`` pairs; an
      alert needs BOTH windows of one pair past the threshold.
    - ``min_samples``: the short window must hold at least this many
      samples before it can alert (one unlucky request is not an SLO
      violation).
    - ``clock``: share the engine's clock (tests drive fake time).
    - ``registry``: where gauges/events publish (default: the
      process-global registry).
    """

    def __init__(self, targets: Sequence[SLOTarget], *,
                 windows: Sequence[Tuple[float, float, float]] =
                 DEFAULT_WINDOWS,
                 registry=None, min_samples: int = 5,
                 capacity: int = 8192, check_every: int = 4,
                 info_every: int = 16, shed: bool = True,
                 clock: Callable[[], float] = time.perf_counter):
        from apex_tpu.telemetry import metrics as _metrics

        if not targets:
            raise ValueError("SLOMonitor needs at least one target")
        self.windows = tuple((float(lo), float(sh), float(th))
                             for lo, sh, th in windows)
        if not self.windows:
            raise ValueError("SLOMonitor needs at least one window")
        for lo, sh, th in self.windows:
            if not 0 < sh <= lo:
                raise ValueError(
                    f"window pair must satisfy 0 < short <= long, got "
                    f"({lo}, {sh})")
        self.min_samples = int(min_samples)
        self.check_every = max(int(check_every), 1)
        self.info_every = max(int(info_every), 1)
        # shed=False: observe-only — alerts/bundles still fire but
        # should_shed() stays False. Shedding on a LATENCY objective
        # makes queued requests' latency worse (positive feedback:
        # shed -> age -> violate -> shed), so admission-side shedding
        # belongs to targets a router can actually relieve (queue
        # depth, goodput) or to a front door that reroutes the load.
        self.shed = bool(shed)
        self.clock = clock
        self._registry = (registry if registry is not None
                          else _metrics.registry())
        horizon = max(lo for lo, _, _ in self.windows)
        self._state: Dict[str, _TargetState] = {}
        for t in targets:
            if t.name in self._state:
                raise ValueError(f"duplicate SLO target {t.name!r}")
            self._state[t.name] = _TargetState(t, horizon,
                                               int(capacity))
        self._horizon = horizon
        self._lock = threading.Lock()
        self._ticks = 0
        self._checks = 0
        self._alerts_total = 0
        self._last_check: Optional[Dict[str, Any]] = None
        # pre-resolved gauge cells (Gauge.bind) keyed (metric, target,
        # window) — per-check publishing is a list store, not a label
        # sort (the <2% engine-step overhead budget)
        self._cells: Dict[Tuple[str, ...], Any] = {}
        # wired by the engine (scheduler.attach): callables producing
        # the offending requests' trace dicts and a live introspection
        # snapshot for the violation bundle
        self._trace_provider: Optional[Callable] = None
        self._introspect_provider: Optional[Callable] = None

    @classmethod
    def serving_default(cls, *, ttft_p99_s: float = 0.5,
                        tpot_p99_s: float = 0.1,
                        queue_depth: int = 64, **kw) -> "SLOMonitor":
        """The serving tier's canonical four targets: TTFT p99, TPOT
        p99, per-request goodput (1.0 = finished ok, 0.0 = error /
        deadline), queue depth."""
        return cls([
            SLOTarget("ttft_p99", ttft_p99_s),
            SLOTarget("tpot_p99", tpot_p99_s),
            SLOTarget("goodput", 1.0, kind="ge", budget=0.02,
                      percentile=0.5),
            SLOTarget("queue_depth", float(queue_depth), budget=0.05),
        ], **kw)

    def attach(self, *, trace_provider: Optional[Callable] = None,
               introspect_provider: Optional[Callable] = None) -> None:
        """Wire the violation bundle's evidence sources: a
        ``trace_provider(request_ids) -> [trace dicts]`` (the
        scheduler's RequestTracer) and an ``introspect_provider() ->
        dict`` (the scheduler's ``introspect``)."""
        if trace_provider is not None:
            self._trace_provider = trace_provider
        if introspect_provider is not None:
            self._introspect_provider = introspect_provider

    # -- observation -------------------------------------------------------

    def observe(self, name: str, value: float, *,
                request_id: Any = None,
                t: Optional[float] = None) -> None:
        """Record one sample for target ``name`` (no-op when the name
        is not configured — publishers stay decoupled from which
        objectives are armed)."""
        st = self._state.get(name)
        if st is None:
            return
        now = t if t is not None else self.clock()
        v = float(value)
        ok = st.target.ok(v)
        with self._lock:
            st.samples.append((now, v, ok))
        st.est.observe(v, now)
        if not ok and request_id is not None:
            st.violators.append((str(request_id), v))

    def observe_request(self, request_id, *,
                        ttft_s: Optional[float] = None,
                        tpot_s: Optional[float] = None,
                        ok: bool = True,
                        t: Optional[float] = None) -> None:
        """One finished request routed to the canonical targets
        (``ttft_p99`` / ``tpot_p99`` / ``goodput``) — the scheduler's
        single call site at result push."""
        now = t if t is not None else self.clock()
        if ttft_s is not None:
            self.observe("ttft_p99", ttft_s, request_id=request_id,
                         t=now)
        if tpot_s is not None:
            self.observe("tpot_p99", tpot_s, request_id=request_id,
                         t=now)
        self.observe("goodput", 1.0 if ok else 0.0,
                     request_id=request_id, t=now)

    # -- checking ----------------------------------------------------------

    def tick(self, *, now: Optional[float] = None,
             step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Per-engine-step entry point: runs :meth:`check` every
        ``check_every``-th call (rate limiting for hot loops)."""
        self._ticks += 1
        if self._ticks % self.check_every:
            return None
        return self.check(now=now, step=step)

    def check(self, *, now: Optional[float] = None,
              step: Optional[int] = None) -> Dict[str, Any]:
        """Evaluate every target against every window pair, publish
        the burn-rate gauges, fire/clear alerts, and return the check
        summary (also mirrored into ``info["slo_window"]``)."""
        t = now if now is not None else self.clock()
        reg = self._registry
        self._checks += 1

        def cell(metric: str, help: str, **labels):
            key = (metric,) + tuple(sorted(labels.items()))
            c = self._cells.get(key)
            if c is None:
                c = reg.gauge(metric, help).bind(**labels)
                self._cells[key] = c
            return c

        out: Dict[str, Any] = {"targets": {}, "alerting": []}
        fires: List[Tuple[str, _TargetState, Dict[str, Any],
                          Optional[float]]] = []
        for name, st in self._state.items():
            tgt = st.target
            with self._lock:
                samples = list(st.samples)
            times = [s[0] for s in samples]
            pairs = []
            firing = None
            for long_s, short_s, thr in self.windows:
                n_lo = bisect.bisect_right(times, t - long_s)
                n_sh = bisect.bisect_right(times, t - short_s)
                w_lo, w_sh = samples[n_lo:], samples[n_sh:]
                bad_lo = sum(1 for _, _, ok in w_lo if not ok)
                bad_sh = sum(1 for _, _, ok in w_sh if not ok)
                frac_lo = bad_lo / len(w_lo) if w_lo else 0.0
                frac_sh = bad_sh / len(w_sh) if w_sh else 0.0
                burn_lo = frac_lo / tgt.budget
                burn_sh = frac_sh / tgt.budget
                cell("slo_burn_rate",
                     "error-budget burn rate per SLO and window (1.0 "
                     "= consuming exactly the budget)",
                     slo=name, window=f"{long_s:g}s").set(burn_lo)
                cell("slo_burn_rate", "", slo=name,
                     window=f"{short_s:g}s").set(burn_sh)
                pair = {"long_s": long_s, "short_s": short_s,
                        "threshold": thr,
                        "burn_long": round(burn_lo, 4),
                        "burn_short": round(burn_sh, 4),
                        "samples_long": len(w_lo),
                        "samples_short": len(w_sh)}
                pairs.append(pair)
                if (firing is None and burn_lo > thr and burn_sh > thr
                        and len(w_sh) >= self.min_samples):
                    firing = pair
            pctl = st.est.quantile(tgt.percentile, t)
            cell("slo_window_value",
                 "current windowed percentile (latency SLOs) or bad "
                 "fraction over the longest window",
                 slo=name).set(pctl if pctl is not None else 0.0)
            was = st.alerting
            st.alerting = firing is not None
            cell("slo_alert_active",
                 "1 while the SLO's burn-rate alert is latched",
                 slo=name).set(1.0 if st.alerting else 0.0)
            if st.alerting and not was:
                st.alerts += 1
                self._alerts_total += 1
                fires.append((name, st, firing, pctl))
            elif was and not st.alerting:
                reg.event("slo_recovered", slo=name, step=step)
            out["targets"][name] = {
                "objective": tgt.objective, "kind": tgt.kind,
                "budget": tgt.budget,
                "percentile": tgt.percentile,
                "window_value": pctl,
                "windows": pairs,
                "alerting": st.alerting,
                "alerts": st.alerts,
            }
            if st.alerting:
                out["alerting"].append(name)
        out["alerts_total"] = self._alerts_total
        prev = self._last_check
        self._last_check = out
        # the info mirror costs a json.dumps validation — refresh it
        # on alert-set changes and every `info_every`-th check, not
        # per step (summary()/introspect() always read _last_check)
        if (fires or prev is None
                or out["alerting"] != prev.get("alerting")
                or self._checks % self.info_every == 0):
            try:
                reg.set_info("slo_window", out)
            except (TypeError, ValueError):  # non-JSON-able — never fatal
                pass
        # fire AFTER the summary is stored, so the violation bundle's
        # embedded introspect()/summary() shows the alerting state the
        # alert describes, not the previous window
        for name, st, pair, pctl in fires:
            self._fire(name, st, pair, pctl, t, step)
        return out

    def _fire(self, name: str, st: _TargetState,
              pair: Dict[str, Any], pctl: Optional[float],
              t: float, step: Optional[int]) -> None:
        """One violation episode begins: ``slo_alert`` event +
        ``slo_violation`` flight bundle embedding the offending
        requests' traces and a live introspection snapshot."""
        from apex_tpu.telemetry import flight as _flight

        reg = self._registry
        ids = [rid for rid, _ in st.violators]
        ev = reg.event("slo_alert", slo=name,
                       objective=st.target.objective,
                       window_value=pctl, step=step,
                       burn_long=pair["burn_long"],
                       burn_short=pair["burn_short"],
                       threshold=pair["threshold"],
                       long_s=pair["long_s"], short_s=pair["short_s"],
                       requests=ids)
        traces = None
        if self._trace_provider is not None:
            try:
                traces = self._trace_provider(ids)
            except Exception:  # noqa: BLE001 — evidence is best-effort
                traces = None
        intro = None
        if self._introspect_provider is not None:
            try:
                intro = self._introspect_provider()
            except Exception:  # noqa: BLE001
                intro = None
        _flight.notify(
            "slo_violation", fleet=False,
            error=RuntimeError(
                f"SLO {name!r} burn rate "
                f"{pair['burn_long']:.2f}/{pair['burn_short']:.2f} over "
                f"{pair['long_s']:g}s/{pair['short_s']:g}s windows "
                f"(threshold {pair['threshold']:g})"),
            extra={"slo": name, "event": ev, "requests": ids,
                   "violating_values": [v for _, v in st.violators],
                   "traces": traces, "introspect": intro})

    # -- the admission hook ------------------------------------------------

    def should_shed(self) -> bool:
        """True while any target's burn-rate alert is latched (and
        shedding is enabled) — the load-shedding signal the scheduler
        consults at admission (and the one a multi-engine router
        routes on)."""
        return self.shed and any(st.alerting
                                 for st in self._state.values())

    def alerting(self) -> List[str]:
        return [n for n, st in self._state.items() if st.alerting]

    def summary(self) -> Dict[str, Any]:
        """The newest check result (or a skeleton before the first
        check) — what ``introspect()`` and telemetry_dump render."""
        if self._last_check is not None:
            return self._last_check
        return {"targets": {n: {"objective": st.target.objective,
                                "kind": st.target.kind,
                                "budget": st.target.budget,
                                "alerting": False}
                            for n, st in self._state.items()},
                "alerting": [], "alerts_total": 0}


__all__ = [
    "DEFAULT_WINDOWS",
    "SLOMonitor",
    "SLOTarget",
    "SlidingWindowQuantile",
]

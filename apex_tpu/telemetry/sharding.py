"""Sharding plane: normalized shardings of compiled executables.

ROADMAP item 1's mesh planner needs to SEE how a compiled program laid
its arrays out — which mesh axes exist, how each input/output is
partitioned, and how many bytes each device actually holds — and
today that story lives in repr strings scattered across
``jax.stages.Compiled`` internals. This module normalizes it into one
fixed-key JSON-able dict, the same way ``cost.py`` normalizes
``cost_analysis()`` and ``devmem.py`` normalizes
``memory_analysis()``, under the same contract: on a backend without
meshes (the CPU CI, a single device) every key is still present and
the nulls carry an explicit ``sharding_reason`` — never silently
absent, never an exception out of an introspection call.

- :func:`normalize_sharding` — one ``jax.sharding.Sharding`` leaf to
  ``{kind, n_devices, mesh, spec, memory_kind, shard_shape,
  shard_bytes}``.
- :func:`executable_shardings` — a compiled executable's inputs +
  outputs + mesh axes + per-device bytes, fixed keys, never raises.
- :func:`jitted_shardings` — lower+compile a jitted fn on example
  args and introspect it (the one-liner bench and tests use).
- :func:`publish_shardings` — ``sharding_devices{fn=}`` gauges + the
  registry info blob ``snapshot_detail()`` folds in, keyed by fn so
  repeated publishes of different programs accumulate.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

# the fixed key set executable_shardings always returns — consumers
# (snapshot_detail, flight bundles, the future planner) can index
# without existence checks
SHARDING_KEYS = ("fn", "backend", "n_devices", "mesh", "inputs",
                 "outputs", "input_bytes_per_device",
                 "output_bytes_per_device", "sharding_reason")

_NO_MESH_REASON = ("no mesh-sharded arrays: every sharding is "
                   "single-device (backend={backend})")


def _aval_bytes(shape: Sequence[int], dtype) -> Optional[int]:
    try:
        import numpy as np

        return int(math.prod(shape)) * int(np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001
        return None


def normalize_sharding(s, shape: Optional[Sequence[int]] = None,
                       dtype=None) -> Dict[str, Any]:
    """One sharding leaf as a fixed-key dict. ``shape``/``dtype`` (the
    aval's) enable the per-shard keys; without them those are null."""
    out: Dict[str, Any] = {
        "kind": type(s).__name__,
        "n_devices": 1,
        "mesh": None,
        "spec": None,
        "memory_kind": None,
        "shard_shape": None,
        "shard_bytes": None,
    }
    try:
        devs = getattr(s, "device_set", None)
        if devs:
            out["n_devices"] = len(devs)
        mesh = getattr(s, "mesh", None)
        if mesh is not None and getattr(mesh, "shape", None):
            out["mesh"] = {str(name): int(size)
                           for name, size in dict(mesh.shape).items()}
        spec = getattr(s, "spec", None)
        if spec is not None:
            out["spec"] = str(tuple(spec))
        out["memory_kind"] = getattr(s, "memory_kind", None)
        if shape is not None:
            shard_shape = tuple(int(d) for d in
                                s.shard_shape(tuple(shape)))
            out["shard_shape"] = list(shard_shape)
            if dtype is not None:
                out["shard_bytes"] = _aval_bytes(shard_shape, dtype)
    except Exception:  # noqa: BLE001 — introspection never raises
        pass
    return out


def _flatten_avals(avals) -> Optional[List[Any]]:
    try:
        import jax.tree_util as jtu

        return list(jtu.tree_leaves(avals))
    except Exception:  # noqa: BLE001
        return None


def _leaf_entries(shardings, avals) -> List[Dict[str, Any]]:
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "device_set"))
    avals = _flatten_avals(avals)
    if avals is not None and len(avals) != len(leaves):
        avals = None                 # structure mismatch: shapes unknown
    out = []
    for i, s in enumerate(leaves):
        shape = dtype = None
        if avals is not None:
            a = avals[i]
            shape = tuple(getattr(a, "shape", ()) or ())
            dtype = getattr(a, "dtype", None)
        out.append(normalize_sharding(s, shape=shape, dtype=dtype))
    return out


def executable_shardings(compiled, *, fn: str = "jit",
                         out_avals=None) -> Dict[str, Any]:
    """A compiled executable (``jit(f).lower(...).compile()``) as the
    fixed-key sharding dict (:data:`SHARDING_KEYS`). Never raises: a
    backend/executable without the introspection surface returns nulls
    with ``sharding_reason``.

    ``out_avals`` supplies output shapes/dtypes (``Compiled`` carries
    input avals but not output ones — :func:`jitted_shardings` fills
    them from ``jax.eval_shape``); without it per-output shard bytes
    are null.
    """
    out: Dict[str, Any] = {k: None for k in SHARDING_KEYS}
    out["fn"] = str(fn)
    try:
        import jax

        out["backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001
        pass
    try:
        in_sh = compiled.input_shardings
        out_sh = compiled.output_shardings
    except Exception as e:  # noqa: BLE001
        out["sharding_reason"] = (
            f"executable exposes no shardings "
            f"({type(e).__name__}: {e})")
        return out
    try:
        in_avals = getattr(compiled, "in_avals", None)
        inputs = _leaf_entries(in_sh, in_avals)
        outputs = _leaf_entries(out_sh, out_avals)
        out["inputs"] = inputs
        out["outputs"] = outputs
        out["n_devices"] = max(
            [e["n_devices"] for e in inputs + outputs] or [1])
        # the union of mesh axes any array is laid out over
        mesh: Dict[str, int] = {}
        for e in inputs + outputs:
            if e["mesh"]:
                mesh.update(e["mesh"])
        out["mesh"] = mesh or None

        def _total(entries):
            vals = [e["shard_bytes"] for e in entries]
            if any(v is None for v in vals):
                return None
            return int(sum(vals))

        out["input_bytes_per_device"] = _total(inputs)
        out["output_bytes_per_device"] = _total(outputs)
        if out["mesh"] is None:
            out["sharding_reason"] = _NO_MESH_REASON.format(
                backend=out["backend"])
    except Exception as e:  # noqa: BLE001
        out["sharding_reason"] = (
            f"sharding introspection failed "
            f"({type(e).__name__}: {e})")
    return out


def jitted_shardings(jitted, *args, fn: str = "jit",
                     **kwargs) -> Dict[str, Any]:
    """Lower+compile ``jitted`` on example args and introspect the
    result; output avals come from ``jax.eval_shape`` so per-output
    shard bytes are real. Never raises."""
    try:
        import jax

        compiled = jitted.lower(*args, **kwargs).compile()
        try:
            out_avals = jax.eval_shape(jitted, *args, **kwargs)
        except Exception:  # noqa: BLE001
            out_avals = None
        return executable_shardings(compiled, fn=fn,
                                    out_avals=out_avals)
    except Exception as e:  # noqa: BLE001
        out = {k: None for k in SHARDING_KEYS}
        out["fn"] = str(fn)
        out["sharding_reason"] = (
            f"lower/compile failed ({type(e).__name__}: {e})")
        return out


def publish_shardings(info: Dict[str, Any], *, registry=None
                      ) -> Dict[str, Any]:
    """Publish one :func:`executable_shardings` dict:
    ``sharding_devices{fn=}`` gauge + per-direction
    ``sharding_bytes_per_device{fn=,dir=}`` gauges (when known), and
    merge it into the registry's ``sharding`` info blob keyed by fn —
    what ``snapshot_detail()`` folds in. Returns ``info``."""
    from apex_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.registry()
    fn = str(info.get("fn") or "jit")
    reg.gauge("sharding_devices",
              "devices a compiled fn's arrays are laid out over"
              ).set(info.get("n_devices") or 1, fn=fn)
    bytes_g = reg.gauge("sharding_bytes_per_device",
                        "per-device buffer bytes of a compiled fn")
    for direction in ("input", "output"):
        v = info.get(f"{direction}_bytes_per_device")
        if v is not None:
            bytes_g.set(v, fn=fn, dir=direction)
    blob = dict(reg.get_info("sharding") or {})
    blob[fn] = info
    reg.set_info("sharding", blob)
    return info


__all__ = [
    "SHARDING_KEYS",
    "executable_shardings",
    "jitted_shardings",
    "normalize_sharding",
    "publish_shardings",
]

"""Run ledger: restart-surviving goodput attribution + step anomalies.

Every plane before this one is instantaneous or episodic — snapshots,
spans, EWMA latches, flight bundles. None of them answers the run-level
production question: *of the wall-clock this run has burned, what
fraction trained the model, and where did the rest go?* The
:class:`GoodputLedger` answers it by attributing **every second of the
run** to a cause bucket:

========================  ==================================================
cause                     fed by
========================  ==================================================
``productive``            ``"step"`` timeline spans (the fused dispatch),
                          net of compile time that landed inside them
``compile``               CompileTracker's ``"compile"`` spans
``checkpoint_save``       ``"checkpoint"`` spans with ``kind=save``
``checkpoint_restore``    ``"checkpoint"`` spans with ``kind=restore``
``data_wait``             ``"data_wait"`` spans (PrefetchLoader / wrap_iter)
``rollback``              watchdog/guard escalation wall time net of the
                          restore I/O (which lands in checkpoint_restore)
``rework``                step spans re-trained after a rollback or a
                          kill-and-resume, counted by replayed step index
``drain_shutdown``        ``graceful_shutdown`` wall net of its final save
``straggler_wait``        fleet aggregation's per-phase straggler spread
``unattributed``          the residual — **published, never hidden**
========================  ==================================================

The attribution identity the tests pin: ``sum(buckets) + unattributed
== wall_seconds`` (buckets that can overlap wall — async checkpoint
saves on their own thread, per-stage pipeline spans — are surfaced as
``overlap_seconds`` / ``stages`` diagnostics, outside the identity).

**Feed.** The ledger rides the spans the instrumented layers already
record: :func:`enable` installs a span observer on
:mod:`~apex_tpu.telemetry.timeline` (one module-global check on the
already-instrumented path — disarmed cost is exactly that check), so
every :class:`~apex_tpu.telemetry.timeline.StepTimeline` — the global
one and the train step's private one — pushes each span through
:meth:`GoodputLedger.observe_span` as it is recorded. Ring eviction
therefore cannot lose attributed time: spans are attributed at record
time, and whatever the ledger never saw stays in ``unattributed`` (the
timeline's own evicted-span seconds ride the summary as
``timeline_dropped_span_seconds``).

**Restart survival.** ``checkpoint.save`` merges :meth:`pack` into the
manifest ``extra`` (tmp→fsync→rename, like everything else in the
payload) and ``restore`` feeds it back through :func:`note_restored` →
:meth:`absorb`: cumulative seconds / tokens / steps / anomaly episodes
carry across the kill, ``restarts`` increments, and the replayed step
range (checkpoint step → pre-kill high water) is re-attributed to
``rework`` as those steps run again. An incarnation guard keeps an
in-process watchdog rollback (save and restore in the same process)
from double-counting its own live state. Wall time is process-alive
wall summed across incarnations — the dead time *between* kill and
resume is not observable from inside the process and is documented
out of the identity.

**Anomaly plane.** :class:`StepSeries` keeps a ring of per-step
loss / grad-norm / step-ms / tokens-per-sec samples and latches two
flight triggers, SLO-monitor style (latch once per episode, re-arm on
recovery): ``loss_spike`` on a robust z-score (median/IQR over the
trailing window, maintained incrementally sorted so the hot path pays
two bisects, not a sort) and ``throughput_regression`` on a fast-vs-slow EWMA
of tokens/sec sustained below the drop threshold. Each latch emits a
registry event, flips ``goodput_anomaly_active{kind=}``, and dumps a
flight bundle embedding the offending series window.

Overhead contract (tools/check_observability.sh): disarmed is one
module-global attribute check on the span path; armed stays <1% on the
2ms CPU step.
"""

from __future__ import annotations

import bisect
import math
import os
import statistics
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from apex_tpu.telemetry import timeline as _timeline

# the bucket taxonomy (docs/observability.md "Run ledger & goodput");
# ``unattributed`` is the published residual, not a bucket anyone feeds
CAUSES = (
    "productive",
    "compile",
    "checkpoint_save",
    "checkpoint_restore",
    "data_wait",
    "rollback",
    "rework",
    "drain_shutdown",
    "straggler_wait",
)

_DISARMED_REASON = ("goodput ledger not armed in this process "
                    "(telemetry.goodput.enable)")


class StepSeries:
    """Ring of per-step training samples + anomaly latches.

    ``push`` ingests one step's ``loss`` / ``grad_norm`` / ``step_ms``
    / ``tokens_per_s`` and returns the anomaly transitions it caused as
    ``(kind, phase, info)`` tuples (``phase`` is ``"latch"`` or
    ``"recover"``) — the ledger turns those into events / gauges /
    flight bundles; the series itself touches no registry so it stays
    unit-testable with plain numbers.

    Detection knobs:

    - ``loss_z`` — latch ``loss_spike`` when the robust z-score of the
      incoming loss against the trailing ``window`` samples
      (``(x−median)/(0.7413·IQR)``, both read in O(1) from an
      incrementally sorted window) exceeds this; re-arm when it falls
      back under ``loss_z/2``. Needs ``min_samples`` priors first.
    - ``throughput_drop`` / ``sustain`` — latch
      ``throughput_regression`` when the fast EWMA (``fast_alpha``) of
      tokens/sec sits below ``(1−throughput_drop)×`` the slow baseline
      EWMA (``slow_alpha``) for ``sustain`` consecutive steps; re-arm
      once the fast EWMA recovers to within half the drop.
    """

    def __init__(self, capacity: int = 512, *, loss_z: float = 6.0,
                 min_samples: int = 16, window: int = 64,
                 throughput_drop: float = 0.3, sustain: int = 5,
                 fast_alpha: float = 0.3, slow_alpha: float = 0.03):
        self.capacity = int(capacity)
        self.loss_z = float(loss_z)
        self.min_samples = int(min_samples)
        self.win = int(window)
        self.throughput_drop = float(throughput_drop)
        self.sustain = int(sustain)
        self.fast_alpha = float(fast_alpha)
        self.slow_alpha = float(slow_alpha)
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        # the loss prior window, kept BOTH in arrival order (for O(1)
        # eviction) and sorted (for O(1) median/IQR reads) — the
        # per-step cost is two bisects, not a sort over the window
        self._loss_win: "deque[float]" = deque()
        self._loss_sorted: List[float] = []
        self._fast: Optional[float] = None
        self._slow: Optional[float] = None
        self._tps_samples = 0
        self._low_streak = 0
        self.active = {"loss_spike": False, "throughput_regression": False}
        self.episodes = {"loss_spike": 0, "throughput_regression": 0}

    # -- ingestion ---------------------------------------------------------

    def push(self, *, step: Optional[int] = None,
             loss: Optional[float] = None,
             grad_norm: Optional[float] = None,
             step_ms: Optional[float] = None,
             tokens_per_s: Optional[float] = None,
             ) -> List[Tuple[str, str, Dict[str, Any]]]:
        fired: List[Tuple[str, str, Dict[str, Any]]] = []
        sample: Dict[str, Any] = {
            "step": int(step) if step is not None else None,
            "loss": self._finite(loss),
            "grad_norm": self._finite(grad_norm),
            "step_ms": self._finite(step_ms),
            "tokens_per_s": self._finite(tokens_per_s),
        }
        z = self._loss_z(sample["loss"])
        if z is not None:
            sample["loss_z"] = round(z, 3)
            if not self.active["loss_spike"] and z > self.loss_z:
                self.active["loss_spike"] = True
                self.episodes["loss_spike"] += 1
                fired.append(("loss_spike", "latch", {
                    "loss": sample["loss"], "loss_z": sample["loss_z"],
                    "threshold": self.loss_z, "step": sample["step"]}))
            elif self.active["loss_spike"] and z < self.loss_z / 2.0:
                self.active["loss_spike"] = False
                fired.append(("loss_spike", "recover", {
                    "loss": sample["loss"], "loss_z": sample["loss_z"],
                    "step": sample["step"]}))
        tps = sample["tokens_per_s"]
        if tps is None and sample["step_ms"]:
            # no token count — regress on step rate instead (steps/sec
            # scaled to a per-ms figure keeps the EWMAs comparable)
            tps = 1e3 / sample["step_ms"]
        fired.extend(self._throughput(tps, sample))
        self._ring.append(sample)
        if sample["loss"] is not None:
            if len(self._loss_win) >= self.win:
                old = self._loss_win.popleft()
                del self._loss_sorted[
                    bisect.bisect_left(self._loss_sorted, old)]
            self._loss_win.append(sample["loss"])
            bisect.insort(self._loss_sorted, sample["loss"])
        return fired

    @staticmethod
    def _finite(v: Optional[float]) -> Optional[float]:
        if v is None:
            return None
        v = float(v)
        return v if math.isfinite(v) else None

    def _loss_z(self, loss: Optional[float]) -> Optional[float]:
        if loss is None:
            return None
        srt = self._loss_sorted        # the PRIOR window: the incoming
        n = len(srt)                   # sample is appended after scoring
        if n < self.min_samples:
            return None
        med = (srt[n // 2] if n % 2
               else 0.5 * (srt[n // 2 - 1] + srt[n // 2]))
        # robust sigma from the IQR of the same sorted window
        # (0.7413·IQR ≈ σ for a normal prior) — O(1) reads where a
        # per-step MAD would pay a fresh sort of the deviations
        scale = 0.7413 * (srt[(3 * n) // 4] - srt[n // 4])
        if scale <= 0.0:
            # flat prior window: any upward deviation is a spike,
            # downward movement never is
            return math.inf if loss > med else 0.0
        return (loss - med) / scale

    def _throughput(self, tps: Optional[float], sample: Dict[str, Any],
                    ) -> List[Tuple[str, str, Dict[str, Any]]]:
        if tps is None or tps <= 0.0:
            return []
        self._tps_samples += 1
        self._fast = (tps if self._fast is None else
                      (1 - self.fast_alpha) * self._fast
                      + self.fast_alpha * tps)
        self._slow = (tps if self._slow is None else
                      (1 - self.slow_alpha) * self._slow
                      + self.slow_alpha * tps)
        if self._tps_samples < self.min_samples:
            return []
        fired: List[Tuple[str, str, Dict[str, Any]]] = []
        floor = (1.0 - self.throughput_drop) * self._slow
        if self._fast < floor:
            self._low_streak += 1
        else:
            self._low_streak = 0
        info = {"tokens_per_s_ewma": round(self._fast, 3),
                "baseline_ewma": round(self._slow, 3),
                "drop_threshold": self.throughput_drop,
                "step": sample["step"]}
        if (not self.active["throughput_regression"]
                and self._low_streak >= self.sustain):
            self.active["throughput_regression"] = True
            self.episodes["throughput_regression"] += 1
            fired.append(("throughput_regression", "latch", info))
        elif (self.active["throughput_regression"]
              and self._fast >= (1.0 - self.throughput_drop / 2.0)
              * self._slow):
            self.active["throughput_regression"] = False
            fired.append(("throughput_regression", "recover", info))
        return fired

    # -- reading -----------------------------------------------------------

    def window(self, n: int = 32) -> List[Dict[str, Any]]:
        """The newest ``n`` samples — what the flight bundle embeds."""
        return list(self._ring)[-int(n):]

    def summary(self) -> Dict[str, Any]:
        last = self._ring[-1] if self._ring else None
        return {
            "samples": len(self._ring),
            "episodes": dict(self.episodes),
            "active": dict(self.active),
            "tokens_per_s_ewma": (round(self._fast, 3)
                                  if self._fast is not None else None),
            "baseline_tokens_per_s_ewma": (round(self._slow, 3)
                                           if self._slow is not None
                                           else None),
            "last": dict(last) if last else None,
        }


class GoodputLedger:
    """Attributes run wall-clock to cause buckets; survives restarts.

    Spans arrive through :meth:`observe_span` (installed as the
    timeline span observer by :func:`enable`); the host loop feeds
    per-step loss/tokens through :meth:`observe_step`; resilience
    layers report episodic costs through :meth:`note_rollback` /
    :meth:`note_drain` / :meth:`note_straggler_wait`; and the
    checkpoint payload round-trips :meth:`pack` / :meth:`absorb`.
    All methods are thread-safe (async checkpoint saves and the
    prefetch consumer record spans off-thread); clock is injectable
    for deterministic tests.
    """

    def __init__(self, *, publish_every: int = 20,
                 series: Optional[StepSeries] = None,
                 clock: Callable[[], float] = time.perf_counter):
        import threading

        self.clock = clock
        self.publish_every = int(publish_every)
        self.series = series if series is not None else StepSeries()
        self.incarnation = f"{os.getpid()}-{id(self):x}"
        self._lock = threading.Lock()
        self._t0 = clock()
        self._seconds: Dict[str, float] = {c: 0.0 for c in CAUSES}
        self._carried_wall = 0.0
        self._tokens = 0.0
        self._steps = 0
        self._rework_steps = 0
        self._step_high_water = -1
        self._replay_remaining = 0
        self._restarts = 0
        self._rollbacks = 0
        self._compile_pending = 0.0
        self._span_step_feed = False
        self._step_durs: "deque[float]" = deque(maxlen=512)
        self._stage_seconds: Dict[str, float] = {}
        self._absorbed: set = set()

    # -- span feed ---------------------------------------------------------

    def observe_span(self, span) -> None:
        """Route one timeline span into its bucket. Called on the
        recording thread for every span of every armed timeline —
        keep it to one dict update under the lock."""
        name = span.name
        if name == "step":
            with self._lock:
                self._span_step_feed = True
                self._step_durs.append(span.dur)
                self._credit_step(span.dur)
        elif name == "data_wait":
            with self._lock:
                self._seconds["data_wait"] += span.dur
        elif name == "compile":
            with self._lock:
                self._seconds["compile"] += span.dur
                # compile happens inside the dispatch the "step" span
                # times — remember it so the step credit nets it out
                # and the identity holds
                self._compile_pending += span.dur
        elif name == "checkpoint":
            kind = (span.args or {}).get("kind", "save")
            key = ("checkpoint_restore" if kind == "restore"
                   else "checkpoint_save")
            with self._lock:
                self._seconds[key] += span.dur
        elif span.category == "pipeline" and name.startswith("pipeline:"):
            # per-stage attribution: stage spans overlap the step wall,
            # so they ride the summary as a diagnostic, outside the
            # identity
            with self._lock:
                self._stage_seconds[name] = (
                    self._stage_seconds.get(name, 0.0) + span.dur)
        # anything else (host_step, h2d, collective:*) stays in the
        # unattributed residual — published, never hidden

    def _credit_step(self, dur: float) -> None:
        # caller holds the lock
        comp, self._compile_pending = self._compile_pending, 0.0
        d = max(0.0, dur - comp)
        if self._replay_remaining > 0:
            self._replay_remaining -= 1
            self._rework_steps += 1
            self._seconds["rework"] += d
        else:
            self._seconds["productive"] += d

    # -- host-loop feed ----------------------------------------------------

    def observe_step(self, step: Optional[int] = None, *,
                     loss: Optional[float] = None,
                     grad_norm: Optional[float] = None,
                     tokens: Optional[float] = None,
                     step_s: Optional[float] = None) -> None:
        """One host-loop step: feeds the anomaly series, the token
        counter, and (only when no timeline ``"step"`` span has ever
        been seen — the span feed is authoritative) the productive /
        rework buckets from ``step_s``."""
        with self._lock:
            self._steps += 1
            if step is not None:
                self._step_high_water = max(self._step_high_water,
                                            int(step))
            if tokens:
                self._tokens += float(tokens)
            if step_s is not None and not self._span_step_feed:
                self._step_durs.append(float(step_s))
                self._credit_step(float(step_s))
            n = self._steps
        tps = None
        if tokens and step_s:
            tps = float(tokens) / float(step_s)
        fired = self.series.push(
            step=step, loss=loss, grad_norm=grad_norm,
            step_ms=step_s * 1e3 if step_s else None, tokens_per_s=tps)
        for kind, phase, info in fired:
            self._fire_anomaly(kind, phase, info)
        if self.publish_every and n % self.publish_every == 0:
            self.publish()

    def _fire_anomaly(self, kind: str, phase: str,
                      info: Dict[str, Any]) -> None:
        from apex_tpu.telemetry import flight as _flight
        from apex_tpu.telemetry import metrics as _metrics

        reg = _metrics.registry()
        g = reg.gauge("goodput_anomaly_active",
                      "1 while a step-series anomaly episode is latched")
        if phase == "latch":
            g.set(1.0, kind=kind)
            reg.event(kind, **{k: v for k, v in info.items()
                               if v is not None})
            _flight.notify(kind, fleet=False, extra={
                "series_window": self.series.window(32), **info})
        else:
            g.set(0.0, kind=kind)
            reg.event(f"{kind}_recovered",
                      **{k: v for k, v in info.items() if v is not None})

    # -- episodic costs ----------------------------------------------------

    def note_rollback(self, seconds: float, *,
                      restore_seconds: float = 0.0,
                      restored_step: Optional[int] = None) -> None:
        """A watchdog/guard escalation: ``seconds`` of wall went to the
        rollback, of which ``restore_seconds`` was the restore I/O
        (already attributed to ``checkpoint_restore`` by its span, so
        it is netted out here). ``restored_step`` arms the rework
        window: steps from it up to the high water re-train."""
        with self._lock:
            self._rollbacks += 1
            self._seconds["rollback"] += max(
                0.0, float(seconds) - float(restore_seconds))
            if restored_step is not None:
                self._replay_remaining = max(
                    self._replay_remaining,
                    self._step_high_water - int(restored_step))

    def note_drain(self, seconds: float, *,
                   save_seconds: float = 0.0) -> None:
        """A graceful drain/shutdown: wall net of the final save (the
        save lands in ``checkpoint_save`` via its own span)."""
        with self._lock:
            self._seconds["drain_shutdown"] += max(
                0.0, float(seconds) - float(save_seconds))

    def note_straggler_wait(self, seconds: float) -> None:
        """Fleet-aggregation straggler spread: seconds the median host
        spent waiting on the slowest one (approximate — one spread
        sample per aggregate call)."""
        if seconds and seconds > 0.0:
            with self._lock:
                self._seconds["straggler_wait"] += float(seconds)

    # -- restart survival --------------------------------------------------

    def pack(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Cumulative state as a JSON-able dict — what rides the
        checkpoint manifest ``extra`` (and serving drain snapshots)
        under the tmp→fsync→rename discipline."""
        with self._lock:
            if step is not None:
                self._step_high_water = max(self._step_high_water,
                                            int(step))
            return {
                "version": 1,
                "incarnation": self.incarnation,
                "seconds": {c: round(v, 6)
                            for c, v in self._seconds.items()},
                "wall_seconds": round(self._wall_locked(), 6),
                "tokens_trained_total": self._tokens,
                "steps": self._steps,
                "rework_steps": self._rework_steps,
                "step_high_water": self._step_high_water,
                "restarts": self._restarts,
                "median_step_s": self._median_locked(),
                "anomaly_episodes": dict(self.series.episodes),
            }

    def absorb(self, packed: Optional[Dict[str, Any]], *,
               restored_step: Optional[int] = None) -> None:
        """Fold a restored :meth:`pack` back in. Prior-incarnation
        state accumulates (seconds, wall, tokens, steps, episodes) and
        counts one restart; the same incarnation (an in-process
        rollback restoring its own checkpoint) only updates the replay
        bookkeeping — its cumulative state is already live. Each
        incarnation is absorbed at most once."""
        with self._lock:
            if isinstance(packed, dict) and packed:
                inc = packed.get("incarnation")
                hw = packed.get("step_high_water")
                if hw is not None:
                    self._step_high_water = max(self._step_high_water,
                                                int(hw))
                if inc != self.incarnation and inc not in self._absorbed:
                    self._absorbed.add(inc)
                    self._restarts = int(packed.get("restarts", 0) or 0) + 1
                    for c, v in (packed.get("seconds") or {}).items():
                        if c in self._seconds:
                            self._seconds[c] += float(v)
                    # prior unattributed arrives implicitly: carried
                    # wall minus carried buckets
                    self._carried_wall += float(
                        packed.get("wall_seconds", 0.0) or 0.0)
                    self._tokens += float(
                        packed.get("tokens_trained_total", 0.0) or 0.0)
                    self._steps += int(packed.get("steps", 0) or 0)
                    self._rework_steps += int(
                        packed.get("rework_steps", 0) or 0)
                    for k, v in (packed.get("anomaly_episodes")
                                 or {}).items():
                        if k in self.series.episodes:
                            self.series.episodes[k] += int(v)
            if restored_step is not None:
                self._replay_remaining = max(
                    self._replay_remaining,
                    self._step_high_water - int(restored_step))

    # -- reading -----------------------------------------------------------

    def _wall_locked(self) -> float:
        return self._carried_wall + (self.clock() - self._t0)

    def _median_locked(self) -> Optional[float]:
        if not self._step_durs:
            return None
        return round(statistics.median(self._step_durs), 6)

    def summary(self) -> Dict[str, Any]:
        """The full attribution table + run totals — the JSON blob the
        bundle / dump / report render. ``unattributed`` is computed
        here as ``max(0, wall − Σ buckets)``; when async overlap pushes
        the buckets past wall, the excess is ``overlap_seconds``."""
        with self._lock:
            wall = self._wall_locked()
            seconds = {c: round(v, 6) for c, v in self._seconds.items()}
            attributed = sum(self._seconds.values())
            out: Dict[str, Any] = {
                "enabled": True,
                "incarnation": self.incarnation,
                "wall_seconds": round(wall, 6),
                "attributed_seconds": round(attributed, 6),
                "unattributed_seconds": round(max(0.0, wall - attributed),
                                              6),
                "overlap_seconds": round(max(0.0, attributed - wall), 6),
                "goodput_fraction": (
                    round(self._seconds["productive"] / wall, 6)
                    if wall > 0 else 0.0),
                "seconds": seconds,
                "tokens_trained_total": self._tokens,
                "effective_tokens_per_sec": (
                    round(self._tokens / wall, 3) if wall > 0 else 0.0),
                "steps": self._steps,
                "rework_steps": self._rework_steps,
                "step_high_water": self._step_high_water,
                "replay_remaining": self._replay_remaining,
                "restarts": self._restarts,
                "rollbacks": self._rollbacks,
                "median_step_s": self._median_locked(),
                "stages": ({k: round(v, 6)
                            for k, v in self._stage_seconds.items()}
                           or None),
            }
        out["seconds"]["unattributed"] = out["unattributed_seconds"]
        out["anomalies"] = self.series.summary()
        out["timeline_dropped_span_seconds"] = self._timeline_dropped()
        return out

    @staticmethod
    def _timeline_dropped() -> float:
        try:
            tl = _timeline._GLOBAL
            return round(tl.dropped_seconds, 6) if tl is not None else 0.0
        except Exception:  # noqa: BLE001 — diagnostics never raise
            return 0.0

    def publish(self, registry=None) -> Dict[str, Any]:
        """Mirror the summary into gauges + the ``goodput`` info blob
        (so any registry snapshot — bundles, fleet gathers, bench
        records — carries the table), and refresh ``mfu_ewma`` from
        the productive-step window when a step cost was published."""
        from apex_tpu.telemetry import cost as _cost
        from apex_tpu.telemetry import metrics as _metrics

        reg = registry if registry is not None else _metrics.registry()
        summ = self.summary()
        g = reg.gauge("goodput_seconds",
                      "run wall-clock attributed to each cause bucket")
        for cause, v in summ["seconds"].items():
            g.set(v, cause=cause)
        reg.gauge("goodput_fraction",
                  "productive seconds / run wall seconds").set(
            summ["goodput_fraction"])
        reg.gauge("tokens_trained_total",
                  "tokens trained across the whole run (restarts "
                  "included)").set(summ["tokens_trained_total"])
        reg.gauge("effective_tokens_per_sec",
                  "tokens trained / run wall seconds").set(
            summ["effective_tokens_per_sec"])
        med = summ["median_step_s"]
        flops = reg.gauge("step_flops",
                          "static FLOPs of one compiled step").value()
        if med and flops:
            nbytes = reg.gauge(
                "step_bytes_accessed",
                "static HBM bytes accessed by one compiled step").value()
            _cost.publish_mfu_window(
                {"flops": flops,
                 "bytes_accessed": nbytes if nbytes else None},
                med, registry=reg)
            summ["mfu_ewma"] = reg.gauge(
                "mfu_ewma", "EWMA model FLOPs utilization over the "
                "ledger's productive-step window").value()
        reg.set_info("goodput", summ)
        return summ


# ---------------------------------------------------------------------------
# The process-global ledger (module API the instrumented layers call)
# ---------------------------------------------------------------------------

_LEDGER: Optional[GoodputLedger] = None


def enable(*, publish_every: int = 20,
           series: Optional[StepSeries] = None,
           clock: Callable[[], float] = time.perf_counter,
           **series_kw) -> GoodputLedger:
    """Arm a fresh ledger: installs the timeline span observer and
    turns the global timeline on if it is off (the ledger rides its
    spans). Extra keyword args construct the :class:`StepSeries`
    (``loss_z=``, ``throughput_drop=``, ...)."""
    global _LEDGER
    led = GoodputLedger(
        publish_every=publish_every,
        series=series if series is not None else StepSeries(**series_kw),
        clock=clock)
    _LEDGER = led
    _timeline.set_span_observer(led.observe_span)
    if not _timeline.global_enabled():
        _timeline.enable()
    return led


def disable() -> None:
    """Disarm: drops the ledger and the span observer (the timeline
    itself is left as-is — ``telemetry.reset()`` handles that)."""
    global _LEDGER
    _LEDGER = None
    _timeline.set_span_observer(None)


def get_ledger() -> Optional[GoodputLedger]:
    return _LEDGER


def enabled() -> bool:
    return _LEDGER is not None


def section() -> Dict[str, Any]:
    """The goodput block snapshots / bundles / dumps carry: the full
    summary when armed, an explicit null-with-reason when not."""
    led = _LEDGER
    if led is None:
        return {"enabled": False, "goodput_reason": _DISARMED_REASON}
    return led.summary()


def observe_step(step: Optional[int] = None, *,
                 loss: Optional[float] = None,
                 grad_norm: Optional[float] = None,
                 tokens: Optional[float] = None,
                 step_s: Optional[float] = None) -> None:
    """Host-loop per-step feed; no-op (one attribute check) when the
    ledger is disarmed."""
    led = _LEDGER
    if led is not None:
        led.observe_step(step, loss=loss, grad_norm=grad_norm,
                         tokens=tokens, step_s=step_s)


def note_rollback(seconds: float, *, restore_seconds: float = 0.0,
                  restored_step: Optional[int] = None) -> None:
    led = _LEDGER
    if led is not None:
        led.note_rollback(seconds, restore_seconds=restore_seconds,
                          restored_step=restored_step)


def note_drain(seconds: float, *, save_seconds: float = 0.0) -> None:
    led = _LEDGER
    if led is not None:
        led.note_drain(seconds, save_seconds=save_seconds)


def note_straggler_wait(seconds: float) -> None:
    led = _LEDGER
    if led is not None:
        led.note_straggler_wait(seconds)


def merge_into_extra(extra: Optional[Dict[str, Any]],
                     step: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Fold :meth:`GoodputLedger.pack` into a checkpoint/snapshot
    ``extra`` payload. Returns ``extra`` unchanged when the ledger is
    disarmed, when ``extra`` is not a dict (caller-owned shape), or
    when the caller already set a ``goodput`` key. Never raises —
    persistence must not take down the save that carries it."""
    led = _LEDGER
    if led is None:
        return extra
    try:
        pack = led.pack(step=step)
        if extra is None:
            return {"goodput": pack}
        if isinstance(extra, dict) and "goodput" not in extra:
            out = dict(extra)
            out["goodput"] = pack
            return out
    except Exception:  # noqa: BLE001
        pass
    return extra


def note_restored(extra: Optional[Dict[str, Any]], *,
                  restored_step: Optional[int] = None) -> None:
    """Absorb the ledger state riding a restored checkpoint's ``extra``
    (and arm the rework window from ``restored_step``). No-op when
    disarmed; never raises."""
    led = _LEDGER
    if led is None:
        return
    try:
        packed = extra.get("goodput") if isinstance(extra, dict) else None
        led.absorb(packed if isinstance(packed, dict) else None,
                   restored_step=restored_step)
    except Exception:  # noqa: BLE001 — restore must not fail on telemetry
        pass


__all__ = [
    "CAUSES",
    "GoodputLedger",
    "StepSeries",
    "disable",
    "enable",
    "enabled",
    "get_ledger",
    "merge_into_extra",
    "note_drain",
    "note_restored",
    "note_rollback",
    "note_straggler_wait",
    "observe_step",
    "section",
]

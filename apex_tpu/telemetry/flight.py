"""Crash flight recorder: a bounded black box dumped on failure.

"A host died at step 40k" is a shrug unless the evidence survives the
death. This module continuously retains the CHEAP tail of a run — the
last window of timeline spans, recent structured telemetry events, and
compact state digests riding the segmented per-leaf checksums from the
consistency guard — and on a trigger dumps one self-contained
``flightrec_*.json`` postmortem bundle through the records tmp→fsync→
rename protocol, so the bundle is on the platter before the process is
gone.

Triggers (wired across the runtime; see docs/observability.md):

====================== ====================================================
trigger                fired by
====================== ====================================================
``watchdog_rollback``  ``resilience.watchdog`` escalation (rollback /
                       scaler reset past the skip threshold)
``replica_divergence`` ``resilience.guard`` divergence boundary (majority
                       repair or rollback)
``divergence_error``   unrecoverable divergence / lost lockstep
                       (``DivergenceError`` about to raise)
``preemption_shutdown`` ``resilience.guard.graceful_shutdown`` (SIGTERM
                       drain, final checkpoint written)
``train_step_exception`` unhandled exception escaping the fused-step
                       dispatch (``optimizers.train_step``)
``elastic_restore_error`` any failed elastic restore
                       (``resilience.elastic`` — plan/fetch/verify
                       failures, and the guard's post-restore baseline
                       mismatch); the bundle's ``extra`` carries the
                       layout manifest, the computed restore plan, and
                       per-range fetch/verify status
``serving_pool_exhausted`` the serving scheduler's admission control hit
                       an exhausted KV pool (real or injected) and shed
                       load (``serving.scheduler``, host-local; extra
                       carries queue depth + blocks in use)
``serving_request_error`` a serving request failed: rejected as larger
                       than the whole pool (host-local; extra names the
                       request id)
``serving_quarantine`` per-request fault isolation quarantined one or
                       more sequences — a decode exception localized by
                       binary-split retry, or nonfinite logits named by
                       the in-jit per-lane finite flag
                       (``serving.scheduler``, host-local; extra names
                       the request ids + reasons). Replaces the old
                       engine-fatal decode-exception path.
``serving_drain``      the serving engine entered preemption drain —
                       extra carries the committed snapshot path (or
                       the save error) and the queued/in-flight counts
                       at the drain point (host-local)
``serving_weight_swap`` a live weight hot-swap was REJECTED by
                       signature/fingerprint validation (extra carries
                       the structured mismatch list; successful swaps
                       emit only the ``serving_weight_swap`` event,
                       which rides this ring into the next bundle)
``slo_violation``      the SLO monitor's multi-window burn-rate alert
                       latched (``telemetry.slo.SLOMonitor`` — TTFT/
                       TPOT p99, goodput, queue depth); the bundle's
                       ``extra`` embeds the OFFENDING requests' trace
                       dicts and a live ``engine.introspect()``
                       snapshot, so the latency postmortem opens with
                       the slow requests' timelines in hand
                       (host-local; one bundle per violation episode)
``fleet_engine_lost``  the fleet router fenced a dead or wedged
                       serving engine (``serving.fleet.FleetRouter``,
                       host-local); the bundle's ``extra`` embeds the
                       victim's LAST ``introspect()`` plus the
                       structured recovery plan — snapshot vs replay
                       source, snapshot path, and the survivor each
                       recovered request was rerouted to
``moe_imbalance``      the MoE expert-load EWMA latch fired
                       (``telemetry.moe.MoEImbalanceDetector`` —
                       max/mean load ratio past ``factor``, e.g. a
                       collapsed router; host-local, one bundle per
                       excursion); the bundle's ``extra`` embeds the
                       offending per-expert load histogram and the
                       hot expert's index
``kv_handoff_failed``  a disaggregated KV handoff exhausted its wire
                       retries or the verified install was refused
                       (``serving.fleet.FleetRouter``, host-local);
                       the bundle's ``extra`` carries the transfer's
                       sha256 manifest (root + per-block hashes), the
                       LAST attempt's block-by-block verify status,
                       the source/destination engines, and the
                       attempt count — the stream itself survives on
                       the source (colocated degradation)
``loss_spike``         the goodput ledger's step-series robust
                       z-score latch fired (``telemetry.goodput
                       .StepSeries`` — loss z past ``loss_z`` against
                       the trailing median/MAD window; host-local,
                       one bundle per episode); the bundle's
                       ``extra`` embeds the offending series window
``throughput_regression`` the step-series fast-vs-slow EWMA of
                       tokens/sec sat below the drop threshold for
                       ``sustain`` consecutive steps (host-local, one
                       bundle per episode); ``extra`` embeds the
                       series window and both EWMAs
====================== ====================================================

Fleet-level triggers (the guard's, the shutdown's) fire on EVERY
replica at the same loop point, so the dump may safely run a fleet
aggregation (:mod:`~apex_tpu.telemetry.fleet`) over the attached
collective — the bundle then carries the merged fleet snapshot and the
straggler gauges, not just this host's view. Host-local triggers
(watchdog, step exception) must never issue a collective (the peers
are not there) — they dump the local snapshot and say so.

The recorder costs a deque append per retained event/digest; the
timeline ring is the one the process already keeps. Nothing here runs
on the step hot path until a trigger fires, and a failing dump never
takes the run down (``notify`` swallows everything — the flight
recorder exists to explain failures, not to cause them).

Retention: bundles get their own records ``kind`` (``flightrec``) with
keep-last-``keep`` pruning (``records.prune_records``) after every
dump, so a crash-looping process cannot fill the disk with black
boxes.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

FLIGHT_KIND = "flightrec"
_CKPT_STEP_RE = re.compile(r"step_(\d+)$")


class FlightRecorder:
    """The black box: bounded rings + the atomic bundle dump.

    Attach it to the metrics registry as a SINK
    (``registry().add_sink(recorder)`` — :func:`enable` does this) and
    every structured event lands in the ``recent_events`` ring; feed
    fingerprint rows to :meth:`record_digest` (the consistency guard
    does, at every boundary) and the last ``digest_capacity`` state
    digests ride along.

    - ``last_steps``: how many host-loop steps of timeline spans the
      bundle's perfetto slice covers.
    - ``timeline``: a :class:`~apex_tpu.telemetry.StepTimeline`; None
      means the process-global timeline at dump time.
    - ``collective`` / ``manager``: the guard's collective (fleet
      snapshot in the bundle, when the trigger is fleet-safe) and the
      checkpoint manager (last valid checkpoint identity).
    - ``keep``: keep-last-k pruning of ``flightrec`` records.
    """

    def __init__(self, *, last_steps: int = 64,
                 event_capacity: int = 256, digest_capacity: int = 128,
                 timeline=None, collective=None, manager=None,
                 keep: int = 5, straggler_factor: float = 2.0):
        self.last_steps = int(last_steps)
        self.keep = int(keep)
        self.timeline = timeline
        self.collective = collective
        self.manager = manager
        self.events: "deque[Dict[str, Any]]" = deque(
            maxlen=int(event_capacity))
        self.digests: "deque[Dict[str, Any]]" = deque(
            maxlen=int(digest_capacity))
        self.dumps = 0
        self.last_dump: Optional[str] = None
        self.last_trigger: Optional[str] = None
        self._lock = threading.Lock()
        self._aggregator = None
        self._straggler_factor = float(straggler_factor)

    # -- sink protocol (registry.add_sink) ---------------------------------

    def write_event(self, event: Dict[str, Any]) -> None:
        self.events.append(dict(event))

    def write_snapshot(self, snap: Dict[str, Any]) -> None:
        pass                                   # rings hold events only

    def close(self) -> None:
        pass

    # -- state digests ------------------------------------------------------

    def record_digest(self, step: int, sums) -> None:
        """Retain a compact digest of one fingerprint: per-buffer
        uint32 checksum rows reduced to one xor word + per-row sums
        (``sums`` is the guard's (n_buffers, num_leaves) array). Cheap
        enough to call at every fingerprint boundary; the full
        per-leaf matrix stays with the guard's divergence record."""
        import numpy as np

        arr = np.asarray(sums, dtype=np.uint32)
        self.digests.append({
            "step": int(step),
            "xor": int(np.bitwise_xor.reduce(arr, axis=None)),
            "row_sums": [int(s) for s in
                         arr.reshape(arr.shape[0], -1)
                         .astype(np.uint64).sum(axis=1) % (1 << 32)],
        })

    # -- the dump -----------------------------------------------------------

    def _fleet_snapshot(self, collective):
        from apex_tpu.telemetry.fleet import FleetAggregator

        if self._aggregator is None or \
                self._aggregator.collective is not collective:
            self._aggregator = FleetAggregator(
                collective, straggler_factor=self._straggler_factor)
        # publishes the fleet/straggler gauges BEFORE the local
        # snapshot below is taken, so the bundle's registry carries them
        return self._aggregator.aggregate()

    def _trace_slice(self, timeline):
        from apex_tpu.telemetry import timeline as _timeline

        tl = timeline if timeline is not None else _timeline.get_timeline()
        if tl is None or not tl.enabled:
            return None
        return tl.export_trace(last_steps=self.last_steps)

    def _devmem(self):
        # the ledger's watermark when one is armed; else one direct
        # poll so every bundle carries the memory plane — values, or
        # nulls with devmem_reason (the mfu_reason contract)
        from apex_tpu.telemetry import devmem as _devmem

        try:
            led = _devmem.get_ledger()
            if led is not None:
                return led.summary()
            return {"polls": 0, "watermark_bytes": None,
                    "last": _devmem.device_memory_stats()}
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    def _compile_plane(self):
        # recent re-trace evidence from this recorder's own event ring
        # (recompiles before a crash usually ARE the story) plus the
        # tracker's totals when one is armed
        from apex_tpu.telemetry import compiled as _compiled

        try:
            recent = [dict(e) for e in self.events
                      if e.get("event") in ("recompile",
                                            "recompile_storm")]
            tracker = _compiled.get_tracker()
            return {"recent_events": recent,
                    "tracker": (tracker.summary()
                                if tracker is not None else None)}
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    def _comms(self):
        # the comms plane: per-op stats + bandwidth ledger + clock
        # offsets when the tracer is armed, else the explicit disabled
        # marker with its reason (comms.section's contract)
        from apex_tpu.telemetry import comms as _comms

        try:
            return _comms.section()
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    def _goodput(self):
        # the run ledger: full attribution table when armed, the
        # explicit disabled marker with its reason otherwise
        from apex_tpu.telemetry import goodput as _goodput

        try:
            return _goodput.section()
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    def _last_checkpoint(self):
        if self.manager is None:
            return None
        try:
            path = self.manager.latest_valid(record_events=False)
        except Exception as e:  # noqa: BLE001 — identity is best-effort
            return {"error": f"{type(e).__name__}: {e}"}
        if path is None:
            return {"path": None}
        m = _CKPT_STEP_RE.search(os.path.basename(path))
        return {"path": path,
                "step": int(m.group(1)) if m else None}

    def dump(self, trigger: str, *, error: Optional[BaseException] = None,
             fleet: bool = True, collective=None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write one postmortem bundle; returns the record path (None
        when the disk write failed — ``write_record`` never raises).

        ``fleet=True`` gathers + merges the fleet snapshot over the
        attached (or passed) collective — ONLY safe when every replica
        reaches this dump (the guard's triggers); host-local triggers
        pass ``fleet=False`` and the bundle records why the fleet view
        is absent.
        """
        from apex_tpu import records, telemetry
        from apex_tpu.resilience import faults

        with self._lock:
            col = collective if collective is not None else self.collective
            fleet_snap = None
            fleet_unavailable = None
            if not fleet:
                fleet_unavailable = ("host-local trigger: peers not at "
                                     "this dump point, no collective "
                                     "issued")
            elif col is None or col.n_replicas <= 1:
                fleet_unavailable = ("no multi-replica collective "
                                     "attached (single-host bundle)")
            else:
                try:
                    fleet_snap = self._fleet_snapshot(col)
                except Exception as e:  # noqa: BLE001
                    fleet_unavailable = f"{type(e).__name__}: {e}"
            bundle = {
                "trigger": str(trigger),
                "wall_time": time.time(),
                "pid": os.getpid(),
                "replica_id": getattr(col, "replica_id", 0),
                "n_replicas": getattr(col, "n_replicas", 1),
                "error": (f"{type(error).__name__}: {error}"
                          if error is not None else None),
                # AFTER the fleet aggregation so the straggler gauges
                # it published are in this registry snapshot
                "telemetry": telemetry.snapshot_detail(),
                "fleet": fleet_snap,
                **({"fleet_unavailable": fleet_unavailable}
                   if fleet_unavailable else {}),
                "trace": self._trace_slice(self.timeline),
                "devmem": self._devmem(),
                "compile_plane": self._compile_plane(),
                "comms": self._comms(),
                "goodput": self._goodput(),
                "recent_events": list(self.events),
                "state_digests": list(self.digests),
                "last_checkpoint": self._last_checkpoint(),
                "faults": os.environ.get(faults.ENV_KNOB) or None,
                "extra": extra,
            }
            path = records.write_record(FLIGHT_KIND, bundle)
            records.prune_records(FLIGHT_KIND, keep=self.keep)
            self.dumps += 1
            self.last_dump = path
            self.last_trigger = str(trigger)
        # after the bundle is durable: one event names it (lands in the
        # registry + sinks + this recorder's own ring for the NEXT dump)
        try:
            telemetry.registry().event("flight_dump", trigger=str(trigger),
                                       path=path)
        except Exception:  # noqa: BLE001
            pass
        return path


# ---------------------------------------------------------------------------
# The process-global recorder (what the runtime triggers notify)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[FlightRecorder] = None


def enable(**kwargs) -> FlightRecorder:
    """Arm the process-global flight recorder (kwargs =
    :class:`FlightRecorder`); attaches it to the metrics registry as an
    event sink. Re-arming replaces the previous recorder."""
    global _GLOBAL
    from apex_tpu.telemetry import metrics as _metrics

    disable()
    _GLOBAL = FlightRecorder(**kwargs)
    _metrics.registry().add_sink(_GLOBAL)
    return _GLOBAL


def disable() -> None:
    global _GLOBAL
    if _GLOBAL is not None:
        try:
            from apex_tpu.telemetry import metrics as _metrics

            _metrics.registry().remove_sink(_GLOBAL)
        except Exception:  # noqa: BLE001
            pass
        _GLOBAL = None


def get_recorder() -> Optional[FlightRecorder]:
    return _GLOBAL


def notify(trigger: str, *, recorder: Optional[FlightRecorder] = None,
           error: Optional[BaseException] = None, fleet: bool = True,
           collective=None,
           extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump a bundle on ``recorder`` (or the global one); a no-op
    returning None when nothing is armed, and NEVER raises — the
    trigger sites sit on failure paths that must stay on course."""
    rec = recorder if recorder is not None else _GLOBAL
    if rec is None:
        return None
    try:
        return rec.dump(trigger, error=error, fleet=fleet,
                        collective=collective, extra=extra)
    except Exception:  # noqa: BLE001 — the black box must not crash the run
        return None


def record_digest(step: int, sums, *,
                  recorder: Optional[FlightRecorder] = None) -> None:
    """Feed one fingerprint digest to ``recorder`` (or the global
    one); no-op when nothing is armed; never raises."""
    rec = recorder if recorder is not None else _GLOBAL
    if rec is None:
        return
    try:
        rec.record_digest(step, sums)
    except Exception:  # noqa: BLE001
        pass


__all__ = [
    "FLIGHT_KIND",
    "FlightRecorder",
    "disable",
    "enable",
    "get_recorder",
    "notify",
    "record_digest",
]

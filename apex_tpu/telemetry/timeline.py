"""Step timeline: per-phase host-loop timing + Chrome-trace export.

Answers "where did step time go" for the training host loop the way
the reference's NVTX ranges + nsight answer it for kernels (ref
apex/parallel/distributed.py:360-561 ``prof`` windows): every phase of
every step — data wait, H2D transfer, the fused step dispatch,
checkpoint writes, collectives — lands in a ring buffer as a
:class:`Span`, and :meth:`StepTimeline.export_trace` emits the whole
window as Chrome-trace / perfetto JSON (load it at ``chrome://tracing``
or ui.perfetto.dev).

This is the ONE spine the previously-duplicated host timers now ride:

- ``transformer.pipeline_parallel.Timers`` (the reference's
  ``_Timers`` port) publishes each stop() into the global timeline —
  new code should use :class:`StepTimeline` directly (see
  docs/transformer.md deprecation note);
- ``profiler.annotate`` adds a host-side span alongside its
  ``jax.named_scope`` HLO annotation when the global timeline is on;
- the fused train step takes a ``telemetry=`` timeline and times each
  dispatch under phase ``"step"`` (host-side only — the jitted
  program is byte-identical with telemetry on or off).

Overhead discipline: a **disabled** timeline records nothing and every
entry point returns immediately (the ``make_train_step`` hook returns
the *same* step object, so the disabled path is exactly the
un-instrumented path — ``tools/check_telemetry.sh`` holds this to
<1%). An enabled one costs one ``perf_counter`` pair + a deque append
per span. ``sync=True`` additionally blocks on the step's outputs
before stopping the clock — that's the wall/device-sync distinction:
without it the "step" phase measures dispatch, with it device
execution (and kills async pipelining, so it's for profiling windows,
not production loops).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, NamedTuple, Optional

# canonical phase names the instrumented layers use; arbitrary names
# are fine — these exist so dashboards agree on spelling
PHASES = ("data_wait", "h2d", "step", "checkpoint", "collective")


class Span(NamedTuple):
    """One timed region: ``t0`` is absolute ``perf_counter`` seconds,
    ``dur`` seconds, ``step`` the host-loop step index it happened in
    (-1 = outside any step scope); ``args`` are extra JSON-able
    key/values the trace export folds into the event (the comms plane
    attributes payload/wire bytes to its ``collective:*`` spans)."""

    name: str
    t0: float
    dur: float
    step: int
    category: str
    args: Optional[Dict[str, Any]] = None


class StepTimeline:
    """Ring-buffered span recorder for the training host loop.

    ``capacity`` bounds memory: the newest ``capacity`` spans are kept,
    older ones fall off (``summary()`` reports how many were dropped).
    All methods are thread-safe; clock is ``time.perf_counter``.
    """

    def __init__(self, capacity: int = 4096, *, enabled: bool = True,
                 sync: bool = False,
                 clock: Callable[[], float] = time.perf_counter):
        self.enabled = bool(enabled)
        self.sync = bool(sync)
        self.capacity = int(capacity)
        self.clock = clock
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=self.capacity)
        self._recorded = 0
        self._dropped_dur = 0.0
        self._dropped_published = 0
        self._origin = clock()
        self._step = -1
        self._step_t0: Optional[float] = None

    # -- recording ---------------------------------------------------------

    def record_span(self, name: str, t0: float, dur: float, *,
                    category: str = "phase",
                    step: Optional[int] = None,
                    args: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            span = Span(
                str(name), float(t0), float(dur),
                self._step if step is None else int(step), str(category),
                dict(args) if args else None)
            if (self._spans.maxlen is not None
                    and len(self._spans) == self._spans.maxlen):
                # ring wraparound: the evicted span's time would vanish
                # from any later pull-based accounting — total it so
                # summary()/publish() can surface the loss (a zero-
                # capacity ring evicts the incoming span itself)
                self._dropped_dur += (self._spans[0].dur
                                      if self._spans else span.dur)
            self._spans.append(span)
            self._recorded += 1
        obs = _SPAN_OBSERVER
        if obs is not None:
            try:
                obs(span)
            except Exception:  # noqa: BLE001 — observers never take down the loop
                pass

    @contextlib.contextmanager
    def phase(self, name: str, *, sync_on: Any = None,
              category: str = "phase"):
        """``with tl.phase("h2d"): ...`` — record the block as a span.
        ``sync_on`` blocks on a jax value before the clock stops, so
        the span covers device completion, not just dispatch."""
        if not self.enabled:
            yield
            return
        t0 = self.clock()
        try:
            yield
        finally:
            if sync_on is not None:
                import jax

                jax.block_until_ready(sync_on)
            self.record_span(name, t0, self.clock() - t0,
                             category=category)

    # -- step scopes -------------------------------------------------------

    def begin_step(self) -> int:
        """Open a host-loop step; spans recorded until ``end_step``
        carry its index. Returns the step index."""
        if not self.enabled:
            return self._step
        with self._lock:
            self._step += 1
            self._step_t0 = self.clock()
        return self._step

    def end_step(self) -> None:
        """Close the open step, recording its whole wall span as
        ``host_step`` (category ``step``)."""
        if not self.enabled:
            return
        with self._lock:
            t0, self._step_t0 = self._step_t0, None
        if t0 is not None:
            self.record_span("host_step", t0, self.clock() - t0,
                             category="step")

    @contextlib.contextmanager
    def step_scope(self):
        """``with tl.step_scope(): ...`` — begin_step/end_step pair."""
        self.begin_step()
        try:
            yield self._step
        finally:
            self.end_step()

    def wrap_iter(self, batches: Iterable,
                  name: str = "data_wait") -> Iterable:
        """Time each ``next()`` of ``batches`` as a ``data_wait`` span
        — wrap your (Prefetch)loader so stalls show in the timeline."""
        it = iter(batches)
        while True:
            t0 = self.clock()
            try:
                b = next(it)
            except StopIteration:
                return
            self.record_span(name, t0, self.clock() - t0)
            yield b

    # -- reading -----------------------------------------------------------

    @property
    def origin(self) -> float:
        """The local clock value ``export_trace``'s ``ts=0`` maps to —
        what ``fleet.export_fleet_trace`` shifts against when it moves
        every host's events onto the shared barrier instant."""
        with self._lock:
            return self._origin

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    @property
    def dropped_seconds(self) -> float:
        """Total duration of spans evicted by ring wraparound — the
        time a pull-based consumer can no longer see (the goodput
        ledger surfaces it as ``timeline_dropped_span_seconds``)."""
        with self._lock:
            return self._dropped_dur

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._recorded = 0
            self._dropped_dur = 0.0
            self._dropped_published = 0
            self._step = -1
            self._step_t0 = None
            self._origin = self.clock()

    def summary(self) -> Dict[str, Any]:
        """Per-phase aggregate over the retained window: count,
        total/mean/max/last ms — the JSON-able phase breakdown bench
        records carry."""
        spans = self.spans()
        phases: Dict[str, Dict[str, float]] = {}
        for s in spans:
            p = phases.setdefault(s.name, {
                "count": 0, "total_ms": 0.0, "max_ms": 0.0, "last_ms": 0.0})
            ms = s.dur * 1e3
            p["count"] += 1
            p["total_ms"] += ms
            p["max_ms"] = max(p["max_ms"], ms)
            p["last_ms"] = ms
        for p in phases.values():
            p["mean_ms"] = p["total_ms"] / p["count"]
            for k in ("total_ms", "mean_ms", "max_ms", "last_ms"):
                p[k] = round(p[k], 4)
        with self._lock:
            dropped = self._recorded - len(spans)
            dropped_s = self._dropped_dur
            steps = self._step + 1
        return {"enabled": self.enabled, "steps": steps,
                "spans": len(spans), "dropped_spans": dropped,
                "dropped_span_seconds": round(dropped_s, 6),
                "phases": phases}

    def export_trace(self, path: Optional[str] = None, *,
                     last_steps: Optional[int] = None) -> Dict[str, Any]:
        """The retained window as Chrome-trace JSON (the "JSON Array
        Format" chrome://tracing and ui.perfetto.dev load): complete
        ``"ph": "X"`` events with microsecond ``ts``/``dur`` relative
        to the timeline origin, one tid per category. Writes to
        ``path`` when given; always returns the dict.

        ``last_steps=N`` slices to the newest ``N`` host-loop steps —
        the flight recorder's bundle window. Spans recorded outside any
        step scope (``step == -1``) are kept: they cannot be dated by
        step, and the ring already bounds them."""
        spans = self.spans()
        if last_steps is not None and spans:
            newest = max(s.step for s in spans)
            cutoff = newest - int(last_steps) + 1
            spans = [s for s in spans if s.step < 0 or s.step >= cutoff]
        pid = os.getpid()
        tids: Dict[str, int] = {}
        events = []
        for s in spans:
            tid = tids.setdefault(s.category, len(tids))
            ev_args: Dict[str, Any] = {"step": s.step}
            if s.args:
                ev_args.update(s.args)
            events.append({
                "name": s.name,
                "cat": s.category,
                "ph": "X",
                "ts": round((s.t0 - self._origin) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": ev_args,
            })
        # thread-name metadata makes the perfetto track labels readable
        for cat, tid in tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": cat},
            })
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            tmp = f"{path}.tmp-{pid}"
            with open(tmp, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
        return trace

    def publish(self, registry=None) -> Dict[str, Any]:
        """Push the per-phase means into ``timeline_phase_ms`` gauges
        on the metrics registry; returns the summary."""
        from apex_tpu.telemetry import metrics as _metrics

        reg = registry if registry is not None else _metrics.registry()
        summ = self.summary()
        g = reg.gauge("timeline_phase_ms",
                      "mean host-loop phase duration over the window")
        for name, p in summ["phases"].items():
            g.set(p["mean_ms"], phase=name)
        # ring-wraparound visibility: count evictions lazily here (a
        # per-span counter inc would violate the hot-path budget)
        with self._lock:
            delta = (self._recorded - len(self._spans)
                     - self._dropped_published)
            if delta > 0:
                self._dropped_published += delta
        if delta > 0:
            reg.counter(
                "timeline_dropped_spans_total",
                "spans evicted by timeline ring wraparound").inc(delta)
        return summ


# ---------------------------------------------------------------------------
# The process-global timeline (the spine Timers/annotate/loaders ride)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[StepTimeline] = None
_ENV = "APEX_TPU_TELEMETRY"

# one push-based listener every StepTimeline (global AND private
# instances, e.g. the train step's) feeds each recorded span through —
# how the goodput ledger attributes time without polling the ring.
# Checked as a single module-global read per span; None means nobody
# is listening.
_SPAN_OBSERVER: Optional[Callable[[Span], None]] = None


def set_span_observer(cb: Optional[Callable[[Span], None]]) -> None:
    """Install (or clear, with None) the process-wide span observer.
    The callback runs on the recording thread for every span of every
    enabled timeline; exceptions are swallowed — it must be cheap."""
    global _SPAN_OBSERVER
    _SPAN_OBSERVER = cb


def _env_enabled() -> bool:
    return os.environ.get(_ENV, "").strip().lower() in (
        "1", "true", "yes", "on")


def get_timeline() -> StepTimeline:
    """The process-global timeline. Created on first use — DISABLED
    unless ``APEX_TPU_TELEMETRY`` is truthy or :func:`enable` ran."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = StepTimeline(enabled=_env_enabled())
    return _GLOBAL


def enable(capacity: int = 4096, *, sync: bool = False) -> StepTimeline:
    """Turn the global timeline on (fresh ring buffer); returns it."""
    global _GLOBAL
    _GLOBAL = StepTimeline(capacity=capacity, enabled=True, sync=sync)
    return _GLOBAL


def disable() -> None:
    global _GLOBAL
    _GLOBAL = StepTimeline(enabled=False)


def global_enabled() -> bool:
    """Cheap hot-path check: is anything listening?"""
    tl = _GLOBAL
    if tl is None:
        return _env_enabled() and get_timeline().enabled
    return tl.enabled


def record_global_span(name: str, t0: float, dur: float, *,
                       category: str = "phase",
                       args: Optional[Dict[str, Any]] = None) -> None:
    """Record into the global timeline iff it is enabled (no-op —
    not even a timeline construction — otherwise)."""
    tl = _GLOBAL
    if tl is not None and tl.enabled:
        tl.record_span(name, t0, dur, category=category, args=args)
    elif tl is None and _env_enabled():
        get_timeline().record_span(name, t0, dur, category=category,
                                   args=args)


__all__ = [
    "PHASES",
    "Span",
    "StepTimeline",
    "disable",
    "enable",
    "get_timeline",
    "global_enabled",
    "record_global_span",
    "set_span_observer",
]

"""Compile-plane observability: compile timing, re-trace detection,
and recompile-storm escalation.

PRs 4-5 lit up the host loop and the fleet; the COMPILER plane stayed
dark — nothing said how long XLA compiles took, or that a training
loop had quietly fallen into a re-trace storm (a shape-polymorphic
input or a drifting static option recompiling the train step every few
steps, each one a multi-second stall that looks like "the chip got
slow"). This module is that plane:

- **Compile timing** rides jax's own ``jax.monitoring`` duration
  events: :func:`enable` registers a listener for XLA backend-compile
  durations, so EVERY real compile in the process — the fused train
  step's per-layout specialization, a guard fingerprint program, a
  Pallas engine sweep — publishes ``compile_count{fn=}`` /
  ``compile_ms{fn=}`` / a ``compile_seconds{fn=}`` histogram into the
  global registry and a ``"compile"`` span into the global timeline.
  Attribution comes from :func:`label` scopes the instrumented entry
  points (``optimizers.train_step``, ``multi_tensor.engine``,
  ``resilience.guard``, ``telemetry.cost``) push around their
  dispatches; unlabeled compiles land under ``fn="unattributed"``.
- **Re-trace detection**: :meth:`CompileTracker.observe` registers the
  abstract signature (static options + aval summary) each jit entry
  point is about to compile under. The first signature of a fn is a
  ``compile``; a signature already seen is a ``hit`` and publishes
  NOTHING (cache hits are free, and must read as free); a NEW
  signature on a previously-compiled fn is a **recompile** — a
  ``recompile`` event carrying the structured signature diff
  (changed/added/removed keys, old -> new) so the log names exactly
  which static option or shape moved.
- **Storm escalation**: more than ``storm_threshold`` recompiles of
  one fn within ``storm_window`` steps emits one ``recompile_storm``
  event (and resets the count, so a persisting storm escalates once
  per threshold-full, not once per recompile). Knobs:
  ``APEX_TPU_RECOMPILE_STORM_N`` (default 3) and
  ``APEX_TPU_RECOMPILE_STORM_WINDOW`` (default 100 steps).

Everything is host-side and disarmed by default: with no tracker
enabled, :func:`observe` is one module-global read and :func:`label`
returns a shared null context — the instrumented entry points only
reach them on their COLD paths (a new layout, a fingerprint boundary),
never per hot-loop dispatch, and the ``disabled is step`` /
<1%-overhead contracts of docs/observability.md hold unchanged
(tools/check_observability.sh re-asserts both with the tracker armed).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

_STORM_N_ENV = "APEX_TPU_RECOMPILE_STORM_N"
_STORM_WINDOW_ENV = "APEX_TPU_RECOMPILE_STORM_WINDOW"
_DEFAULT_STORM_N = 3
_DEFAULT_STORM_WINDOW = 100

# the jax.monitoring duration key fired once per actual XLA backend
# compile (trace/lowering have their own keys; the backend compile is
# the multi-second one worth a span)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_LOCAL = threading.local()
_NULL_CM = contextlib.nullcontext()


def _label_stack():
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
    return st


def current_label() -> Optional[str]:
    """The innermost :func:`label` scope on this thread, or None."""
    st = getattr(_LOCAL, "stack", None)
    return st[-1] if st else None


@contextlib.contextmanager
def _labeled(fn: str):
    st = _label_stack()
    st.append(str(fn))
    try:
        yield
    finally:
        st.pop()


def label(fn: str):
    """Attribution scope: backend compiles fired inside the block are
    credited to ``fn`` by the monitoring bridge. A shared null context
    (no allocation, no state) when no tracker is armed — entry points
    may wrap their cold-path dispatches unconditionally."""
    if _TRACKER is None:
        return _NULL_CM
    return _labeled(fn)


def signature_diff(old: Dict[str, Any],
                   new: Dict[str, Any]) -> Dict[str, Any]:
    """Structured top-level diff between two abstract signatures:
    ``{"changed": {k: [old, new]}, "added": {...}, "removed": {...}}``
    with empty sections dropped — what a ``recompile`` event carries so
    the log names exactly which static option or shape moved."""
    changed, added, removed = {}, {}, {}
    for k in sorted(set(old) | set(new)):
        if k not in old:
            added[k] = new[k]
        elif k not in new:
            removed[k] = old[k]
        elif old[k] != new[k]:
            changed[k] = [old[k], new[k]]
    out: Dict[str, Any] = {}
    if changed:
        out["changed"] = changed
    if added:
        out["added"] = added
    if removed:
        out["removed"] = removed
    return out


def abstract_signature(tree=None, **static) -> Dict[str, Any]:
    """A JSON-able abstract signature: the ``static`` kwargs verbatim
    plus, when a pytree is given, a compact aval summary (leaf count,
    total elements, digest of every leaf's shape/dtype string) — big
    trees never inline thousands of shapes into an event."""
    sig: Dict[str, Any] = dict(static)
    if tree is not None:
        import jax

        leaves = jax.tree.leaves(tree)
        avals = [f"{getattr(l, 'dtype', type(l).__name__)}"
                 f"[{','.join(str(d) for d in getattr(l, 'shape', ()))}]"
                 for l in leaves]
        sig["leaves"] = len(leaves)
        sig["total_elements"] = int(sum(
            int(getattr(l, "size", 1)) for l in leaves))
        sig["aval_digest"] = hashlib.sha256(
            "|".join(avals).encode()).hexdigest()[:12]
    return sig


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class CompileTracker:
    """Signature registry + recompile/storm detection + the metric
    surface the monitoring bridge publishes through.

    - ``storm_threshold`` (N) / ``storm_window`` (M): escalate past N
      recompiles of one fn within M steps. Step indices come from the
      explicit ``step=`` argument, else the global timeline's current
      step, else an internal observation counter.
    - ``registry``: defaults to the process-global metrics registry.
    """

    def __init__(self, registry=None, *, storm_threshold: Optional[int] = None,
                 storm_window: Optional[int] = None):
        from apex_tpu.telemetry import metrics as _metrics

        self.registry = (registry if registry is not None
                         else _metrics.registry())
        self.storm_threshold = int(
            storm_threshold if storm_threshold is not None
            else _env_int(_STORM_N_ENV, _DEFAULT_STORM_N))
        self.storm_window = int(
            storm_window if storm_window is not None
            else _env_int(_STORM_WINDOW_ENV, _DEFAULT_STORM_WINDOW))
        self._lock = threading.Lock()
        self._signatures: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._last_key: Dict[str, str] = {}
        self._recompile_steps: Dict[str, deque] = {}
        self._observations = 0
        self.compiles = 0
        self.recompiles = 0
        self.storms = 0

    # -- steps ---------------------------------------------------------------

    def _step_now(self, step: Optional[int]) -> int:
        if step is not None:
            return int(step)
        from apex_tpu.telemetry import timeline as _timeline

        tl = _timeline._GLOBAL          # never CREATE the global here
        if tl is not None and tl.enabled and tl._step >= 0:
            return tl._step
        return self._observations

    # -- signature observation ----------------------------------------------

    def observe(self, fn: str, signature: Dict[str, Any], *,
                step: Optional[int] = None) -> str:
        """Register that ``fn`` is being dispatched under ``signature``.

        Returns ``"hit"`` (seen before — publishes NOTHING),
        ``"compile"`` (first signature of this fn), or ``"recompile"``
        (new signature on a previously-compiled fn: ``recompile`` event
        with the signature diff, ``recompile_count{fn=}`` bump, and a
        ``recompile_storm`` escalation past the threshold).
        """
        fn = str(fn)
        key = json.dumps(signature, sort_keys=True, default=str)
        with self._lock:
            self._observations += 1
            sigs = self._signatures.setdefault(fn, {})
            if key in sigs:
                return "hit"
            prev_key = self._last_key.get(fn)
            prev_sig = sigs.get(prev_key) if prev_key is not None else None
            sigs[key] = dict(signature)
            self._last_key[fn] = key
            now = self._step_now(step)
        self.registry.counter(
            "compiled_signatures",
            "distinct (fn, abstract signature) pairs observed by the "
            "compile tracker").inc(fn=fn)
        if prev_sig is None:
            self.compiles += 1
            return "compile"
        self.recompiles += 1
        diff = signature_diff(prev_sig, signature)
        self.registry.counter(
            "recompile_count",
            "re-traces: a NEW abstract signature on a previously-"
            "compiled fn").inc(fn=fn)
        self.registry.event("recompile", fn=fn, step=now,
                            signature_diff=diff,
                            signatures=len(self._signatures[fn]))
        with self._lock:
            ring = self._recompile_steps.setdefault(fn, deque())
            ring.append(now)
            while ring and ring[0] <= now - self.storm_window:
                ring.popleft()
            storm = len(ring) >= self.storm_threshold
            count = len(ring)
            if storm:
                # escalate once per threshold-full: a persisting storm
                # re-escalates after N MORE recompiles, not per recompile
                ring.clear()
        if storm:
            self.storms += 1
            self.registry.counter(
                "recompile_storms",
                "recompile-storm escalations (> threshold recompiles "
                "of one fn inside the window)").inc(fn=fn)
            self.registry.event("recompile_storm", fn=fn, step=now,
                                count=count,
                                threshold=self.storm_threshold,
                                window_steps=self.storm_window)
        return "recompile"

    # -- compile durations (monitoring bridge) -------------------------------

    def record_compile(self, fn: str, seconds: float) -> None:
        """One actual XLA backend compile: ``compile_count{fn=}``,
        ``compile_ms{fn=}`` (most recent), the ``compile_seconds{fn=}``
        histogram, and a ``"compile"`` span into the global timeline
        (when it is on)."""
        seconds = float(seconds)
        self.registry.counter(
            "compile_count", "XLA backend compiles observed").inc(fn=fn)
        self.registry.gauge(
            "compile_ms",
            "duration of the most recent XLA backend compile").set(
            seconds * 1e3, fn=fn)
        self.registry.histogram(
            "compile_seconds", "XLA backend compile durations").observe(
            seconds, fn=fn)
        from apex_tpu.telemetry.timeline import record_global_span

        record_global_span("compile", time.perf_counter() - seconds,
                           seconds, category="compile")

    def summary(self) -> Dict[str, Any]:
        """Compact JSON-able state: per-fn signature counts plus the
        compile/recompile/storm totals — what dashboards and the
        flight recorder's ``compile_plane`` block read."""
        with self._lock:
            per_fn = {fn: len(sigs)
                      for fn, sigs in self._signatures.items()}
        return {"signatures": per_fn, "compiles": self.compiles,
                "recompiles": self.recompiles, "storms": self.storms,
                "storm_threshold": self.storm_threshold,
                "storm_window": self.storm_window}


# ---------------------------------------------------------------------------
# The process-global tracker + jax.monitoring bridge
# ---------------------------------------------------------------------------

_TRACKER: Optional[CompileTracker] = None
_LISTENER = None


def _on_duration(name: str, secs: float, **kw) -> None:
    t = _TRACKER
    if t is None or name != BACKEND_COMPILE_EVENT:
        return
    try:
        t.record_compile(current_label() or "unattributed", secs)
    except Exception:  # noqa: BLE001 — observability must not kill a compile
        pass


def _register_bridge() -> None:
    global _LISTENER
    if _LISTENER is not None:
        return
    try:
        from jax import monitoring as _monitoring

        _monitoring.register_event_duration_secs_listener(_on_duration)
        _LISTENER = _on_duration
    except Exception:  # noqa: BLE001 — no monitoring API: signatures still work
        _LISTENER = None


def _unregister_bridge() -> None:
    global _LISTENER
    if _LISTENER is None:
        return
    try:
        from jax._src import monitoring as _monitoring

        _monitoring._unregister_event_duration_listener_by_callback(
            _LISTENER)
        _LISTENER = None
    except Exception:  # noqa: BLE001 — listener self-disarms on _TRACKER None
        _LISTENER = None


def enable(**kwargs) -> CompileTracker:
    """Arm the process-global compile tracker (kwargs =
    :class:`CompileTracker`) and register the jax.monitoring bridge.
    Re-arming replaces the previous tracker (fresh signature state)."""
    global _TRACKER
    disable()
    _TRACKER = CompileTracker(**kwargs)
    _register_bridge()
    return _TRACKER


def disable() -> None:
    global _TRACKER
    _TRACKER = None
    _unregister_bridge()


def get_tracker() -> Optional[CompileTracker]:
    return _TRACKER


def observe(fn: str, signature: Dict[str, Any], *,
            step: Optional[int] = None) -> str:
    """Observe on the global tracker; ``"disabled"`` (and nothing else
    — not even an exception) when no tracker is armed."""
    t = _TRACKER
    if t is None:
        return "disabled"
    try:
        return t.observe(fn, signature, step=step)
    except Exception:  # noqa: BLE001
        return "error"


__all__ = [
    "BACKEND_COMPILE_EVENT",
    "CompileTracker",
    "abstract_signature",
    "current_label",
    "disable",
    "enable",
    "get_tracker",
    "label",
    "observe",
    "signature_diff",
]

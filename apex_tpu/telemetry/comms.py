"""Comms plane: collective tracing + the wire bandwidth ledger.

Every cross-host byte in this repo moves through the guard's 4-method
:class:`~apex_tpu.resilience.guard.Collective` abstraction (all_gather
/ broadcast_from / barrier / agree_any — fingerprint gathers, majority
repairs, quorum barriers, elastic range fetches, fleet snapshot
gathers). Until this module, none of it was observable: ROADMAP item
1's mesh planner needs MEASURED comms costs as its objective, and a
fleet that is quietly gating on one slow interconnect has no metric to
say so. This module is the wire's analog of the compile/memory plane:

- :func:`instrument` wraps any ``Collective`` in an
  :class:`InstrumentedCollective` that times every op and publishes
  ``collective_ops{op=,impl=}`` counters, ``collective_bytes{op=}`` /
  ``collective_ms{op=}`` histograms, and per-op
  ``collective:<op>`` spans into the (global or per-tracer)
  :class:`~apex_tpu.telemetry.timeline.StepTimeline`. **Disabled
  means untouched**: with no tracer armed, ``instrument(col) is col``
  — the raw object, zero overhead, the ``make_train_step``
  disabled-is-step discipline applied to the wire.
- :class:`CommsTracer` keeps the **bandwidth ledger** — the PR-6
  measured-vs-analytic HBM-ledger discipline applied to the wire.
  Per op it accumulates payload bytes (what the caller handed over),
  analytic *wire* bytes (what the op must move per host given
  ``n_replicas``: an all_gather delivers ``payload x n``, a broadcast
  ``payload``, agree_any one int32 gathered), and wall ms — so
  ``measured_mbps`` next to the payload-size histogram says whether an
  op is latency-bound (tiny fingerprint gathers) or bandwidth-bound
  (elastic range fetches). With ``link_gbps`` configured the ledger
  also derives ``analytic_ms`` and the measured/analytic ratio; with
  no link figure those fields are null WITH a reason (the
  mfu_reason contract — never silently absent).
- A ``collective_slow`` **escalation event** fires when one op's wall
  time exceeds ``slow_factor`` x its own EWMA (after ``min_samples``
  warm samples), latched per episode so a persistently slow
  interconnect raises one event per excursion, not one per op. The
  EWMA only folds in healthy samples — a slow episode cannot drag its
  own reference up and silence itself.

Fault drills (resilience/faults.py): every traced op calls
``faults.check("collective")`` (``io:collective=<idx>`` raises out of
the op), ``collective_slow=<ms>`` injects a per-op delay, and
``collective_payload_corrupt=<idx>`` flips one byte of a gathered
payload — the deterministic drills behind
``tools/check_observability.sh``'s comms smoke.

Wiring: ``parallel.multiproc.process_collective()`` and the elastic
restore's range-fetch path route their collectives through
:func:`instrument`, so arming the tracer (:func:`enable`, or the
``APEX_TPU_COMMS`` env knob) instruments every runtime-built
collective with no call-site changes; flight bundles carry
:func:`section`; ``fleet.estimate_clock_offsets`` deposits its offsets
here so one ``summary()`` holds the whole comms story.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from apex_tpu.telemetry import metrics as _metrics
from apex_tpu.telemetry import timeline as _timeline

# the four ops of the Collective contract, in escalation-report order
COLLECTIVE_OPS = ("all_gather", "broadcast_from", "barrier", "agree_any")


def wire_bytes(op: str, payload_bytes: int, n_replicas: int) -> int:
    """Analytic bytes ONE host moves for ``op`` on a ``n_replicas``
    replica set — the ledger's "analytic" column (what the op must
    transfer, independent of how fast the transport did it)."""
    n = max(int(n_replicas), 1)
    if op == "all_gather":
        return int(payload_bytes) * n        # every replica's copy lands
    if op == "agree_any":
        return 4 * n                         # one int32 gathered
    if op == "barrier":
        return 0
    if op == "ppermute":
        return int(payload_bytes)            # ring rotation: one hop out
    if op == "all_to_all":
        # MoE dispatch/combine: each host keeps its own 1/n shard and
        # ships the other (n-1)/n of its payload, per direction
        return int(payload_bytes) * (n - 1) // n
    return int(payload_bytes)                # broadcast_from: src's copy


class CommsTracer:
    """Per-op accounting + escalation state behind instrumented
    collectives. One tracer per registry: the process-global one
    (:func:`enable`) for real runs, private ones for the threaded
    LocalCollective sims (each simulated host passes its own registry,
    the same pattern ``gather_snapshots`` uses for snapshots)."""

    def __init__(self, *, registry=None, timeline=None,
                 slow_factor: float = 4.0, ewma_alpha: float = 0.25,
                 min_samples: int = 5, link_gbps: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter):
        if slow_factor <= 1.0:
            raise ValueError(
                f"slow_factor must be > 1, got {slow_factor}")
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.registry = (registry if registry is not None
                         else _metrics.registry())
        self.timeline = timeline          # None -> the global timeline
        self.slow_factor = float(slow_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.min_samples = int(min_samples)
        self.link_gbps = link_gbps
        self.clock = clock
        self._lock = threading.Lock()
        self._ops: Dict[str, Dict[str, Any]] = {}
        self.clock_offsets: Optional[Dict[str, Any]] = None
        reg = self.registry
        self._ops_counter = reg.counter(
            "collective_ops", "traced collective ops by op and impl")
        self._bytes_hist = reg.histogram(
            "collective_bytes", "payload bytes per traced collective op",
            buckets=_metrics.PAYLOAD_BYTES_BUCKETS)
        self._ms_hist = reg.histogram(
            "collective_ms", "wall milliseconds per traced collective op",
            buckets=_metrics.LATENCY_MS_BUCKETS)
        self._slow_counter = reg.counter(
            "collective_slow_total",
            "collective_slow escalation events by op")

    # -- recording ---------------------------------------------------------

    def _new_op(self) -> Dict[str, Any]:
        return {"calls": 0, "payload_bytes": 0, "wire_bytes": 0,
                "wall_ms": 0.0, "last_ms": 0.0, "max_ms": 0.0,
                "ewma_ms": None, "slow_latched": False, "slow_events": 0}

    def record(self, op: str, impl: str, payload_bytes: int,
               wire: int, t0: float, dur_s: float) -> None:
        """Account one completed op (the instrumented wrapper's exit
        path; tests drive it directly with synthetic durations)."""
        ms = dur_s * 1e3
        self._ops_counter.inc(op=op, impl=impl)
        if payload_bytes:
            self._bytes_hist.observe(payload_bytes, op=op)
        self._ms_hist.observe(ms, op=op)
        span_args = {"payload_bytes": int(payload_bytes),
                     "wire_bytes": int(wire), "impl": impl}
        if self.timeline is not None:
            self.timeline.record_span(f"collective:{op}", t0, dur_s,
                                      category="collective",
                                      args=span_args)
        else:
            _timeline.record_global_span(f"collective:{op}", t0, dur_s,
                                         category="collective",
                                         args=span_args)
        escalate_from = None
        with self._lock:
            st = self._ops.setdefault(op, self._new_op())
            st["calls"] += 1
            st["payload_bytes"] += int(payload_bytes)
            st["wire_bytes"] += int(wire)
            st["wall_ms"] += ms
            st["last_ms"] = ms
            st["max_ms"] = max(st["max_ms"], ms)
            prev = st["ewma_ms"]
            warmed = prev is not None and st["calls"] > self.min_samples
            if warmed and ms > self.slow_factor * prev:
                # slow sample: the reference EWMA stays put (a slow
                # episode must not raise its own bar) and the episode
                # latch means one event per excursion
                if not st["slow_latched"]:
                    st["slow_latched"] = True
                    st["slow_events"] += 1
                    escalate_from = prev
            else:
                st["ewma_ms"] = (ms if prev is None else
                                 self.ewma_alpha * ms
                                 + (1.0 - self.ewma_alpha) * prev)
                st["slow_latched"] = False
        if escalate_from is not None:
            self._slow_counter.inc(op=op)
            self.registry.event(
                "collective_slow", op=op, impl=impl,
                ms=round(ms, 4), ewma_ms=round(escalate_from, 4),
                factor=self.slow_factor,
                payload_bytes=int(payload_bytes))

    def note_clock_offsets(self, offsets: Dict[str, Any]) -> None:
        """Deposit the latest ``fleet.estimate_clock_offsets`` result
        so bundles carry offsets next to the per-op stats."""
        with self._lock:
            self.clock_offsets = {
                k: offsets.get(k) for k in
                ("offsets_ms", "spread_ms", "rounds", "rtt_ms")}

    # -- reading -----------------------------------------------------------

    def op_stats(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {op: dict(st) for op, st in self._ops.items()}

    def ledger(self) -> List[Dict[str, Any]]:
        """The measured-vs-analytic bandwidth ledger, one row per op:
        measured MB/s from accumulated wire bytes over wall ms; the
        analytic side (expected ms at ``link_gbps``, measured/analytic
        ratio) is a value or null with ``analytic_reason``."""
        rows: List[Dict[str, Any]] = []
        for op, st in sorted(self.op_stats().items()):
            wall_ms = st["wall_ms"]
            row: Dict[str, Any] = {
                "op": op,
                "calls": st["calls"],
                "payload_bytes": st["payload_bytes"],
                "wire_bytes": st["wire_bytes"],
                "wall_ms": round(wall_ms, 4),
                "mean_ms": round(wall_ms / st["calls"], 4),
                "ewma_ms": (round(st["ewma_ms"], 4)
                            if st["ewma_ms"] is not None else None),
                "measured_mbps": (
                    round(st["wire_bytes"] / 1e6 / (wall_ms / 1e3), 4)
                    if wall_ms > 0 and st["wire_bytes"] else None),
                "slow_events": st["slow_events"],
            }
            if self.link_gbps:
                analytic_ms = (st["wire_bytes"] * 8.0
                               / (self.link_gbps * 1e9) * 1e3)
                row["analytic_ms"] = round(analytic_ms, 4)
                row["measured_over_analytic"] = (
                    round(wall_ms / analytic_ms, 4)
                    if analytic_ms > 0 else None)
            else:
                row["analytic_ms"] = None
                row["analytic_reason"] = (
                    "no link_gbps configured (CommsTracer(link_gbps=...)"
                    " enables the analytic column)")
            rows.append(row)
        return rows

    def summary(self) -> Dict[str, Any]:
        """The JSON-able comms story: per-op stats, the ledger, the
        latest clock offsets (or null), and the escalation config."""
        with self._lock:
            offsets = (dict(self.clock_offsets)
                       if self.clock_offsets is not None else None)
        return {
            "ops": self.op_stats(),
            "ledger": self.ledger(),
            "clock_offsets": offsets,
            "slow_factor": self.slow_factor,
            "ewma_alpha": self.ewma_alpha,
            "min_samples": self.min_samples,
            "link_gbps": self.link_gbps,
        }


def _flip_first_byte(out):
    """One flipped byte in a gathered payload — the injected
    silent-corruption drill (``collective_payload_corrupt``)."""
    if isinstance(out, (list, tuple)):
        if not out:
            return list(out)
        return [_flip_first_byte(out[0])] + [np.asarray(a)
                                             for a in out[1:]]
    a = np.array(out, copy=True)
    if a.nbytes:
        a.view(np.uint8).reshape(-1)[0] ^= 0xFF
    return a


class InstrumentedCollective:
    """A ``Collective`` wrapper that times and accounts every op.

    Duck-typed to the guard's 4-method contract (plus ``n_replicas``
    / ``replica_id`` / ``impl_name``), delegating each op to the
    wrapped ``inner`` — results are byte-identical to the raw
    collective (fault clauses aside). Never constructed on the
    disabled path: :func:`instrument` returns the raw object then.
    """

    def __init__(self, inner, tracer: CommsTracer):
        from apex_tpu.resilience import faults as _faults

        self.inner = inner
        self.tracer = tracer
        self._faults = _faults
        self._impl = (inner.impl_name() if hasattr(inner, "impl_name")
                      else type(inner).__name__)

    @property
    def n_replicas(self) -> int:
        return self.inner.n_replicas

    @property
    def replica_id(self) -> int:
        return self.inner.replica_id

    def impl_name(self) -> str:
        return self._impl

    def _traced(self, op: str, payload_bytes: int, fn,
                corruptible: bool = False):
        f = self._faults
        f.check("collective")                    # io:collective=<idx>
        delay = f.collective_delay_s()
        t0 = self.tracer.clock()
        out = fn()
        if delay > 0.0:
            time.sleep(delay)
        dur = self.tracer.clock() - t0
        if corruptible and f.should_corrupt_collective():
            out = _flip_first_byte(out)
            self.tracer.registry.event(
                "collective_payload_corrupt", op=op, impl=self._impl,
                payload_bytes=int(payload_bytes))
        self.tracer.record(op, self._impl, payload_bytes,
                           wire_bytes(op, payload_bytes, self.n_replicas),
                           t0, dur)
        return out

    def all_gather(self, arr):
        arr = np.asarray(arr)
        return self._traced("all_gather", arr.nbytes,
                            lambda: self.inner.all_gather(arr),
                            corruptible=True)

    def broadcast_from(self, src, arrays):
        arrs = [np.asarray(a) for a in arrays]
        nbytes = sum(a.nbytes for a in arrs)
        return self._traced("broadcast_from", nbytes,
                            lambda: self.inner.broadcast_from(src, arrs),
                            corruptible=True)

    def barrier(self) -> None:
        self._traced("barrier", 0, lambda: self.inner.barrier())

    def agree_any(self, flag: bool) -> bool:
        # delegate to the inner impl (whose agree_any rides its own
        # UNtraced all_gather) so one logical op counts once, as itself
        return self._traced("agree_any", 4,
                            lambda: self.inner.agree_any(flag))


# ---------------------------------------------------------------------------
# The process-global tracer (what instrument() consults)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[CommsTracer] = None
_ENV = "APEX_TPU_COMMS"


def _env_enabled() -> bool:
    return os.environ.get(_ENV, "").strip().lower() in (
        "1", "true", "yes", "on")


def enable(**kwargs) -> CommsTracer:
    """Arm the process-global comms tracer (kwargs =
    :class:`CommsTracer`); collectives built AFTER this (or re-passed
    through :func:`instrument`) are traced. Returns the tracer."""
    global _GLOBAL
    _GLOBAL = CommsTracer(**kwargs)
    return _GLOBAL


def disable() -> None:
    """Disarm: :func:`instrument` becomes the identity again (already-
    wrapped collectives keep their tracer — rebuild them to shed it)."""
    global _GLOBAL
    _GLOBAL = None


def get_tracer() -> Optional[CommsTracer]:
    """The armed tracer, auto-created when ``APEX_TPU_COMMS`` is
    truthy, else None — the zero-overhead fast path."""
    global _GLOBAL
    if _GLOBAL is None and _env_enabled():
        _GLOBAL = CommsTracer()
    return _GLOBAL


def enabled() -> bool:
    return get_tracer() is not None


def instrument(collective, *, tracer: Optional[CommsTracer] = None):
    """``collective``, traced — or UNTOUCHED when no tracer is armed.

    The overhead discipline in one identity: with the plane disabled
    this returns the exact object passed in (``instrument(col) is
    col``), so the raw guard/fleet/elastic paths never see a wrapper.
    Armed, it wraps (idempotently — re-instrumenting a wrapped
    collective with the same tracer returns it as-is).
    """
    if collective is None:
        return None
    t = tracer if tracer is not None else get_tracer()
    if t is None:
        return collective
    if isinstance(collective, InstrumentedCollective):
        if collective.tracer is t:
            return collective
        return InstrumentedCollective(collective.inner, t)
    return InstrumentedCollective(collective, t)


def section() -> Dict[str, Any]:
    """The flight bundle's ``comms`` section: the tracer summary, or
    an explicit disabled marker with the reason (the value-or-null-
    with-reason contract — a bundle never silently lacks the plane)."""
    t = get_tracer()
    if t is None:
        return {"enabled": False,
                "reason": "comms tracing not armed "
                          "(telemetry.comms.enable() or APEX_TPU_COMMS=1)"}
    return {"enabled": True, **t.summary()}


__all__ = [
    "COLLECTIVE_OPS",
    "CommsTracer",
    "InstrumentedCollective",
    "disable",
    "enable",
    "enabled",
    "get_tracer",
    "instrument",
    "section",
    "wire_bytes",
]

"""Process-global metrics registry: counters, gauges, histograms.

The reference has no metrics layer at all (its observability is NVTX
ranges + print statements); TorchTitan (PAPERS.md, arXiv:2410.06511)
shows a production pre-training stack treats metrics as a first-class
subsystem. This module is that subsystem's spine for apex_tpu: every
runtime layer (train step, resilience ladder, prefetch pipeline,
backend guard) publishes into ONE registry instead of growing bespoke
counters (``PrefetchLoader.worker_deaths``, ``Watchdog.escalations``,
and the backend-probe report bench once held in a module global — the
per-object attributes still exist for compat but mirror into here).

Design:

- **Three instrument kinds.** :class:`Counter` (monotonic float),
  :class:`Gauge` (last-write-wins float), :class:`Histogram`
  (fixed-bucket cumulative counts + sum/count). All three support
  **labeled series**: ``counter.inc(action="rollback")`` creates/bumps
  the ``name{action="rollback"}`` series. Fixed buckets (no dynamic
  rebucketing) keep ``observe`` O(len(buckets)) with zero allocation
  on the hot path.
- **One snapshot.** :meth:`MetricsRegistry.snapshot` returns a single
  JSON-able dict of every series — what ``bench.py`` folds into each
  record's ``detail.telemetry`` and what tests assert against.
- **Structured events.** :meth:`MetricsRegistry.event` routes a
  discrete occurrence (probe verdict, corrupt record skipped,
  watchdog escalation) to every attached sink and counts it under
  ``telemetry_events{event=...}``.
- **Pluggable sinks.** :class:`InMemorySink` (tests),
  :class:`JsonlSink` (a dated JSONL file claimed with the same
  ``O_CREAT|O_EXCL`` + fsync-file-then-directory protocol as
  ``apex_tpu.records.write_record`` — a crash mid-run cannot lose the
  directory entry), :class:`StdoutSink` (one-line JSON protocol for
  log scrapers).

Everything here is host-side Python: no jax import, nothing traced.
A registry nobody publishes to costs one module import.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# seconds-scale latencies from sub-ms host ops to multi-second
# checkpoint writes; the last bucket is +Inf implicitly
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# count-scale quantities (tokens per chunk, queue depths, batch
# sizes). Observing a count into the seconds-scale grid above lands
# EVERYTHING in +Inf and the histogram reads as one useless spike —
# use this grid (or your own) for anything that isn't a duration;
# `MetricsRegistry.histogram` now refuses conflicting re-registration
# so the mismatch fails loudly instead of silently mis-bucketing.
TOKEN_COUNT_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)

# millisecond-scale latencies (collective ops: a KV-store barrier is
# ~1ms, an elastic range fetch can be seconds) — values observed here
# are ALREADY in ms, unlike the seconds-scale default grid
LATENCY_MS_BUCKETS: Tuple[float, ...] = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
    50.0, 100.0, 250.0, 1000.0, 5000.0)

# payload sizes in bytes, 64 B fingerprints to 256 MB buffer
# broadcasts — the grid `collective_bytes{op=}` rides so the bandwidth
# ledger can tell latency-bound ops from bandwidth-bound ones
PAYLOAD_BYTES_BUCKETS: Tuple[float, ...] = (
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304,
    16777216, 67108864, 268435456)


def _series_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series_name(name: str, key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return f"{name}{{{inner}}}"


class _Metric:
    """Shared labeled-series machinery; subclasses define the series
    payload and how an operation mutates it."""

    kind = "metric"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _get(self, labels: Dict[str, Any]):
        key = _series_key(labels)
        s = self._series.get(key)
        if s is None:
            with self._lock:
                s = self._series.setdefault(key, self._new_series())
        return s

    def _new_series(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def series(self) -> Dict[str, Any]:
        """``{series_name: snapshot_value}`` for every labeled child."""
        with self._lock:
            return {_series_name(self.name, k): self._snap(v)
                    for k, v in self._series.items()}

    def _snap(self, s):
        return s


class Counter(_Metric):
    """Monotonically increasing float, optionally labeled."""

    kind = "counter"

    def _new_series(self):
        return [0.0]

    def inc(self, n: float = 1.0, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        s = self._get(labels)
        with self._lock:
            s[0] += n

    def value(self, **labels) -> float:
        return self._get(labels)[0]

    def _snap(self, s):
        return s[0]


class _BoundGauge:
    """One pre-resolved labeled gauge series: hot loops pay a list
    store per :meth:`set` instead of per-call label sorting + dict
    lookup (:meth:`Gauge.bind`)."""

    __slots__ = ("_s", "_lock")

    def __init__(self, s, lock):
        self._s = s
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self._s[0] = float(v)

    def value(self) -> float:
        return self._s[0]


class Gauge(_Metric):
    """Last-write-wins float, optionally labeled."""

    kind = "gauge"

    def _new_series(self):
        return [0.0]

    def set(self, v: float, **labels) -> None:
        s = self._get(labels)
        with self._lock:
            s[0] = float(v)

    def bind(self, **labels) -> _BoundGauge:
        """Resolve one labeled series once; the returned handle's
        ``set`` skips the label machinery (per-step publishers)."""
        return _BoundGauge(self._get(labels), self._lock)

    def inc(self, n: float = 1.0, **labels) -> None:
        s = self._get(labels)
        with self._lock:
            s[0] += n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        return self._get(labels)[0]

    def _snap(self, s):
        return s[0]


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative counts, prometheus-style
    ``le`` upper bounds plus implicit ``+Inf``), with sum and count."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs

    def _new_series(self):
        # [counts per bucket ..., +Inf count, sum, count]
        return [0] * (len(self.buckets) + 1) + [0.0, 0]

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        s = self._get(labels)
        i = len(self.buckets)              # +Inf slot
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        with self._lock:
            s[i] += 1
            s[-2] += v
            s[-1] += 1

    def time(self, **labels):
        """``with hist.time():`` — observe the block's wall duration."""
        return _HistTimer(self, labels)

    def _snap(self, s):
        buckets = {str(b): sum(s[: i + 1])
                   for i, b in enumerate(self.buckets)}
        buckets["+Inf"] = sum(s[: len(self.buckets) + 1])
        return {"buckets": buckets, "sum": s[-2], "count": s[-1]}


class _HistTimer:
    def __init__(self, hist: Histogram, labels: Dict[str, Any]):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)
        return False


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------


class InMemorySink:
    """Collects events and snapshots in lists — the test sink."""

    def __init__(self):
        self.events: List[Dict[str, Any]] = []
        self.snapshots: List[Dict[str, Any]] = []

    def write_event(self, event: Dict[str, Any]) -> None:
        self.events.append(event)

    def write_snapshot(self, snap: Dict[str, Any]) -> None:
        self.snapshots.append(snap)

    def close(self) -> None:
        pass


class StdoutSink:
    """One-line JSON protocol: ``telemetry {...}`` per event/snapshot,
    greppable out of any log stream."""

    def __init__(self, stream=None, prefix: str = "telemetry"):
        self._stream = stream
        self.prefix = prefix

    def _emit(self, obj: Dict[str, Any]) -> None:
        stream = self._stream if self._stream is not None else sys.stdout
        print(f"{self.prefix} {json.dumps(obj, sort_keys=True)}",
              file=stream, flush=True)

    def write_event(self, event: Dict[str, Any]) -> None:
        self._emit({"type": "event", **event})

    def write_snapshot(self, snap: Dict[str, Any]) -> None:
        self._emit({"type": "snapshot", "snapshot": snap})

    def close(self) -> None:
        pass


class JsonlSink:
    """Durable JSONL event/snapshot log riding the ``records.py``
    atomic-claim writer protocol (PR 3):

    - the file name is **claimed** with ``O_CREAT|O_EXCL`` (an
      exists-then-open check is a TOCTOU race across processes);
      same-second collisions fall back to a strictly-increasing
      ``time.monotonic_ns()`` disambiguator;
    - after the claim the records DIRECTORY is fsync'd (fault site
      ``record_fsync``) — the claim is a directory entry, and a crash
      right after the first write could otherwise lose the whole file
      even though the data hit the platter;
    - every line is flushed and (with ``fsync=True``) fsync'd, so the
      telemetry trail survives exactly the preemption kills the
      resilience layer is built for.

    The default directory is ``records.RECORDS_DIR`` so telemetry logs
    land next to the bench records they explain.
    """

    def __init__(self, directory: Optional[str] = None,
                 name: str = "telemetry", fsync: bool = True):
        self._directory = directory
        self.name = str(name)
        self.fsync = bool(fsync)
        self.path: Optional[str] = None
        self._fd = None
        self._lock = threading.Lock()

    def _claim(self):
        from apex_tpu.resilience import faults

        directory = self._directory
        if directory is None:
            from apex_tpu import records

            directory = records.RECORDS_DIR
        faults.check("record_write")
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        base = f"{self.name}_{stamp}"
        path = os.path.join(directory, f"{base}.jsonl")
        while True:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o644)
                break
            except FileExistsError:
                path = os.path.join(
                    directory, f"{base}.{time.monotonic_ns()}.jsonl")
        try:
            # the claim is a directory entry: fsync the directory too,
            # or a crash right after the first append can erase the
            # file the caller was told exists (same fault site as
            # records.write_record so one knob covers both writers)
            faults.check("record_fsync")
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(path)          # never leave an unfsynced claim
            except OSError:
                pass
            raise
        self._fd = os.fdopen(fd, "w")
        self.path = path

    def _write(self, obj: Dict[str, Any]) -> None:
        with self._lock:
            if self._fd is None:
                self._claim()
            self._fd.write(json.dumps(obj, sort_keys=True) + "\n")
            self._fd.flush()
            if self.fsync:
                os.fsync(self._fd.fileno())

    def write_event(self, event: Dict[str, Any]) -> None:
        self._write({"type": "event", **event})

    def write_snapshot(self, snap: Dict[str, Any]) -> None:
        self._write({"type": "snapshot", "snapshot": snap})

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Name -> instrument map with get-or-create accessors, structured
    events, info blobs, and pluggable sinks. Thread-safe (one RLock
    shared with every instrument)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._info: Dict[str, Any] = {}
        self._sinks: List[Any] = []

    # -- instruments -------------------------------------------------------

    def _instrument(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self._lock, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._instrument(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._instrument(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create. ``buckets=None`` means "no opinion": a new
        histogram gets :data:`DEFAULT_BUCKETS`, an existing one is
        returned as-is (readers never pin a grid). EXPLICIT buckets on
        an already-registered histogram must match its grid exactly —
        a silent mismatch would route observations into the wrong
        buckets (the classic failure: a token COUNT observed into the
        seconds-scale default grid lands every sample in +Inf), so it
        raises instead."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help, self._lock,
                              buckets=(buckets if buckets is not None
                                       else DEFAULT_BUCKETS))
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not histogram")
            elif buckets is not None:
                want = tuple(sorted(float(b) for b in buckets))
                if want != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}; conflicting grid {want} "
                        "would silently mis-bucket observations")
            return m

    # -- info blobs --------------------------------------------------------

    def set_info(self, name: str, value: Any) -> None:
        """Attach a JSON-able structured value (e.g. the backend-probe
        verdict) that rides every snapshot under ``info``."""
        json.dumps(value)                # fail fast on non-JSON-able
        with self._lock:
            self._info[str(name)] = value

    def get_info(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._info.get(str(name), default)

    # -- events ------------------------------------------------------------

    def event(self, name: str, **fields) -> Dict[str, Any]:
        """Record a discrete structured occurrence: counts it under
        ``telemetry_events{event=name}`` and forwards it to every sink.
        Sinks must never take the publisher down — a dead disk under a
        JsonlSink degrades to the counter, not to an exception."""
        ev = {"event": str(name), "wall_time": time.time(), **fields}
        self.counter("telemetry_events",
                     "structured events by name").inc(event=name)
        for sink in list(self._sinks):
            try:
                sink.write_event(ev)
            except Exception:  # noqa: BLE001 — sinks are best-effort
                pass
        return ev

    # -- sinks -------------------------------------------------------------

    def add_sink(self, sink) -> None:
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    @property
    def sinks(self) -> List[Any]:
        return list(self._sinks)

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Everything, one JSON-able dict: per-kind series maps plus
        the info blobs."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        with self._lock:
            for m in self._metrics.values():
                out[m.kind + "s"].update(m.series())
            if self._info:
                out["info"] = dict(self._info)
        return out

    def flush(self) -> Dict[str, Any]:
        """Push one snapshot through every sink; returns the snapshot."""
        snap = self.snapshot()
        for sink in list(self._sinks):
            try:
                sink.write_snapshot(snap)
            except Exception:  # noqa: BLE001
                pass
        return snap

    def to_prometheus_text(self) -> str:
        """This registry as the Prometheus text exposition format
        (``# HELP``/``# TYPE`` with the instruments' live help text,
        labeled series, histogram ``le`` buckets + ``_sum``/``_count``
        — see :func:`prometheus_text_from_snapshot`)."""
        with self._lock:
            help_map = {m.name: (m.help, m.kind)
                        for m in self._metrics.values()}
        return prometheus_text_from_snapshot(self.snapshot(), help_map)

    def reset(self) -> None:
        """Drop every metric, info blob, and sink (tests)."""
        with self._lock:
            for sink in self._sinks:
                try:
                    sink.close()
                except Exception:  # noqa: BLE001
                    pass
            self._metrics.clear()
            self._info.clear()
            self._sinks.clear()


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

# "name" or 'name{k="v",k2="v2"}' — the exact shape _series_name emits,
# so the label block can be reused verbatim in the output lines
_SERIES_RE = re.compile(r"^(?P<name>[^{]+?)(?:\{(?P<labels>.*)\})?$")


def _split_series(series_name: str) -> Tuple[str, str]:
    m = _SERIES_RE.match(series_name)
    return m.group("name"), (m.group("labels") or "")


def _prom_escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _with_label(labels: str, extra: str) -> str:
    inner = f"{labels},{extra}" if labels else extra
    return "{" + inner + "}"


def prometheus_text_from_snapshot(
        snap: Dict[str, Any],
        help_map: Optional[Dict[str, Tuple[str, str]]] = None) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` dict (live, or loaded
    back from a bench record / flight-recorder bundle) as the
    Prometheus text exposition format: ``# HELP``/``# TYPE`` headers,
    labeled series, histogram ``_bucket{le=...}`` rows (cumulative,
    ``+Inf`` included) plus ``_sum``/``_count``.

    ``help_map`` is ``{base_name: (help, kind)}``; absent entries get
    an empty HELP line (a snapshot on disk does not carry help text).
    Info blobs are not representable in the text format and are
    skipped.
    """
    help_map = help_map or {}
    lines: List[str] = []
    seen_header: set = set()

    def header(name: str, default_kind: str) -> None:
        if name in seen_header:
            return
        seen_header.add(name)
        help_text, kind = help_map.get(name, ("", default_kind))
        lines.append(f"# HELP {name} {_prom_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {kind or default_kind}")

    for section, kind in (("counters", "counter"), ("gauges", "gauge")):
        for series, value in sorted((snap.get(section) or {}).items()):
            name, labels = _split_series(series)
            header(name, kind)
            label_block = "{" + labels + "}" if labels else ""
            lines.append(f"{name}{label_block} {_prom_num(value)}")
    for series, h in sorted((snap.get("histograms") or {}).items()):
        name, labels = _split_series(series)
        header(name, "histogram")
        buckets = h.get("buckets") or {}

        def _le_key(le: str) -> float:
            return float("inf") if le == "+Inf" else float(le)

        for le in sorted(buckets, key=_le_key):
            le_label = 'le="' + le + '"'
            lines.append(f"{name}_bucket{_with_label(labels, le_label)} "
                         f"{_prom_num(buckets[le])}")
        label_block = "{" + labels + "}" if labels else ""
        lines.append(f"{name}_sum{label_block} {_prom_num(h.get('sum', 0.0))}")
        lines.append(f"{name}_count{label_block} "
                     f"{_prom_num(h.get('count', 0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_prometheus_text(snapshot: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition of ``snapshot`` (or the process-global
    registry, with live HELP text) — what ``tools/telemetry_dump.py``
    prints and a node-exporter-style scrape endpoint would serve."""
    if snapshot is None:
        return _REGISTRY.to_prometheus_text()
    return prometheus_text_from_snapshot(snapshot)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every subsystem publishes to."""
    return _REGISTRY


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def reset() -> None:
    _REGISTRY.reset()


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "LATENCY_MS_BUCKETS",
    "PAYLOAD_BYTES_BUCKETS",
    "TOKEN_COUNT_BUCKETS",
    "Gauge",
    "Histogram",
    "InMemorySink",
    "JsonlSink",
    "MetricsRegistry",
    "StdoutSink",
    "prometheus_text_from_snapshot",
    "registry",
    "reset",
    "snapshot",
    "to_prometheus_text",
]

"""Static step-cost estimation: FLOPs / bytes-moved / MFU from XLA.

``jax.jit(...).lower().compile().cost_analysis()`` is XLA's own static
accounting of a compiled program — model FLOPs and HBM bytes accessed
— available before (and independent of) any timed run. Pairing it with
a measured step time gives:

- **MFU** (model FLOPs utilization) against the chip's published peak
  (``backend_guard.chip_peak_tflops``), the TorchTitan-style headline
  efficiency number;
- **achieved HBM bandwidth** for the memory-bound phases (the fused
  optimizer step's real ceiling — see docs/train_step.md's
  accesses-per-element budget).

Every helper degrades to ``None`` **with a reason string** instead of
raising: some backends expose no cost model, some device kinds have no
peak-TFLOPs entry, and a bench record must say *why* its ``mfu`` is
null rather than silently dropping the field (BENCH_r0x fallback-saga
rule: records never contradict themselves).

The program's memory FOOTPRINT (``memory_analysis()``) lives next
door in :mod:`~apex_tpu.telemetry.devmem`; :func:`bytes_per_element`
below is the measured side of the bench's measured-vs-analytic HBM
ledger (docs/observability.md "compile & memory plane").
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def normalize_cost_analysis(ca: Any) -> Optional[Dict[str, float]]:
    """``cost_analysis()`` returns a dict on new jax, a one-element
    list of dicts on older releases, or None/raises when the backend
    has no cost model — normalize all of that to one dict or None."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return ca


def compiled_cost(compiled) -> Optional[Dict[str, float]]:
    """``{"flops": ..., "bytes_accessed": ...}`` of a compiled
    computation (``jax.jit(f).lower(...).compile()``), or None when
    the backend exposes no cost model."""
    try:
        ca = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — "no cost model" raises on some backends
        return None
    if ca is None:
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    if flops is None and nbytes is None:
        return None
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": float(nbytes) if nbytes is not None else None,
    }


def jitted_cost(fn, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Lower+compile ``fn`` (a ``jax.jit`` result) on the given
    arguments and return its static cost; None on any failure — cost
    accounting must never take down the loop it describes."""
    from apex_tpu.telemetry import compiled as _compiled

    try:
        with _compiled.label("jitted_cost"):
            return compiled_cost(fn.lower(*args, **kwargs).compile())
    except Exception:  # noqa: BLE001
        return None


def train_step_cost(step, state, flat_grads,
                    scaler_state=None, lr=None) -> Optional[Dict[str, float]]:
    """Static cost of one fused train step
    (:class:`~apex_tpu.optimizers.train_step.TrainStep`). Uses the
    step's ``lower`` passthrough, so nothing executes and no buffer is
    donated — safe to call right before the timed run."""
    from apex_tpu.telemetry import compiled as _compiled

    try:
        with _compiled.label("train_step_cost"):
            return compiled_cost(
                step.lower(state, flat_grads, scaler_state,
                           lr=lr).compile())
    except Exception:  # noqa: BLE001
        return None


def bytes_per_element(cost: Optional[Dict[str, float]],
                      n_elements: int) -> Optional[float]:
    """Measured HBM bytes per model element from a cost dict — the
    MEASURED side of the bench's measured-vs-analytic HBM ledger (the
    analytic side is ``hbm_accesses_per_element``, the fp32
    accesses/element design numbers of docs/train_step.md). None when
    the backend has no cost model or the element count is unusable —
    the record then says null instead of a made-up number."""
    if not cost or not cost.get("bytes_accessed") or not n_elements:
        return None
    return round(float(cost["bytes_accessed"]) / float(n_elements), 3)


def device_kind() -> str:
    try:
        import jax

        return str(getattr(jax.devices()[0], "device_kind", "cpu"))
    except Exception:  # noqa: BLE001
        return "unknown"


def mfu_estimate(cost: Optional[Dict[str, float]], seconds: float,
                 kind: Optional[str] = None) -> Dict[str, Any]:
    """MFU + bandwidth accounting for one timed step.

    Always returns the full key set — ``mfu`` is a value or None, and
    when None ``mfu_reason`` names exactly why (no cost model, unknown
    chip, bad timing) so downstream JSON consumers never guess.
    """
    from apex_tpu.backend_guard import chip_peak_tflops

    kind = kind if kind is not None else device_kind()
    out: Dict[str, Any] = {
        "flops_per_step": None, "bytes_per_step": None,
        "tflops_per_sec": None, "hbm_gb_per_sec": None,
        "chip": kind, "chip_peak_tflops": chip_peak_tflops(kind),
        "mfu": None, "mfu_reason": None,
    }
    if cost is None:
        out["mfu_reason"] = ("backend exposes no XLA cost model "
                             "(cost_analysis unavailable)")
        return out
    out["flops_per_step"] = cost.get("flops")
    out["bytes_per_step"] = cost.get("bytes_accessed")
    if not seconds or seconds <= 0.0:
        out["mfu_reason"] = f"non-positive step time ({seconds})"
        return out
    if out["bytes_per_step"] is not None:
        out["hbm_gb_per_sec"] = round(out["bytes_per_step"] / seconds / 1e9,
                                      2)
    if out["flops_per_step"] is None:
        out["mfu_reason"] = "cost model reports no flops for this program"
        return out
    tflops = out["flops_per_step"] / seconds / 1e12
    out["tflops_per_sec"] = round(tflops, 4)
    peak = out["chip_peak_tflops"]
    if not peak:
        out["mfu_reason"] = (f"no peak-TFLOPs entry for device kind "
                             f"{kind!r} — mfu denominator unknown")
        return out
    out["mfu"] = round(tflops / peak, 6)
    return out


def publish_mfu(est: Dict[str, Any], registry=None) -> None:
    """Mirror an :func:`mfu_estimate` into the metrics registry: the
    ``mfu`` gauge when known, the reason as an info blob when not, plus
    the flops/bytes gauges — so ``snapshot()`` (and through it every
    bench record) carries the numbers."""
    from apex_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.registry()
    if est.get("mfu") is not None:
        reg.gauge("mfu", "model FLOPs utilization of the timed step").set(
            est["mfu"])
    reg.set_info("mfu_reason", est.get("mfu_reason"))
    if est.get("flops_per_step") is not None:
        reg.gauge("step_flops", "static FLOPs of one compiled step").set(
            est["flops_per_step"])
    if est.get("bytes_per_step") is not None:
        reg.gauge("step_bytes_accessed",
                  "static HBM bytes accessed by one compiled step").set(
            est["bytes_per_step"])
    if est.get("hbm_gb_per_sec") is not None:
        reg.gauge("step_hbm_gb_per_sec",
                  "achieved HBM bandwidth of the timed step").set(
            est["hbm_gb_per_sec"])


def publish_mfu_window(cost: Optional[Dict[str, float]], seconds: float,
                       *, kind: Optional[str] = None, alpha: float = 0.2,
                       registry=None) -> Dict[str, Any]:
    """Windowed MFU: fold one :func:`mfu_estimate` into the
    ``mfu_ewma`` gauge so utilization updates continuously (the
    goodput ledger calls this with its productive-step-window median
    each publish) instead of only at the one-shot :func:`publish_mfu`.

    Same degradation contract as everything here: when the estimate is
    null, the gauge is left untouched and ``mfu_reason`` says why —
    the returned dict carries ``mfu_ewma`` as a value or None."""
    from apex_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.registry()
    est = mfu_estimate(cost, seconds, kind)
    if est["mfu"] is None:
        reg.set_info("mfu_reason", est.get("mfu_reason"))
        est["mfu_ewma"] = None
        return est
    g = reg.gauge("mfu_ewma",
                  "EWMA model FLOPs utilization over the ledger's "
                  "productive-step window")
    prev = g.value()
    cur = est["mfu"] if not prev else (
        (1.0 - alpha) * prev + alpha * est["mfu"])
    cur = round(cur, 6)
    g.set(cur)
    est["mfu_ewma"] = cur
    return est


__all__ = [
    "bytes_per_element",
    "compiled_cost",
    "device_kind",
    "jitted_cost",
    "mfu_estimate",
    "normalize_cost_analysis",
    "publish_mfu",
    "publish_mfu_window",
    "train_step_cost",
]

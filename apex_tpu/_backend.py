"""Backend/implementation dispatch.

The reference dispatches CUDA vs ROCm at build time (ref: setup.py:160-175).
Here the choice is runtime: Pallas TPU kernels on TPU backends, pure-XLA
(jnp) reference paths elsewhere (CPU tests, simulated meshes). Every fused
op in this package has both paths and tests compare them.

Env override: ``APEX_TPU_IMPL`` = ``pallas`` | ``xla`` | ``interpret``
(``interpret`` runs the Pallas kernels in interpreter mode — used by the
kernel-parity test suite on CPU).
"""

import os
from functools import lru_cache

import jax

VALID_IMPLS = ("pallas", "xla", "interpret")


@lru_cache(maxsize=None)
def default_impl() -> str:
    """Resolve which implementation fused ops use by default."""
    env = os.environ.get("APEX_TPU_IMPL", "").strip().lower()
    if env:
        if env not in VALID_IMPLS:
            raise ValueError(
                f"APEX_TPU_IMPL={env!r} invalid; expected one of {VALID_IMPLS}"
            )
        return env
    if is_tpu() and pallas_available():
        return "pallas"
    return "xla"


@lru_cache(maxsize=None)
def pallas_available() -> bool:
    """One-time probe: can Mosaic compile and run a trivial kernel on
    this backend? The runtime analog of the reference's
    ``multi_tensor_applier.available`` extension check
    (ref: apex/multi_tensor_apply/multi_tensor_apply.py:8-25). A failure
    downgrades the default to the XLA paths with a warning instead of
    breaking every fused op."""
    import logging

    try:
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        x = jnp.ones((16, 128), jnp.float32)
        out = pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
        )(x)
        jax.block_until_ready(out)
        return True
    except Exception as e:  # noqa: BLE001 — any failure means "degrade"
        logging.getLogger("apex_tpu").warning(
            "Pallas probe failed on backend %r (%s: %s) — fused ops "
            "default to the XLA implementations. Set APEX_TPU_IMPL=pallas "
            "to force kernels.", jax.default_backend(),
            type(e).__name__, str(e).split("\n")[0][:200])
        return False


@lru_cache(maxsize=None)
def is_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def resolve_impl(impl=None) -> str:
    """Resolve an op-level ``impl=`` kwarg against the global default."""
    if impl is None:
        return default_impl()
    if impl not in VALID_IMPLS:
        raise ValueError(f"impl={impl!r} invalid; expected one of {VALID_IMPLS}")
    return impl


def interpret_flag(impl: str) -> bool:
    """Whether a pallas_call built for ``impl`` should run interpreted."""
    return impl == "interpret"

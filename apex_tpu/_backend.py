"""Backend/implementation dispatch.

The reference dispatches CUDA vs ROCm at build time (ref: setup.py:160-175).
Here the choice is runtime: Pallas TPU kernels on TPU backends, pure-XLA
(jnp) reference paths elsewhere (CPU tests, simulated meshes). Every fused
op in this package has both paths and tests compare them.

Env override: ``APEX_TPU_IMPL`` = ``pallas`` | ``xla`` | ``interpret``
(``interpret`` runs the Pallas kernels in interpreter mode — used by the
kernel-parity test suite on CPU).
"""

import os
from functools import lru_cache

import jax

VALID_IMPLS = ("pallas", "xla", "interpret")


@lru_cache(maxsize=None)
def default_impl() -> str:
    """Resolve which implementation fused ops use by default."""
    env = os.environ.get("APEX_TPU_IMPL", "").strip().lower()
    if env:
        if env not in VALID_IMPLS:
            raise ValueError(
                f"APEX_TPU_IMPL={env!r} invalid; expected one of {VALID_IMPLS}"
            )
        return env
    return "pallas" if is_tpu() else "xla"


@lru_cache(maxsize=None)
def is_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def resolve_impl(impl=None) -> str:
    """Resolve an op-level ``impl=`` kwarg against the global default."""
    if impl is None:
        return default_impl()
    if impl not in VALID_IMPLS:
        raise ValueError(f"impl={impl!r} invalid; expected one of {VALID_IMPLS}")
    return impl


def interpret_flag(impl: str) -> bool:
    """Whether a pallas_call built for ``impl`` should run interpreted."""
    return impl == "interpret"

"""jax version-compat shims.

The package targets the current jax surface (``jax.shard_map`` with
``check_vma``); older releases ship ``shard_map`` under
``jax.experimental`` with the ``check_rep`` spelling of the same knob.
Importing through this module keeps every call site written against the
modern API while degrading cleanly on the older runtime — the analog of
the reference's version-gated ``torch`` imports (ref:
apex/transformer/utils.py torch_version gates).
"""

from __future__ import annotations

try:  # modern surface (jax >= 0.6): top-level, check_vma spelling
    from jax import shard_map as _shard_map

    _VMA_KW = "check_vma"
except ImportError:  # older runtime: experimental, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _VMA_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the ``check_vma`` knob on every version.

    ``check_vma`` (None = the runtime's default) maps to ``check_rep``
    on runtimes that predate the rename; all other kwargs pass through.
    """
    if check_vma is not None:
        kwargs[_VMA_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


try:  # modern surface: static mapped-axis size lookup
    from jax.lax import axis_size
except ImportError:
    def axis_size(axis_name):
        """``lax.axis_size`` for runtimes that predate it: psum of the
        constant 1 over the axis constant-folds to the static size
        (a plain int under shard_map tracing) and raises the same
        NameError for unbound axis names."""
        from jax import lax

        return lax.psum(1, axis_name)


def _install_polyfills() -> None:
    """Backfill the missing names onto jax itself so the package's
    (and its tests'/examples') call sites — written against the modern
    surface — run unmodified on the older runtime. Pure additions:
    nothing existing is overridden."""
    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        lax.axis_size = axis_size
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            # pre-rename spelling of the same params dataclass
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:  # noqa: BLE001 — pallas backend absent is fine
        pass


_install_polyfills()


__all__ = ["shard_map", "axis_size"]

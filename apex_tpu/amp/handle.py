"""`with amp.scale_loss(...)` — the reference's context-manager surface
(ref: apex/amp/handle.py:16-158).

The reference yields ``loss.float() * scale`` and, on exit, unscales the
stashed grads, updates the scale, and patches ``optimizer.step`` to a
skip on overflow. The functional TPU analog keeps the exact `with` shape
users port, with the imperative steps becoming fields on the yielded
handle (everything traces under jit):

    with amp.scale_loss(loss, amp_state, loss_id=0) as scaled:
        scaled.grads = jax.grad(scaled_loss_fn)(params)   # grads of
                                                          # scaled.loss
    # exiting the block unscales + updates the scaler:
    grads      = scaled.grads        # unscaled grad pytree
    amp_state  = scaled.amp_state    # scaler advanced (overflow halves)
    skip       = scaled.skip         # fp32 0/1 — gate the step on it

``skip`` replaces the reference's monkey-patched skip-step
(handle.py:127-154): pass it to a fused optimizer's ``found_inf`` /
``skip_if_nonfinite`` input or gate the update with ``lax.cond``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax.numpy as jnp

from apex_tpu.amp.frontend import AmpState, make_scaler


class _ScaledLossHandle:
    """Yielded by :func:`scale_loss`. ``loss`` is the scaled loss;
    assign the grads of that scaled loss to ``.grads`` inside the block
    and read back the unscaled grads, advanced ``amp_state`` and
    ``skip`` flag after it."""

    def __init__(self, loss, scaler, amp_state: AmpState, loss_id: int):
        self._scaler = scaler
        self._in_state = amp_state
        self._loss_id = loss_id
        self.loss = scaler.scale_loss(loss, amp_state.scalers[loss_id])
        self.grads: Optional[Any] = None
        self.amp_state: Optional[AmpState] = None
        self.skip = None

    def _finish(self):
        state = self._in_state.scalers[self._loss_id]
        if self.grads is not None:
            self.grads, found_inf = self._scaler.unscale(self.grads, state)
        else:
            found_inf = jnp.zeros((), jnp.float32)
        new_scaler = self._scaler.update(state, found_inf)
        scalers = list(self._in_state.scalers)
        scalers[self._loss_id] = new_scaler
        self.amp_state = AmpState(properties=self._in_state.properties,
                                  scalers=tuple(scalers))
        self.skip = found_inf


@contextlib.contextmanager
def scale_loss(loss, amp_state: AmpState, *, loss_id: int = 0,
               delay_unscale: bool = False):
    """Drop-in shape of ``apex.amp.scale_loss`` (ref handle.py:16-158).

    ``delay_unscale=True`` mirrors the reference's grad-accumulation
    knob (handle.py:62-76): exit leaves ``.grads`` scaled and the scaler
    state unchanged — unscale once on the final accumulation step.
    """
    if loss_id >= len(amp_state.scalers):
        raise ValueError(
            f"loss_id {loss_id} out of range for {len(amp_state.scalers)} "
            f"scalers (pass num_losses to amp.initialize)")
    scaler = make_scaler(amp_state.properties)
    handle = _ScaledLossHandle(loss, scaler, amp_state, loss_id)
    yield handle
    if delay_unscale:
        handle.amp_state = amp_state
        handle.skip = jnp.zeros((), jnp.float32)
    else:
        handle._finish()


@contextlib.contextmanager
def disable_casts():
    """Suspend amp casting inside the block (ref handle.py:163-167):
    every ``amp.F`` wrapper becomes a passthrough until exit. (Only
    meaningful OUTSIDE jit or at trace time — a compiled program has
    its casts baked in.)"""
    from apex_tpu.amp import _amp_state
    with _amp_state.suspend_casts():
        yield


__all__ = ["scale_loss", "disable_casts"]

"""Mixed precision engine (ref: apex/amp).

Opt levels O0-O5 as explicit precision policies, a functional dynamic
LossScaler, and function-level cast decorators. See `frontend.py` for the
design mapping from the reference's monkey-patching architecture.
"""

from apex_tpu.amp.frontend import (
    OPT_LEVELS,
    AmpState,
    Properties,
    initialize,
    load_state_dict,
    make_scaler,
    state_dict,
)
from apex_tpu.amp.functional import (
    bfloat16_function,
    compute_cast,
    float_function,
    half_function,
    promote_function,
)
from apex_tpu.amp.scaler import LossScaler, ScalerState

__all__ = [
    "OPT_LEVELS",
    "AmpState",
    "Properties",
    "initialize",
    "state_dict",
    "load_state_dict",
    "make_scaler",
    "LossScaler",
    "ScalerState",
    "half_function",
    "bfloat16_function",
    "float_function",
    "promote_function",
    "compute_cast",
]

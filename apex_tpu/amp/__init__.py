"""Mixed precision engine (ref: apex/amp).

Opt levels O0-O5 as explicit precision policies, a functional dynamic
LossScaler, and function-level cast decorators. See `frontend.py` for the
design mapping from the reference's monkey-patching architecture.
"""

from apex_tpu.amp.frontend import (
    OPT_LEVELS,
    AmpState,
    Properties,
    initialize,
    load_state_dict,
    make_scaler,
    master_params,
    state_dict,
)
from apex_tpu.amp.functional import (
    bfloat16_function,
    compute_cast,
    float_function,
    half_function,
    promote_function,
    register_bfloat16_function,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp.handle import disable_casts, scale_loss
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.amp import lists
from apex_tpu.amp import nn_functional as F
from apex_tpu.amp._amp_state import policy_scope

__all__ = [
    "scale_loss",
    "disable_casts",
    "OPT_LEVELS",
    "AmpState",
    "Properties",
    "initialize",
    "state_dict",
    "load_state_dict",
    "make_scaler",
    "LossScaler",
    "ScalerState",
    "half_function",
    "bfloat16_function",
    "float_function",
    "promote_function",
    "compute_cast",
    "register_half_function",
    "register_bfloat16_function",
    "register_float_function",
    "register_promote_function",
    "master_params",
    "lists",
    "F",
    "policy_scope",
]

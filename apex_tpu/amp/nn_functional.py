"""``amp.F`` — a functional namespace with the shipped op
classification pre-applied.

The reference patches ``torch.nn.functional`` at ``amp.init`` so a
model written against it gets casts for free (ref: apex/amp/amp.py:
75-198, apex/amp/wrap.py:10-286). JAX namespaces are not patched;
instead this module *is* the patched namespace: every function consults
the active policy (:mod:`apex_tpu.amp._amp_state`, set by
``amp.initialize``) at trace time and applies the classification from
:mod:`apex_tpu.amp.lists` —

- whitelist ops cast float inputs to the policy compute dtype (O1
  fp16 / O4 bf16) before hitting the MXU;
- blacklist ops compute and return fp32;
- promote ops cast mixed float args to the widest dtype;
- ``binary_cross_entropy`` is banned with guidance.

With no active policy (or under ``amp.disable_casts()``) every wrapper
is a passthrough, so code written against ``amp.F`` runs unchanged in
pure fp32. Implementations are plain jnp/lax — XLA fuses them; the
hand-fused Pallas versions stay in the layer zoo (`apex_tpu.ops`,
`apex_tpu.normalization`) for the hot paths.

Torch-porting conventions are kept where they are free: ``linear``
takes an (out, in) weight, convs default to NCHW/OIHW layouts, losses
default to mean reduction.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp import _amp_state
from apex_tpu.amp.functional import _cast_floats, promote_function
from apex_tpu.amp.lists import BANNED_MESSAGE


# --------------------------------------------------------------------------
# classification decorators (policy-aware variants of amp.functional's
# static-dtype decorators; the cast policy itself — which leaves count
# as float, Python scalars stay weak-typed — is defined ONCE in
# amp/functional.py and shared)
# --------------------------------------------------------------------------

def whitelisted(fn):
    """Run in the active compute dtype (MXU-bound op)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        dt = _amp_state.active_compute_dtype()
        if dt is not None:
            args, kwargs = _cast_floats(args, dt), _cast_floats(kwargs, dt)
        return fn(*args, **kwargs)

    return wrapper


def blacklisted(fn):
    """Compute and return fp32 whenever a patch-style policy is active
    (matches the reference's ALWAYS_FLOAT expectation)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _amp_state.active_compute_dtype() is not None:
            args = _cast_floats(args, jnp.float32)
            kwargs = _cast_floats(kwargs, jnp.float32)
        return fn(*args, **kwargs)

    return wrapper


def promoted(fn):
    """Cast mixed float args to the widest float dtype among them when a
    patch-style policy is active (delegates to amp.functional's
    promote_function so the promotion semantics live in one place)."""
    promoted_fn = promote_function(fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _amp_state.active_compute_dtype() is None:
            return fn(*args, **kwargs)
        return promoted_fn(*args, **kwargs)

    return wrapper


def banned(name: str, message: str):
    def wrapper(*args, **kwargs):
        if (_amp_state.active_compute_dtype() is not None
                and not _amp_state.allow_banned):
            raise RuntimeError(f"amp banned function {name!r}: {message}")
        return _binary_cross_entropy_impl(*args, **kwargs)

    wrapper.__name__ = name
    return wrapper


# --------------------------------------------------------------------------
# whitelist: MXU ops
# --------------------------------------------------------------------------

@whitelisted
def linear(x, weight, bias=None):
    """y = x @ weight.T (+ bias); weight is (out, in) torch-style."""
    y = jnp.matmul(x, jnp.swapaxes(weight, -1, -2))
    return y if bias is None else y + bias


dense = linear


@whitelisted
def matmul(a, b):
    return jnp.matmul(a, b)


@whitelisted
def bmm(a, b):
    return jnp.matmul(a, b)


@whitelisted
def dot(a, b):
    return jnp.dot(a, b)


@whitelisted
def einsum(subscripts, *operands):
    return jnp.einsum(subscripts, *operands)


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd):
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilation, int):
        dilation = (dilation,) * nd
    if isinstance(padding, int):
        padding = [(padding, padding)] * nd
    elif isinstance(padding, (tuple, list)) and padding and isinstance(
            padding[0], int):
        padding = [(p, p) for p in padding]
    # torch layouts: activations NC<spatial>, weights OI<spatial>
    spatial = "DHW"[3 - nd:]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y


@whitelisted
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1)


@whitelisted
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2)


@whitelisted
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3)


@whitelisted
def conv_transpose2d(x, weight, bias=None, stride=1, padding=0, groups=1):
    if groups != 1:
        raise NotImplementedError(
            "conv_transpose2d with groups > 1: the gradient-of-conv "
            "formulation needs block-diagonal weight handling; use "
            "groups=1 or a per-group loop")
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    elif isinstance(padding, (tuple, list)) and padding and isinstance(
            padding[0], int):
        padding = tuple((p, p) for p in padding)
    # torch transposed-conv weight is (in, out/groups, H, W): the IOHW
    # spec swaps in/out channels; the gradient-of-conv kernel flip is
    # explicit
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape, ("NCHW", "IOHW", "NCHW"))
    k = weight.shape[-2:]
    pads = tuple((d - 1 - lo, d - 1 - hi)
                 for d, (lo, hi) in zip(k, padding))
    y = lax.conv_general_dilated(
        x, jnp.flip(weight, (-2, -1)), window_strides=(1, 1),
        padding=pads, lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        y = y + bias.reshape(1, -1, 1, 1)
    return y


# --------------------------------------------------------------------------
# blacklist: fp32 ops
# --------------------------------------------------------------------------

@blacklisted
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@blacklisted
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@blacklisted
def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


@blacklisted
def softplus(x):
    return jax.nn.softplus(x)


@blacklisted
def gelu(x, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


@blacklisted
def logsumexp(x, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


@blacklisted
def layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mu = jnp.mean(x, axis=axes, keepdims=True)
    var_ = jnp.mean(jnp.square(x - mu), axis=axes, keepdims=True)
    y = (x - mu) * lax.rsqrt(var_ + eps)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


@blacklisted
def rms_norm(x, weight=None, eps=1e-6):
    y = x * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return y if weight is None else y * weight


@blacklisted
def group_norm(x, num_groups, weight=None, bias=None, eps=1e-5):
    n, c = x.shape[:2]
    g = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
    axes = tuple(range(2, g.ndim))
    mu = jnp.mean(g, axis=axes, keepdims=True)
    var_ = jnp.mean(jnp.square(g - mu), axis=axes, keepdims=True)
    y = ((g - mu) * lax.rsqrt(var_ + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@blacklisted
def batch_norm(x, running_mean=None, running_var=None, weight=None,
               bias=None, training=False, eps=1e-5):
    """Functional BN. Unlike torch this never mutates running stats:
    ``training=True`` normalizes with batch statistics, else with the
    given running stats (train-time stat updates live in
    `apex_tpu.parallel.sync_batchnorm`, where they are carried state)."""
    axes = (0,) + tuple(range(2, x.ndim))
    if training or running_mean is None:
        mu = jnp.mean(x, axis=axes)
        var_ = jnp.var(x, axis=axes)
    else:
        mu, var_ = running_mean, running_var
    shape = (1, -1) + (1,) * (x.ndim - 2)
    y = (x - mu.reshape(shape)) * lax.rsqrt(var_.reshape(shape) + eps)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@blacklisted
def normalize(x, p=2, axis=1, eps=1e-12):
    n = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(n, eps)


@blacklisted
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return jnp.sum(x1 * x2, axis=axis) / jnp.maximum(n1 * n2, eps)


@blacklisted
def norm(x, ord=None, axis=None):
    return jnp.linalg.norm(x, ord=ord, axis=axis)


@blacklisted
def var(x, axis=None, ddof=0):
    return jnp.var(x, axis=axis, ddof=ddof)


@blacklisted
def std(x, axis=None, ddof=0):
    return jnp.std(x, axis=axis, ddof=ddof)


@blacklisted
def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


@blacklisted
def cumprod(x, axis=None):
    return jnp.cumprod(x, axis=axis)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


@blacklisted
def mse_loss(pred, target, reduction="mean"):
    return _reduce(jnp.square(pred - target), reduction)


@blacklisted
def l1_loss(pred, target, reduction="mean"):
    return _reduce(jnp.abs(pred - target), reduction)


@blacklisted
def smooth_l1_loss(pred, target, beta=1.0, reduction="mean"):
    d = jnp.abs(pred - target)
    v = jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta)
    return _reduce(v, reduction)


@blacklisted
def nll_loss(log_probs, target, reduction="mean"):
    v = -jnp.take_along_axis(
        log_probs, target[..., None], axis=-1)[..., 0]
    return _reduce(v, reduction)


@blacklisted
def cross_entropy(logits, target, reduction="mean"):
    lp = jax.nn.log_softmax(logits, axis=-1)
    v = -jnp.take_along_axis(lp, target[..., None], axis=-1)[..., 0]
    return _reduce(v, reduction)


@blacklisted
def kl_div(log_pred, target, reduction="mean"):
    v = target * (jnp.log(jnp.maximum(target, 1e-38)) - log_pred)
    return _reduce(v, reduction)


@blacklisted
def poisson_nll_loss(log_input, target, reduction="mean"):
    v = jnp.exp(log_input) - target * log_input
    return _reduce(v, reduction)


@blacklisted
def binary_cross_entropy_with_logits(logits, target, reduction="mean"):
    v = (jnp.maximum(logits, 0) - logits * target
         + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    return _reduce(v, reduction)


def _binary_cross_entropy_impl(probs, target, reduction="mean",
                               eps=1e-12):
    p = jnp.clip(probs, eps, 1.0 - eps)
    v = -(target * jnp.log(p) + (1.0 - target) * jnp.log1p(-p))
    return _reduce(v, reduction)


binary_cross_entropy = banned("binary_cross_entropy", BANNED_MESSAGE)


# --------------------------------------------------------------------------
# promote: mixed-dtype math / sequence casts
# --------------------------------------------------------------------------

@promoted
def add(a, b):
    return jnp.add(a, b)


@promoted
def mul(a, b):
    return jnp.multiply(a, b)


@promoted
def div(a, b):
    return jnp.divide(a, b)


@promoted
def atan2(a, b):
    return jnp.arctan2(a, b)


@promoted
def cat(arrays: Sequence, axis=0):
    return jnp.concatenate(arrays, axis=axis)


concatenate = cat


@promoted
def stack(arrays: Sequence, axis=0):
    return jnp.stack(arrays, axis=axis)


# --------------------------------------------------------------------------
# match-input: dtype-preserving activations (deliberately unwrapped —
# the reference leaves these unpatched, MATCH_INPUT in its tests)
# --------------------------------------------------------------------------

def relu(x):
    return jax.nn.relu(x)


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def silu(x):
    return jax.nn.silu(x)

"""Shipped op-classification defaults.

The reference's O1 value is that it *ships* the judgment call of which
ops are fp16-safe (ref: apex/amp/lists/functional_overrides.py:18-92,
apex/amp/lists/torch_overrides.py:7-133); users get a working mixed-
precision policy with zero registration. These tables are that judgment
call for the JAX/TPU op surface, consumed two ways:

- :mod:`apex_tpu.amp.nn_functional` (exported as ``amp.F``) ships a
  functional namespace with the classification pre-applied — the
  equivalent of the reference's patched ``torch.nn.functional``;
- :func:`register_defaults` applies the same classification to any
  user module holding same-named functions, via the
  ``amp.functional.register_*`` machinery.

Classification rationale (TPU terms):
- COMPUTE_FUNCS ride the MXU: matmuls/convs are where bf16/fp16 wins
  throughput and the systolic array accumulates in fp32 anyway.
- FP32_FUNCS are numerically unsafe in 16-bit: exponent-range ops
  (softmax/logsumexp family), variance-style reductions (norms), and
  loss functions whose gradients scale poorly.
- PROMOTE/SEQUENCE_CASTS mix dtypes: promote to the widest float.
- BANNED: sigmoid-output BCE saturates in fp16; the reference refuses
  it with guidance (functional_overrides.py:95-107) and so do we.
"""

from __future__ import annotations

# -- whitelist: run in the policy compute dtype (fp16 for O1, bf16 for
#    O4) -----------------------------------------------------------------
COMPUTE_FUNCS = [
    "linear",
    "dense",
    "conv1d",
    "conv2d",
    "conv3d",
    "conv_transpose2d",
    "matmul",
    "bmm",
    "einsum",
    "dot",
]

# fork parity: the reference fork classifies the same ops for bf16
# (ref: apex/amp/lists/functional_overrides.py BFLOAT16_FUNCS)
FP16_FUNCS = list(COMPUTE_FUNCS)
BFLOAT16_FUNCS = list(COMPUTE_FUNCS)

# -- blacklist: always computed (and returned) in fp32 -------------------
FP32_FUNCS = [
    # softmax family / exponent-range pointwise
    "softmax",
    "log_softmax",
    "softmin",
    "softplus",
    "gelu",
    "logsumexp",
    # normalization
    "layer_norm",
    "rms_norm",
    "group_norm",
    "batch_norm",
    "normalize",
    "cosine_similarity",
    # variance-style reductions
    "norm",
    "var",
    "std",
    "cumsum",
    "cumprod",
    # losses
    "mse_loss",
    "l1_loss",
    "smooth_l1_loss",
    "nll_loss",
    "cross_entropy",
    "kl_div",
    "poisson_nll_loss",
    "binary_cross_entropy_with_logits",
]

# -- mixed-argument math: cast every float arg to the widest dtype -------
PROMOTE_FUNCS = [
    "add",
    "mul",
    "div",
    "atan2",
]

# sequence-taking variants of the same (ref torch_overrides.py:116-133)
SEQUENCE_CASTS = [
    "cat",
    "stack",
    "concatenate",
]

# -- run in whatever dtype the input already has -------------------------
MATCH_INPUT_FUNCS = [
    "relu",
    "tanh",
    "sigmoid",
    "silu",
]

BANNED_MESSAGE = (
    "amp does not work out-of-the-box with `binary_cross_entropy` on "
    "probabilities: a sigmoid output saturates to exactly 0/1 in 16-bit "
    "and the loss gradient blows up. Use "
    "`binary_cross_entropy_with_logits` (sigmoid fused into the loss, "
    "classified fp32 here), or if you really know what you are doing "
    "pass allow_banned=True to amp.initialize. "
    "(ref: apex/amp/lists/functional_overrides.py:95-107)"
)

BANNED_FUNCS = [("binary_cross_entropy", BANNED_MESSAGE)]


# attribute stamped on every wrapper register_defaults installs, so a
# repeated call (or an alias pair like linear/dense resolving to an
# already-wrapped function) can never stack a second cast wrapper —
# double-wrapping double-casts every call and breaks disable_casts
_WRAPPED_FLAG = "_apex_tpu_amp_wrapped"


def register_defaults(module, compute_dtype="float16") -> int:
    """Apply the default classification to ``module`` in place.

    For each table name present on ``module``, rebinds it through the
    matching ``amp.functional`` decorator (the reference's amp.init
    patching pass, ref: apex/amp/amp.py:75-198, applied eagerly to one
    namespace). Idempotent: functions already wrapped by a previous
    call (marked with a wrapper attribute) are skipped, so re-running
    amp.initialize never stacks casts. Returns the number of functions
    NEWLY rebound.
    """
    import jax.numpy as jnp

    from apex_tpu.amp import functional as afn

    compute = (afn.bfloat16_function
               if jnp.dtype(compute_dtype) == jnp.dtype(jnp.bfloat16)
               else afn.half_function)
    n = 0
    for names, deco in (
        (COMPUTE_FUNCS, compute),
        (FP32_FUNCS, afn.float_function),
        (PROMOTE_FUNCS + SEQUENCE_CASTS, afn.promote_function),
    ):
        for name in names:
            fn = getattr(module, name, None)
            if not callable(fn) or getattr(fn, _WRAPPED_FLAG, False):
                continue
            wrapped = deco(fn)
            try:
                setattr(wrapped, _WRAPPED_FLAG, True)
            except (AttributeError, TypeError):
                pass      # non-function callable; wrap but can't mark
            setattr(module, name, wrapped)
            n += 1
    return n


__all__ = [
    "COMPUTE_FUNCS", "FP16_FUNCS", "BFLOAT16_FUNCS", "FP32_FUNCS",
    "PROMOTE_FUNCS", "SEQUENCE_CASTS", "MATCH_INPUT_FUNCS",
    "BANNED_FUNCS", "BANNED_MESSAGE", "register_defaults",
]

"""Process-level active amp policy (ref: apex/amp/_amp_state.py:1-70).

The reference keeps a module-global ``_amp_state`` that its patched
torch functions consult at call time. The TPU-native equivalent is the
same idea one level up: :mod:`apex_tpu.amp.nn_functional` wrappers read
the policy registered here *at trace time* (everything under ``jit`` is
traced once, so the policy is baked into the compiled program — exactly
the static behavior the reference's per-call checks approximate).

``amp.initialize`` activates the policy; ``policy_scope`` scopes one;
``amp.disable_casts`` suspends casting inside a block
(ref: apex/amp/handle.py:163-167, here actually meaningful again).
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

_active_props: Optional[Any] = None
_casts_disabled: int = 0
allow_banned: bool = False


def set_active(props: Optional[Any]) -> None:
    global _active_props
    _active_props = props


def get_active() -> Optional[Any]:
    return _active_props


def casts_enabled() -> bool:
    return _casts_disabled == 0


def active_compute_dtype():
    """The dtype whitelist ops should run in right now, or None when
    no patch-style policy (O1/O4) is active or casts are suspended."""
    if not casts_enabled() or _active_props is None:
        return None
    return getattr(_active_props, "compute_dtype", None)


@contextlib.contextmanager
def policy_scope(props: Optional[Any]):
    """Activate ``props`` for the duration of the block (the scoped
    alternative to ``amp.initialize``'s process-global activation)."""
    global _active_props
    prev = _active_props
    _active_props = props
    try:
        yield
    finally:
        _active_props = prev


@contextlib.contextmanager
def suspend_casts():
    global _casts_disabled
    _casts_disabled += 1
    try:
        yield
    finally:
        _casts_disabled -= 1


__all__ = [
    "set_active", "get_active", "casts_enabled", "active_compute_dtype",
    "policy_scope", "suspend_casts", "allow_banned",
]

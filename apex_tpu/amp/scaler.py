"""Functional loss scaler.

TPU re-design of the reference's LossScaler (ref: apex/amp/scaler.py:42-226):
static or dynamic scaling with the exact dynamic schedule — init 2^16,
x2 every 2000 unskipped steps, /2 on overflow, clamped — but expressed as
a carried ``ScalerState`` updated with ``jnp.where``/``lax.cond`` inside
jit, instead of a Python-side object that patches ``optimizer.step``
(ref: apex/amp/handle.py:127-154). A skipped step is the caller gating
the optimizer update on ``found_inf`` (see FlatFusedOptimizer.step's
``skip_if_nonfinite``).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class ScalerState(NamedTuple):
    """Carried loss-scale state (a valid pytree; jit/scan friendly)."""

    loss_scale: jax.Array     # f32
    unskipped: jax.Array      # i32 consecutive unskipped steps
    found_inf: jax.Array      # f32 {0,1} from the last update


class LossScaler:
    """Static or dynamic loss scaler.

    ``loss_scale="dynamic"`` reproduces the reference's schedule
    (apex/amp/scaler.py:14-18,206-226): start at 2^16, halve on overflow
    (floored at ``min_loss_scale``), double after ``scale_window``
    consecutive good steps (capped at ``max_loss_scale``, default 2^24).
    """

    def __init__(
        self,
        loss_scale="dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
    ):
        self.dynamic = loss_scale == "dynamic"
        self._static_scale = 1.0 if self.dynamic else float(loss_scale)
        self.init_scale = init_scale if self.dynamic else self._static_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale

    def init(self) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(self.init_scale, jnp.float32),
            unskipped=jnp.zeros((), jnp.int32),
            found_inf=jnp.zeros((), jnp.float32),
        )

    # -- hot-loop ops ------------------------------------------------------

    def scale_loss(self, loss: jax.Array, state: ScalerState) -> jax.Array:
        """yield loss.float() * scale (ref: apex/amp/handle.py:113)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, grads: Any, state: ScalerState) -> Tuple[Any, jax.Array]:
        """Unscale a grad pytree and report found_inf.

        The fused-buffer path (ref multi_tensor_scale unscaling,
        apex/amp/scaler.py:123-126) lives in the fused optimizers'
        ``grad_scale`` argument; this tree version serves unfused loops.
        """
        inv = 1.0 / state.loss_scale
        unscaled = jax.tree.map(lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        leaves = jax.tree.leaves(unscaled)
        finite = jnp.bool_(True)
        for l in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(l)))
        return unscaled, jnp.where(finite, 0.0, 1.0).astype(jnp.float32)

    def update(self, state: ScalerState, found_inf: jax.Array) -> ScalerState:
        """Advance scale state after a step attempt
        (ref: apex/amp/scaler.py:206-226)."""
        found_inf = jnp.asarray(found_inf, jnp.float32)
        if not self.dynamic:
            return ScalerState(
                loss_scale=state.loss_scale,
                unskipped=state.unskipped + jnp.where(found_inf > 0, 0, 1).astype(jnp.int32),
                found_inf=found_inf,
            )
        overflow = found_inf > 0
        backed_off = state.loss_scale / self.scale_factor
        if self.min_loss_scale is not None:
            backed_off = jnp.maximum(backed_off, self.min_loss_scale)
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        grow = unskipped >= self.scale_window
        grown = jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale)
        new_scale = jnp.where(overflow, backed_off, jnp.where(grow, grown, state.loss_scale))
        unskipped = jnp.where(grow & ~overflow, 0, unskipped)
        return ScalerState(
            loss_scale=new_scale.astype(jnp.float32),
            unskipped=unskipped.astype(jnp.int32),
            found_inf=found_inf,
        )

    # -- (de)serialization: ref apex/amp/frontend.py:434-473 ---------------

    def state_dict(self, state: ScalerState) -> Dict[str, Any]:
        """Full (de)serializable state — including ``found_inf``, so a
        checkpoint written right after a skipped step resumes with the
        skip visible (the resilience checkpoint payload embeds exactly
        this dict; apex_tpu/resilience/checkpoint.py)."""
        return {
            "loss_scale": float(state.loss_scale),
            "unskipped": int(state.unskipped),
            "found_inf": float(state.found_inf),
        }

    def load_state_dict(self, d: Dict[str, Any]) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            # pre-found_inf checkpoints (and the reference's state_dict
            # shape) default to "last step was clean"
            found_inf=jnp.asarray(d.get("found_inf", 0.0), jnp.float32),
        )

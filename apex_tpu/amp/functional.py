"""Function-level precision control.

The reference patches torch namespaces with cast wrappers driven by
FP16/BF16 whitelists and FP32 blacklists (ref: apex/amp/amp.py:75-198,
apex/amp/wrap.py:10-286, apex/amp/lists/functional_overrides.py:18-92).
JAX functions cannot (and should not) be monkey-patched; the equivalent
control points are explicit decorators applied where a function is
*defined or used*, with the same names as the reference's registration
API (ref: apex/amp/amp.py:29-44).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp


def _cast_floats(tree: Any, dtype) -> Any:
    def cast(x):
        if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
            jnp.asarray(x).dtype, jnp.floating
        ):
            return jnp.asarray(x).astype(dtype)
        return x

    return jax.tree.map(cast, tree)


def _cast_function(fn: Callable, dtype) -> Callable:
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        args = _cast_floats(args, dtype)
        kwargs = _cast_floats(kwargs, dtype)
        return fn(*args, **kwargs)

    return wrapper


def half_function(fn: Callable) -> Callable:
    """Run ``fn`` with float16 inputs (ref: apex/amp/amp.py:29-31)."""
    return _cast_function(fn, jnp.float16)


def bfloat16_function(fn: Callable) -> Callable:
    """Run ``fn`` with bfloat16 inputs (ref fork: apex/amp/amp.py:33-35)."""
    return _cast_function(fn, jnp.bfloat16)


def float_function(fn: Callable) -> Callable:
    """Run ``fn`` with float32 inputs (ref: apex/amp/amp.py:37-39)."""
    return _cast_function(fn, jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Run ``fn`` with all float args promoted to the widest float dtype
    among them (ref: apex/amp/wrap.py promote/sequence_promote)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        leaves = [
            l
            for l in jax.tree.leaves((args, kwargs))
            if isinstance(l, (jax.Array, jnp.ndarray))
            and jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
        ]
        if not leaves:
            return fn(*args, **kwargs)
        widest = jnp.result_type(*[l.dtype for l in leaves])
        return fn(*_cast_floats(args, widest), **_cast_floats(kwargs, widest))

    return wrapper


def compute_cast(fn: Callable, compute_dtype) -> Callable:
    """Cast inputs to ``compute_dtype`` and outputs back to fp32 — the
    O1/O4 'patched forward' behavior at one boundary
    (ref: apex/amp/_initialize.py:196-203 patches model.forward)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        out = fn(*_cast_floats(args, compute_dtype),
                 **_cast_floats(kwargs, compute_dtype))
        return _cast_floats(out, jnp.float32)

    return wrapper


def _register(module, name: str, deco: Callable) -> None:
    fn = getattr(module, name)
    setattr(module, name, deco(fn))


def register_half_function(module, function_name: str) -> None:
    """Replace ``module.function_name`` with its half_function-wrapped
    form (ref: apex/amp/amp.py:48-53 registry + patch; here the rebind
    happens immediately — there is no deferred amp.init patching pass)."""
    _register(module, function_name, half_function)


def register_bfloat16_function(module, function_name: str) -> None:
    """ref fork: apex/amp/amp.py:55-59."""
    _register(module, function_name, bfloat16_function)


def register_float_function(module, function_name: str) -> None:
    """ref: apex/amp/amp.py:61-65."""
    _register(module, function_name, float_function)


def register_promote_function(module, function_name: str) -> None:
    """ref: apex/amp/amp.py:67-71."""
    _register(module, function_name, promote_function)

"""amp opt levels and initialize — precision policies, not monkey-patches.

The reference configures mixed precision through opt levels O0-O5
(ref: apex/amp/frontend.py:119-255) implemented by patching torch
namespaces and optimizer methods. The TPU-native design keeps the same
user-facing opt-level semantics as explicit *policies*:

  O0  fp32 everywhere, no scaling                  (frontend.py:119-135)
  O1  mixed: whitelist ops in fp16, dynamic scale  (frontend.py:137-160)
  O2  cast model fp16, fp32 master, dynamic scale  (frontend.py:162-186)
  O3  pure fp16                                    (frontend.py:188-206)
  O4  mixed bf16, no loss scaling                  (frontend.py:208-226, fork-only)
  O5  cast model bf16, fp32 master                 (frontend.py:228-247, fork-only)

O4/O5 are the natural TPU modes. "Patching functions" becomes a compute
dtype applied at module boundaries (`Policy.compute_dtype` consumed by
apex_tpu layers and the `half_function`-style decorators in
`apex_tpu.amp.functional`); "casting the model" becomes casting the
param pytree with batchnorm params optionally kept fp32.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, ScalerState

# parameters that stay fp32 when keep_batchnorm_fp32 is set; matched
# against the '/'-joined pytree path (flax naming: BatchNorm_0, bn, ...)
_BN_PATTERN = re.compile(r"(batch_?norm|(^|[/_])bn\d*([/_]|$)|group_?norm)", re.I)


@dataclasses.dataclass(frozen=True)
class Properties:
    """Validated option struct (ref: apex/amp/frontend.py:8-114)."""

    opt_level: str
    cast_model_type: Optional[Any]      # dtype params are cast to (O2/O3/O5)
    compute_dtype: Optional[Any]        # dtype whitelist ops run in (O1/O4)
    keep_batchnorm_fp32: bool
    master_weights: bool
    loss_scale: Any                     # "dynamic" | float | None

    def __post_init__(self):
        if self.cast_model_type is not None and self.compute_dtype is not None:
            raise ValueError(
                "cast_model_type and compute_dtype are mutually exclusive "
                "(patch-style vs cast-style opt levels)"
            )


def _props(opt_level, cast=None, compute=None, keep_bn=False, master=False,
           loss_scale=None) -> Properties:
    return Properties(
        opt_level=opt_level, cast_model_type=cast, compute_dtype=compute,
        keep_batchnorm_fp32=keep_bn, master_weights=master,
        loss_scale=loss_scale,
    )


OPT_LEVELS: Dict[str, Properties] = {
    "O0": _props("O0"),
    "O1": _props("O1", compute=jnp.float16, loss_scale="dynamic"),
    "O2": _props("O2", cast=jnp.float16, keep_bn=True, master=True,
                 loss_scale="dynamic"),
    "O3": _props("O3", cast=jnp.float16),
    "O4": _props("O4", compute=jnp.bfloat16),
    "O5": _props("O5", cast=jnp.bfloat16, keep_bn=True, master=True),
}


class AmpState(NamedTuple):
    """Carried amp state: one ScalerState per loss
    (ref: apex/amp/_initialize.py:229-233 creates num_losses scalers)."""

    properties: Properties            # static
    scalers: Tuple[ScalerState, ...]


# registered static so AmpState is a pytree with only scaler leaves
jax.tree_util.register_static(Properties)


def _path_name(path) -> str:
    """Join a pytree key path to a '/'-separated name string."""
    return "/".join(str(getattr(k, "key", getattr(k, "name", k))) for k in path)


def _cast_params(params: Any, dtype, keep_batchnorm_fp32: bool) -> Any:
    """Cast a param pytree, optionally keeping norm params fp32
    (ref: apex/amp/_initialize.py:178-184 convert_network)."""

    def cast(path, leaf):
        # accept jax arrays AND numpy leaves (checkpoints often load as
        # numpy); skip anything without a float dtype
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
            leaf.dtype, jnp.floating
        ):
            return leaf
        if not isinstance(leaf, (jax.Array, jnp.ndarray)):
            leaf = jnp.asarray(leaf)
        if keep_batchnorm_fp32:
            if _BN_PATTERN.search(_path_name(path)):
                return leaf.astype(jnp.float32)
        return leaf.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def initialize(
    params: Any,
    optimizers=None,
    opt_level: str = "O1",
    num_losses: int = 1,
    cast_model_type=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    min_loss_scale=None,
    max_loss_scale=2.0 ** 24,
    allow_banned=False,
):
    """Configure mixed precision (ref: apex/amp/frontend.py:259-431).

    Returns ``(cast_params, amp_state)`` — and, if ``optimizers`` is
    given (a FlatFusedOptimizer or list), their states initialized from
    the *fp32 master view* appended: ``(params, opt_states, amp_state)``.

    Unlike the reference there is nothing to patch: the returned params
    are the cast pytree, and `amp_state.scalers` carry the loss scales
    through the training loop functionally.
    """
    if opt_level not in OPT_LEVELS:
        raise ValueError(f"Unexpected opt_level {opt_level!r}; expected O0..O5")
    base = OPT_LEVELS[opt_level]
    props = Properties(
        opt_level=opt_level,
        cast_model_type=cast_model_type if cast_model_type is not None else base.cast_model_type,
        compute_dtype=base.compute_dtype,
        keep_batchnorm_fp32=(
            keep_batchnorm_fp32 if keep_batchnorm_fp32 is not None else base.keep_batchnorm_fp32
        ),
        master_weights=master_weights if master_weights is not None else base.master_weights,
        loss_scale=loss_scale if loss_scale is not None else base.loss_scale,
    )

    # activate the policy for the shipped functional namespace (amp.F
    # consults it at trace time — the analog of the reference's
    # amp.init patching pass, ref apex/amp/_initialize.py:229-263)
    from apex_tpu.amp import _amp_state
    _amp_state.set_active(props)
    _amp_state.allow_banned = bool(allow_banned)

    cast_params = params
    if props.cast_model_type is not None:
        cast_params = _cast_params(
            params, props.cast_model_type, props.keep_batchnorm_fp32
        )

    scaler = make_scaler(props, min_loss_scale=min_loss_scale,
                         max_loss_scale=max_loss_scale)
    amp_state = AmpState(
        properties=props,
        scalers=tuple(scaler.init() for _ in range(num_losses)),
    )

    if optimizers is None:
        return cast_params, amp_state
    single = not isinstance(optimizers, (list, tuple))
    opts = [optimizers] if single else list(optimizers)
    # master weights are created from the ORIGINAL fp32 params, exactly as
    # the reference stashes fp32 masters before the model cast
    # (apex/amp/_process_optimizer.py:28-90)
    opt_states = [o.init(params) for o in opts]
    return cast_params, (opt_states[0] if single else opt_states), amp_state


def make_scaler(props: Properties, min_loss_scale=None,
                max_loss_scale=2.0 ** 24) -> LossScaler:
    """Build the LossScaler implied by a Properties object."""
    if props.loss_scale is None:
        return LossScaler(loss_scale=1.0)
    return LossScaler(
        loss_scale=props.loss_scale,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale,
    )


# -- scaler state (de)serialization (ref: apex/amp/frontend.py:434-473) ----


def state_dict(amp_state: AmpState) -> Dict[str, Any]:
    return {
        f"loss_scaler{i}": {
            "loss_scale": float(s.loss_scale),
            "unskipped": int(s.unskipped),
        }
        for i, s in enumerate(amp_state.scalers)
    }


def load_state_dict(amp_state: AmpState, d: Dict[str, Any]) -> AmpState:
    scalers = []
    for i, s in enumerate(amp_state.scalers):
        key = f"loss_scaler{i}"
        if key in d:
            scalers.append(
                ScalerState(
                    loss_scale=jnp.asarray(d[key]["loss_scale"], jnp.float32),
                    unskipped=jnp.asarray(d[key]["unskipped"], jnp.int32),
                    found_inf=jnp.zeros((), jnp.float32),
                )
            )
        else:
            scalers.append(s)
    return AmpState(properties=amp_state.properties, scalers=tuple(scalers))


def master_params(optimizer, state):
    """fp32 master-weight view of a fused optimizer's state
    (ref: apex/amp/_amp_state.py:49-59 master_params(optimizer) iterator;
    functional form takes the carried state). Yields leaves, matching the
    reference's flat iteration order."""
    return iter(jax.tree.leaves(optimizer.master_params(state)))

"""Mixture-of-Experts: group-GEMM ops + expert-parallel layer.

The reference has no MoE module, but BASELINE configs[4] specifies a
"group-GEMM / fused_dense MoE-style expert-parallel microbench" built
from the fused-dense analogs (ref: apex/fused_dense/fused_dense.py,
csrc/fused_dense_cuda.cu — cublasLt grouped/batched GEMMs). The TPU
design provides two complementary paths:

  - **Dropless (megablocks-style)** — :func:`group_gemm` wraps
    ``lax.ragged_dot`` (the TPU group-GEMM primitive: one MXU pass over
    tokens sorted by expert with per-expert group sizes) and
    :class:`GroupedMLP` runs router -> sort -> ragged fc1/gelu/fc2 ->
    unsort -> weighted combine with NO token dropping. Static shapes
    throughout (sort + bincount), so it jits cleanly.
  - **Capacity-based expert parallel (GShard/Switch-style)** —
    :class:`ExpertParallelMLP` dispatches tokens into a fixed
    (experts, capacity) buffer via one-hot/cumsum masks, runs batched
    expert matmuls, and — inside ``shard_map`` over the "expert" mesh
    axis — exchanges the expert dimension with ``lax.all_to_all`` so
    each device computes only its local experts. This is the
    all-to-all EP pattern that rides ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import EXPERT_AXIS
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis


def group_gemm(
    tokens: jax.Array,
    weights: jax.Array,
    group_sizes: jax.Array,
) -> jax.Array:
    """Grouped matmul: row block g of ``tokens`` (rows assigned to group
    g, contiguous, sizes ``group_sizes``) hits ``weights[g]``.

    tokens (n, k), weights (E, k, m), group_sizes (E,) int32 summing to
    <= n. The TPU lowering tiles each group onto the MXU without
    padding tokens to per-expert capacity — the group-GEMM of the
    reference's cublasLt grouped-batched path (ref: setup.py:376-388
    fused_dense_cuda).
    """
    return lax.ragged_dot(
        tokens, weights, group_sizes,
        preferred_element_type=jnp.float32,
    ).astype(tokens.dtype)


def router_topk(
    x: jax.Array,
    gate_kernel: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k softmax routing. x (n, h), gate (h, E) ->
    (weights (n, k) fp32 normalized over the chosen k, expert_ids
    (n, k) int32, full probs (n, E) fp32 for aux losses)."""
    logits = jnp.einsum(
        "nh,he->ne", x, gate_kernel, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_ids = lax.top_k(probs, k)
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    return top_vals, top_ids.astype(jnp.int32), probs


def load_balancing_loss(probs: jax.Array, expert_ids: jax.Array) -> jax.Array:
    """Switch-transformer aux loss: E * sum_e f_e * P_e, where f_e is
    the fraction of tokens whose top-1 choice is e and P_e the mean
    router probability of e."""
    E = probs.shape[-1]
    f = jnp.mean(
        jax.nn.one_hot(expert_ids[..., 0], E, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return E * jnp.sum(f * p)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


class GroupedMLP(nn.Module):
    """Dropless MoE MLP via sort + group-GEMM (single device, or the
    per-shard compute of a dropless EP layer). Input (n, h) tokens."""

    config: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        n, h = x.shape
        E, k = cfg.num_experts, cfg.top_k
        gate = self.param("gate", nn.initializers.normal(stddev=0.02),
                          (h, E), cfg.param_dtype)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, h, cfg.ffn_hidden_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, cfg.ffn_hidden_size, h), cfg.param_dtype)

        weights, ids, probs = router_topk(x, gate.astype(cfg.dtype), k)
        self.sow("intermediates", "aux_loss",
                 load_balancing_loss(probs, ids))

        # flatten k copies, stable-sort by expert so groups are contiguous
        flat_ids = ids.reshape(-1)                     # (n*k,)
        order = jnp.argsort(flat_ids, stable=True)
        inv = jnp.argsort(order)
        tok_rep = jnp.repeat(x, k, axis=0)[order]      # (n*k, h) sorted
        group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)

        h1 = group_gemm(tok_rep.astype(cfg.dtype), w1.astype(cfg.dtype),
                        group_sizes)
        h1 = jax.nn.gelu(h1, approximate=True)
        h2 = group_gemm(h1, w2.astype(cfg.dtype), group_sizes)

        out = h2[inv].reshape(n, k, h)                 # back to token order
        return jnp.sum(out * weights[..., None].astype(cfg.dtype), axis=1)


class ExpertParallelMLP(nn.Module):
    """Capacity-based MoE MLP, expert-parallel over the "expert" mesh
    axis when called inside shard_map (dense fallback otherwise).

    Dispatch: one-hot position-in-expert masks (static (n, E, C)
    shapes), batched expert GEMMs, combine with router weights. Under
    EP each device holds E/ep experts; two ``all_to_all`` exchanges move
    the dispatched buffer expert-major -> token-major and back.
    Tokens over a full expert's capacity are dropped (their output is
    the zero vector), matching Switch/GShard semantics.
    """

    config: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        n, h = x.shape
        E, k = cfg.num_experts, cfg.top_k
        C = max(1, int(cfg.capacity_factor * n * k / E))
        gate = self.param("gate", nn.initializers.normal(stddev=0.02),
                          (h, E), cfg.param_dtype)
        inside = _inside_axis(EXPERT_AXIS)
        ep = lax.axis_size(EXPERT_AXIS) if inside else 1
        if E % ep:
            raise ValueError(f"num_experts {E} not divisible by ep={ep}")
        e_local = E // ep
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (e_local, h, cfg.ffn_hidden_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (e_local, cfg.ffn_hidden_size, h), cfg.param_dtype)

        weights, ids, probs = router_topk(x, gate.astype(cfg.dtype), k)
        self.sow("intermediates", "aux_loss",
                 load_balancing_loss(probs, ids))

        # position of each (token, choice) within its expert's buffer:
        # cumsum over the flattened (choice-major) one-hot stream so
        # earlier tokens / lower k win capacity slots. O(n*k*E) ints —
        # the (expert, capacity) buffers below are built by scatter /
        # gather instead of dispatch-mask einsums, so nothing of size
        # (n, E, C) is ever materialized (C grows with n).
        onehot = jax.nn.one_hot(ids, E, dtype=jnp.int32)   # (n, k, E)
        flat = onehot.transpose(1, 0, 2).reshape(k * n, E)
        pos_flat = jnp.cumsum(flat, axis=0) - 1            # (k*n, E)
        pos = (pos_flat * flat).sum(-1).reshape(k, n).transpose(1, 0)  # (n,k)
        keep = pos < C

        # scatter token copies into the (E*C, h) buffer; dropped copies
        # get an out-of-range destination and fall away (mode="drop")
        dest = jnp.where(keep, ids * C + pos, E * C).reshape(-1)   # (n*k,)
        x_rep = jnp.repeat(x.astype(cfg.dtype), k, axis=0)         # (n*k, h)
        buf = jnp.zeros((E * C, h), cfg.dtype).at[dest].add(
            x_rep, mode="drop").reshape(E, C, h)

        if inside:
            # (E, C, h) = (ep * e_local, C, h) -> gather every device's
            # slots for MY experts: (e_local, ep * C, h)
            buf = lax.all_to_all(buf, EXPERT_AXIS, split_axis=0,
                                 concat_axis=1, tiled=True)
        h1 = jnp.einsum("ech,ehf->ecf", buf, w1.astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        h1 = jax.nn.gelu(h1, approximate=True)
        h2 = jnp.einsum("ecf,efh->ech", h1, w2.astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        if inside:
            h2 = lax.all_to_all(h2, EXPERT_AXIS, split_axis=1,
                                concat_axis=0, tiled=True)

        # combine: gather each copy's expert output and weight it
        out = jnp.take(h2.reshape(E * C, h), jnp.minimum(dest, E * C - 1),
                       axis=0)                                     # (n*k, h)
        w = (weights.reshape(-1) * keep.reshape(-1)).astype(cfg.dtype)
        return jnp.sum((out * w[:, None]).reshape(n, k, h), axis=1)


__all__ = [
    "ExpertParallelMLP",
    "GroupedMLP",
    "MoEConfig",
    "group_gemm",
    "load_balancing_loss",
    "router_topk",
]

"""Mixture-of-Experts: group-GEMM ops + expert-parallel layers.

The reference has no MoE module, but BASELINE configs[4] specifies a
"group-GEMM / fused_dense MoE-style expert-parallel microbench" built
from the fused-dense analogs (ref: apex/fused_dense/fused_dense.py,
csrc/fused_dense_cuda.cu — cublasLt grouped/batched GEMMs). The TPU
design provides three complementary paths (docs/moe.md):

  - **Dropless (megablocks-style)** — :func:`group_gemm` wraps
    ``lax.ragged_dot`` (the TPU group-GEMM primitive: one MXU pass over
    tokens sorted by expert with per-expert group sizes) and
    :class:`GroupedMLP` runs router -> sort -> ragged fc1/gelu/fc2 ->
    unsort -> weighted combine with NO token dropping. Static shapes
    throughout (sort + bincount), so it jits cleanly.
  - **Capacity-based expert parallel (GShard/Switch-style)** —
    :class:`ExpertParallelMLP` dispatches tokens into a fixed
    (experts, capacity) buffer via one-hot/cumsum masks, runs batched
    expert matmuls, and — inside ``shard_map`` over the "expert" mesh
    axis — exchanges the expert dimension with ``lax.all_to_all`` so
    each device computes only its local experts. This is the legacy
    explicit-collective toolbox variant of the all-to-all EP pattern.
  - **Mesh-native (GSPMD)** — :class:`MoEMLP` is the
    :class:`~apex_tpu.models.gpt.GPTLayer` drop-in: expert params
    shard on the mesh's ``model`` axis via NamedShardings
    (``gpt_param_specs``) and in-jit ``with_sharding_constraint``
    hints, so XLA lowers the capacity dispatch/combine layout changes
    to the token all-to-all — no shard_map anywhere on this path
    (docs/mesh.md). Both ``impl="dropless"`` and ``impl="capacity"``
    ride it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import annotate as _gspmd
from apex_tpu.transformer.parallel_state import EXPERT_AXIS
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis


def group_gemm(
    tokens: jax.Array,
    weights: jax.Array,
    group_sizes: jax.Array,
) -> jax.Array:
    """Grouped matmul: row block g of ``tokens`` (rows assigned to group
    g, contiguous, sizes ``group_sizes``) hits ``weights[g]``.

    tokens (n, k), weights (E, k, m), group_sizes (E,) int32 summing to
    <= n. The TPU lowering tiles each group onto the MXU without
    padding tokens to per-expert capacity — the group-GEMM of the
    reference's cublasLt grouped-batched path (ref: setup.py:376-388
    fused_dense_cuda).

    No ``preferred_element_type`` here: ``ragged_dot``'s transpose
    rule emits cotangents in the accumulator dtype, so a f32
    accumulator under bf16 operands breaks the backward pass with a
    dtype mismatch. The MXU accumulates bf16 matmuls in f32
    regardless.
    """
    return lax.ragged_dot(tokens, weights, group_sizes)


def router_topk(
    x: jax.Array,
    gate_kernel: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k softmax routing. x (n, h), gate (h, E) ->
    (weights (n, k) fp32 normalized over the chosen k, expert_ids
    (n, k) int32, full probs (n, E) fp32 for aux losses)."""
    logits = jnp.einsum(
        "nh,he->ne", x, gate_kernel, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_ids = lax.top_k(probs, k)
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    return top_vals, top_ids.astype(jnp.int32), probs


def load_balancing_loss(probs: jax.Array, expert_ids: jax.Array) -> jax.Array:
    """Switch-transformer aux loss: E * sum_e f_e * P_e, where f_e is
    the fraction of tokens whose top-1 choice is e and P_e the mean
    router probability of e."""
    E = probs.shape[-1]
    f = jnp.mean(
        jax.nn.one_hot(expert_ids[..., 0], E, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return E * jnp.sum(f * p)


def expert_load(expert_ids: jax.Array, num_experts: int) -> jax.Array:
    """(E,) fp32 count of (token, choice) assignments per expert — the
    in-jit histogram behind the ``moe_expert_load{expert=}`` gauges."""
    onehot = jax.nn.one_hot(expert_ids, num_experts, dtype=jnp.float32)
    return jnp.sum(onehot.reshape(-1, num_experts), axis=0)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def _dropless_experts(x, weights, ids, w1, w2, cfg: MoEConfig):
    """Sort + group-GEMM expert compute over (n, h) tokens; returns
    (combined (n, h), dropped scalar — always 0: dropless)."""
    n, h = x.shape
    E, k = cfg.num_experts, cfg.top_k
    # flatten k copies, stable-sort by expert so groups are contiguous
    flat_ids = ids.reshape(-1)                     # (n*k,)
    order = jnp.argsort(flat_ids, stable=True)
    inv = jnp.argsort(order)
    tok_rep = jnp.repeat(x, k, axis=0)[order]      # (n*k, h) sorted
    group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)

    h1 = group_gemm(tok_rep.astype(cfg.dtype), w1.astype(cfg.dtype),
                    group_sizes)
    h1 = jax.nn.gelu(h1, approximate=True)
    h2 = group_gemm(h1, w2.astype(cfg.dtype), group_sizes)

    out = h2[inv].reshape(n, k, h)                 # back to token order
    out = jnp.sum(out * weights[..., None].astype(cfg.dtype), axis=1)
    return out, jnp.zeros((), jnp.float32)


def _capacity_dispatch(x, weights, ids, cfg: MoEConfig):
    """The GShard dispatch bookkeeping over (n, h) tokens: scatter the
    token copies into an (E, C, h) buffer. Returns
    (buf, dest (n*k,), keep (n, k), capacity)."""
    n, h = x.shape
    E, k = cfg.num_experts, cfg.top_k
    C = max(1, int(cfg.capacity_factor * n * k / E))
    # position of each (token, choice) within its expert's buffer:
    # cumsum over the flattened (choice-major) one-hot stream so
    # earlier tokens / lower k win capacity slots. O(n*k*E) ints —
    # the (expert, capacity) buffers below are built by scatter /
    # gather instead of dispatch-mask einsums, so nothing of size
    # (n, E, C) is ever materialized (C grows with n).
    onehot = jax.nn.one_hot(ids, E, dtype=jnp.int32)   # (n, k, E)
    flat = onehot.transpose(1, 0, 2).reshape(k * n, E)
    pos_flat = jnp.cumsum(flat, axis=0) - 1            # (k*n, E)
    pos = (pos_flat * flat).sum(-1).reshape(k, n).transpose(1, 0)  # (n,k)
    keep = pos < C

    # scatter token copies into the (E*C, h) buffer; dropped copies
    # get an out-of-range destination and fall away (mode="drop")
    dest = jnp.where(keep, ids * C + pos, E * C).reshape(-1)   # (n*k,)
    x_rep = jnp.repeat(x.astype(cfg.dtype), k, axis=0)         # (n*k, h)
    buf = jnp.zeros((E * C, h), cfg.dtype).at[dest].add(
        x_rep, mode="drop").reshape(E, C, h)
    return buf, dest, keep, C


def _capacity_combine(h2, dest, keep, weights, n: int, cfg: MoEConfig):
    """Gather each token copy's expert output and combine with the
    router weights (dropped copies contribute zero)."""
    E, k = cfg.num_experts, cfg.top_k
    h = h2.shape[-1]
    C = h2.shape[1]
    out = jnp.take(h2.reshape(E * C, h), jnp.minimum(dest, E * C - 1),
                   axis=0)                                     # (n*k, h)
    w = (weights.reshape(-1) * keep.reshape(-1)).astype(cfg.dtype)
    return jnp.sum((out * w[:, None]).reshape(n, k, h), axis=1)


class GroupedMLP(nn.Module):
    """Dropless MoE MLP via sort + group-GEMM (single device, or the
    per-shard compute of a dropless EP layer). Input (n, h) tokens.

    ``return_stats=True`` additionally returns the per-call stats dict
    (``aux_loss`` scalar, ``expert_load`` (E,), ``dropped`` scalar —
    always 0 here, ``keep`` (n, k) all-True mask)."""

    config: MoEConfig

    @nn.compact
    def __call__(self, x, *, return_stats: bool = False):
        cfg = self.config
        n, h = x.shape
        E, k = cfg.num_experts, cfg.top_k
        gate = self.param("gate", nn.initializers.normal(stddev=0.02),
                          (h, E), cfg.param_dtype)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, h, cfg.ffn_hidden_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, cfg.ffn_hidden_size, h), cfg.param_dtype)

        weights, ids, probs = router_topk(x, gate.astype(cfg.dtype), k)
        aux = load_balancing_loss(probs, ids)
        self.sow("intermediates", "aux_loss", aux)

        out, dropped = _dropless_experts(x, weights, ids, w1, w2, cfg)
        if return_stats:
            return out, {"aux_loss": aux,
                         "expert_load": expert_load(ids, E),
                         "dropped": dropped,
                         "keep": jnp.ones((n, k), bool)}
        return out


class ExpertParallelMLP(nn.Module):
    """Capacity-based MoE MLP, expert-parallel over the "expert" mesh
    axis when called inside shard_map (dense fallback otherwise).

    Dispatch: one-hot position-in-expert masks (static (n, E, C)
    shapes), batched expert GEMMs, combine with router weights. Under
    EP each device holds E/ep experts; two ``all_to_all`` exchanges move
    the dispatched buffer expert-major -> token-major and back.
    Tokens over a full expert's capacity are dropped (their output is
    the zero vector), matching Switch/GShard semantics.

    Drops are never silent: the count is sown as the ``moe_dropped``
    intermediate, and ``return_stats=True`` returns the full stats
    dict — ``aux_loss``, ``expert_load`` (E,), ``dropped`` scalar, and
    the per-(token, choice) ``keep`` (n, k) drop mask — so callers can
    publish ``moe_dropped_tokens`` (telemetry/moe.py)."""

    config: MoEConfig

    @nn.compact
    def __call__(self, x, *, return_stats: bool = False):
        cfg = self.config
        n, h = x.shape
        E, k = cfg.num_experts, cfg.top_k
        gate = self.param("gate", nn.initializers.normal(stddev=0.02),
                          (h, E), cfg.param_dtype)
        inside = _inside_axis(EXPERT_AXIS)
        ep = lax.axis_size(EXPERT_AXIS) if inside else 1
        if E % ep:
            raise ValueError(f"num_experts {E} not divisible by ep={ep}")
        e_local = E // ep
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (e_local, h, cfg.ffn_hidden_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (e_local, cfg.ffn_hidden_size, h), cfg.param_dtype)

        weights, ids, probs = router_topk(x, gate.astype(cfg.dtype), k)
        aux = load_balancing_loss(probs, ids)
        self.sow("intermediates", "aux_loss", aux)

        buf, dest, keep, C = _capacity_dispatch(x, weights, ids, cfg)
        if inside:
            # (E, C, h) = (ep * e_local, C, h) -> gather every device's
            # slots for MY experts: (e_local, ep * C, h)
            buf = lax.all_to_all(buf, EXPERT_AXIS, split_axis=0,
                                 concat_axis=1, tiled=True)
        h1 = jnp.einsum("ech,ehf->ecf", buf, w1.astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        h1 = jax.nn.gelu(h1, approximate=True)
        h2 = jnp.einsum("ecf,efh->ech", h1, w2.astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype)
        if inside:
            h2 = lax.all_to_all(h2, EXPERT_AXIS, split_axis=1,
                                concat_axis=0, tiled=True)

        out = _capacity_combine(h2, dest, keep, weights, n, cfg)
        dropped = jnp.sum(1.0 - keep.astype(jnp.float32))
        self.sow("intermediates", "moe_dropped", dropped)
        if return_stats:
            return out, {"aux_loss": aux,
                         "expert_load": expert_load(ids, E),
                         "dropped": dropped,
                         "keep": keep}
        return out


class MoEMLP(nn.Module):
    """Mesh-native MoE MLP — the :class:`~apex_tpu.models.gpt.GPTLayer`
    drop-in replacing :class:`~apex_tpu.models.gpt.ParallelMLP` on MoE
    layers (docs/moe.md).

    Input is the block's seq-major ``(s, b, h)`` activation. Tokens
    flatten batch-major to ``(b*s, h)`` — preserving the mesh's
    ``batch`` split through the flatten — then :func:`router_topk`
    picks ``top_k`` experts per token and one of two implementations
    computes the expert outputs:

    - ``impl="dropless"`` — sort + :func:`group_gemm`
      (:class:`GroupedMLP`'s path): no token is ever dropped. On a
      >1-``model`` mesh the group-GEMM runs replicated
      (``constrain_replicated``): its ragged per-expert groups align
      to no mesh axis, and GSPMD cannot partition ``ragged_dot``
      correctly once a sharding seed touches it — expert weights stay
      expert-sharded at rest and gather at use; the capacity impl is
      the EP-scaled compute path.
    - ``impl="capacity"`` — GShard/Switch ``(E, C)`` buffers built by
      scatter. The buffer's expert dim carries a ``model``-axis
      sharding hint (``annotate.constrain_experts``), so on a
      >1-``model`` mesh XLA lowers the dispatch/combine layout changes
      to the token all-to-all — GSPMD, no shard_map (the legacy
      shard_map variant lives in :class:`ExpertParallelMLP`).

    Expert params — ``gate (h, E)`` replicated, ``w1 (E, h, ffn)`` /
    ``w2 (E, ffn, h)`` sharded on the expert dim — ride
    ``gpt_param_specs`` into training plans and serving checkpoints.

    Each call sows three "intermediates" leaves — ``moe_aux_loss``,
    ``moe_expert_load`` (E,), ``moe_dropped`` — collected by
    :func:`collect_moe_stats` under ``mutable=["intermediates"]``. A
    non-mutable apply (``model.init``, the serving decode path) makes
    the sows no-ops, keeping the checkpoint signature and the compiled
    decode program identical to a stats-blind forward."""

    config: MoEConfig
    impl: str = "dropless"

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        if self.impl not in ("dropless", "capacity"):
            raise ValueError(
                f"MoEMLP impl must be 'dropless' or 'capacity', got "
                f"{self.impl!r}")
        s, b, h = x.shape
        E, k = cfg.num_experts, cfg.top_k
        gate = self.param("gate", nn.initializers.normal(stddev=0.02),
                          (h, E), cfg.param_dtype)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, h, cfg.ffn_hidden_size), cfg.param_dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, cfg.ffn_hidden_size, h), cfg.param_dtype)
        if self.impl == "capacity":
            w1 = _gspmd.constrain_experts(w1)
            w2 = _gspmd.constrain_experts(w2)

        # (s, b, h) -> (b*s, h) batch-major: the leading dim keeps the
        # mesh's batch split, so routing stays a local matmul
        tokens = _gspmd.constrain_batch_major(
            x.transpose(1, 0, 2).reshape(b * s, h))
        n = b * s
        weights, ids, probs = router_topk(tokens, gate.astype(cfg.dtype), k)
        aux = load_balancing_loss(probs, ids)
        load = expert_load(ids, E)

        if self.impl == "dropless":
            # ragged groups align to NO mesh axis: GSPMD cannot
            # partition ragged_dot correctly (the global group sizes
            # don't survive a split of the expert or token dim), so
            # the group-GEMM endpoints pin fully replicated — the
            # capacity impl is the EP-scaled path
            out, dropped = _dropless_experts(
                _gspmd.constrain_replicated(tokens), weights, ids,
                _gspmd.constrain_replicated(w1),
                _gspmd.constrain_replicated(w2), cfg)
            out = _gspmd.constrain_replicated(out)
        else:
            buf, dest, keep, C = _capacity_dispatch(tokens, weights, ids,
                                                    cfg)
            # pin the buffer's expert dim on `model`: this layout
            # change from the token-major scatter IS the dispatch
            # all-to-all once XLA partitions it
            buf = _gspmd.constrain_experts(buf)
            h1 = jnp.einsum(
                "ech,ehf->ecf", buf, w1.astype(cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
            h1 = jax.nn.gelu(h1, approximate=True)
            h2 = jnp.einsum(
                "ecf,efh->ech", h1, w2.astype(cfg.dtype),
                preferred_element_type=jnp.float32).astype(cfg.dtype)
            h2 = _gspmd.constrain_experts(h2)
            out = _capacity_combine(h2, dest, keep, weights, n, cfg)
            dropped = jnp.sum(1.0 - keep.astype(jnp.float32))

        self.sow("intermediates", "moe_aux_loss", aux)
        self.sow("intermediates", "moe_expert_load", load)
        self.sow("intermediates", "moe_dropped", dropped)
        y = out.reshape(b, s, h).transpose(1, 0, 2)
        return _gspmd.constrain_hidden(y)


# -- stats collection ------------------------------------------------------


def collect_moe_stats(variables: Any,
                      num_experts: Optional[int] = None) -> Dict[str, Any]:
    """Fold the sown MoE intermediates of one apply into a flat stats
    dict: ``aux_loss`` (mean over MoE layers), ``expert_load`` ((E,)
    summed over layers), ``dropped`` (scalar sum).

    ``variables`` is the mutated-variables dict a
    ``model.apply(..., mutable=["intermediates"])`` returns (or the
    "intermediates" collection itself); scan-stacked leaves ((L, ...)
    from ``variable_axes={"intermediates": 0}``) and per-layer leaves
    both fold. Pure jnp — callable inside a jitted loss. With no MoE
    sows present, returns zeros ((``num_experts``,) load when given,
    else (0,))."""
    aux, load, dropped = [], [], []
    flat = jax.tree_util.tree_flatten_with_path(variables)[0]
    for path, leaf in flat:
        names = [str(getattr(p, "key", getattr(p, "idx", p)))
                 for p in path]
        if "moe_aux_loss" in names:
            aux.append(leaf)
        elif "moe_expert_load" in names:
            load.append(leaf)
        elif "moe_dropped" in names:
            dropped.append(leaf)
    if not aux:
        E = int(num_experts or 0)
        return {"aux_loss": jnp.zeros((), jnp.float32),
                "expert_load": jnp.zeros((E,), jnp.float32),
                "dropped": jnp.zeros((), jnp.float32)}
    n_layers = sum(int(a.size) for a in aux)
    aux_mean = sum(jnp.sum(a.astype(jnp.float32)) for a in aux) / n_layers
    load_sum = sum(
        jnp.sum(l.astype(jnp.float32).reshape(-1, l.shape[-1]), axis=0)
        for l in load)
    dropped_sum = (sum(jnp.sum(d.astype(jnp.float32)) for d in dropped)
                   if dropped else jnp.zeros((), jnp.float32))
    return {"aux_loss": aux_mean, "expert_load": load_sum,
            "dropped": dropped_sum}


# -- fault drills (resilience/faults.py moe_* clauses) ---------------------


def poison_moe_params(params: Any, *, collapse: bool = False,
                      dead_expert: Optional[int] = None) -> Any:
    """Apply the MoE fault drills to a param tree (docs/resilience.md).

    ``collapse=True`` zeroes every router ``gate`` leaf: all logits tie
    and ``lax.top_k``'s deterministic lowest-index tie-break routes
    EVERY token to experts ``0..top_k-1`` — the router-collapse load
    signature the ``moe_imbalance`` latch must catch (note the Switch
    aux loss stays at its balanced value 1.0 under uniform probs — the
    histogram, not the loss, is the detector).

    ``dead_expert=<idx>`` zeroes expert idx's slice of every ``w2``
    down-projection: the expert keeps receiving traffic but contributes
    the zero vector."""
    if not collapse and dead_expert is None:
        return params

    def edit(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        if collapse and name == "gate":
            return jnp.zeros_like(leaf)
        if dead_expert is not None and name == "w2" and leaf.ndim >= 3:
            # (E, ffn, h), or scan-stacked (L, E, ffn, h)
            sl = ((slice(None),) * (leaf.ndim - 3)
                  + (int(dead_expert),))
            return leaf.at[sl].set(0.0)
        return leaf

    return jax.tree_util.tree_map_with_path(edit, params)


__all__ = [
    "ExpertParallelMLP",
    "GroupedMLP",
    "MoEConfig",
    "MoEMLP",
    "collect_moe_stats",
    "expert_load",
    "group_gemm",
    "load_balancing_loss",
    "poison_moe_params",
    "router_topk",
]

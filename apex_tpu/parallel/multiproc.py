"""Multi-host bootstrap (ref: apex/parallel/multiproc.py — the
pre-torchrun one-process-per-GPU launcher).

On TPU the per-device process model disappears: one Python process per
host drives all local chips, and SPMD partitioning replaces per-rank
scripts. What remains of the launcher is cluster bootstrap, which JAX
provides via ``jax.distributed.initialize``; this module wraps it with
the reference launcher's env-var conventions so launch tooling can
stay the same.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Connect this host into the cluster.

    Falls back to the reference launcher's environment variables
    (MASTER_ADDR/MASTER_PORT, WORLD_SIZE, RANK) when arguments are not
    given; single-host runs (no env, no args) are a no-op.
    """
    import jax

    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT", "12355")
        coordinator_address = f"{addr}:{port}" if addr else None
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if coordinator_address is None or num_processes in (None, 1):
        return  # single host

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def local_rank() -> int:
    """ref launcher's --local_rank was the per-node device index; with
    one JAX process driving all local chips it is always 0 (use
    ``jax.local_devices()`` for per-chip work)."""
    return 0


def process_index() -> int:
    """Global rank of this host's process (the reference's RANK)."""
    import jax

    return jax.process_index()


def world_size() -> int:
    import jax

    return jax.process_count()


__all__ = ["initialize_distributed", "local_rank", "process_index",
           "world_size"]

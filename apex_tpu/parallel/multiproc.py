"""Multi-host bootstrap (ref: apex/parallel/multiproc.py — the
pre-torchrun one-process-per-GPU launcher).

On TPU the per-device process model disappears: one Python process per
host drives all local chips, and SPMD partitioning replaces per-rank
scripts. What remains of the launcher is cluster bootstrap, which JAX
provides via ``jax.distributed.initialize``; this module wraps it with
the reference launcher's env-var conventions so launch tooling can
stay the same.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Connect this host into the cluster.

    Falls back to the reference launcher's environment variables
    (MASTER_ADDR/MASTER_PORT, WORLD_SIZE, RANK) when arguments are not
    given; single-host runs (no env, no args) are a no-op.
    """
    import jax

    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT", "12355")
        coordinator_address = f"{addr}:{port}" if addr else None
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if coordinator_address is None or num_processes in (None, 1):
        return  # single host

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_coordinator() -> bool:
    """True on the process that owns cluster-singleton duties (rank 0):
    the quorum-checkpoint commit manifest (resilience/checkpoint.py),
    fleet-level records, progress logging."""
    import jax

    return jax.process_index() == 0


def process_collective():
    """The resilience guard's :class:`~apex_tpu.resilience.guard.
    Collective` for THIS runtime: a ``ProcessCollective`` over
    ``jax.experimental.multihost_utils`` when the cluster has more than
    one process (call :func:`initialize_distributed` first), else the
    no-op ``NullCollective`` — so single-host code paths cost nothing
    and the same training loop runs unchanged at both scales::

        multiproc.initialize_distributed()
        col = multiproc.process_collective()
        mgr = CheckpointManager(dir, process_id=col.replica_id,
                                n_processes=col.n_replicas)
        guard = ConsistencyGuard(step.with_options(fingerprint_every=N),
                                 collective=col, manager=mgr)

    On a multi-process CPU cluster (the two-process drills,
    ``tools/fleet_drill.py``) device collectives don't exist
    ("Multiprocess computations aren't implemented on the CPU
    backend"), so the pick is the
    :class:`~apex_tpu.resilience.guard.KVStoreCollective` riding the
    same ``jax.distributed`` coordination service — identical
    protocol, host-side transport.

    When the comms plane is armed (``telemetry.comms.enable()`` or
    ``APEX_TPU_COMMS=1``) the returned collective is routed through
    ``comms.instrument`` — per-op counters/bytes/ms, timeline spans,
    the wire bandwidth ledger. Disabled, the raw object comes back
    untouched.
    """
    import jax

    from apex_tpu.resilience.guard import (KVStoreCollective,
                                           NullCollective,
                                           ProcessCollective)
    from apex_tpu.telemetry import comms

    if jax.process_count() > 1:
        if jax.default_backend() == "cpu":
            return comms.instrument(KVStoreCollective())
        return comms.instrument(ProcessCollective())
    return comms.instrument(NullCollective())


def elastic_checkpoint_manager(directory, **kwargs):
    """An :class:`~apex_tpu.resilience.elastic.ElasticCheckpointManager`
    sized to THIS runtime's world — the one-liner that makes a training
    loop's checkpoints survive a topology change (resume on any host
    count; docs/resilience.md "Elastic resume")::

        multiproc.initialize_distributed()
        col = multiproc.process_collective()
        mgr = multiproc.elastic_checkpoint_manager(ckpt_dir, keep=3)
        ...
        restored = mgr.restore(template=opt.init(params),
                               collective=col)

    kwargs pass through to ``ElasticCheckpointManager``.
    """
    import jax

    from apex_tpu.resilience.elastic import ElasticCheckpointManager

    return ElasticCheckpointManager(
        directory, process_id=jax.process_index(),
        n_processes=jax.process_count(), **kwargs)


def fleet_aggregator(**kwargs):
    """A :class:`~apex_tpu.telemetry.fleet.FleetAggregator` over this
    runtime's :func:`process_collective` — the one-liner a training
    loop calls at its aggregation boundaries::

        agg = multiproc.fleet_aggregator(straggler_factor=2.0)
        ...
        if (i + 1) % aggregate_every == 0:
            fleet = agg.aggregate()       # all hosts call it (collective)

    kwargs pass through to ``FleetAggregator``.
    """
    from apex_tpu.telemetry.fleet import FleetAggregator

    return FleetAggregator(process_collective(), **kwargs)


def local_rank() -> int:
    """ref launcher's --local_rank was the per-node device index; with
    one JAX process driving all local chips it is always 0 (use
    ``jax.local_devices()`` for per-chip work)."""
    return 0


def process_index() -> int:
    """Global rank of this host's process (the reference's RANK)."""
    import jax

    return jax.process_index()


def world_size() -> int:
    import jax

    return jax.process_count()


__all__ = ["elastic_checkpoint_manager", "fleet_aggregator",
           "initialize_distributed", "is_coordinator", "local_rank",
           "process_collective", "process_index", "world_size"]

"""Data-parallel gradient synchronization.

TPU re-design of the reference's DistributedDataParallel
(ref: apex/parallel/distributed.py). The reference's machinery —
per-grad-accumulator hooks, arrival-order bucket construction, side
streams, flatten/unflatten (distributed.py:254-557) — exists to overlap
NCCL all-reduce with backward compute. Under XLA, the *scheduler* does
that: gradients are averaged with one `psum`/`pmean` over the mesh's
data axis inside the jitted step, and XLA overlaps the collectives with
the backward automatically. What remains of DDP's surface is its
*policy* knobs, kept here with reference semantics:

  gradient_average          -> mean instead of sum     (distributed.py:166)
  gradient_predivide_factor -> divide by f before, by world/f after
                               (distributed.py:170-175,451-457)
  allreduce_always_fp32     -> cast grads fp32 for the reduction
                               (distributed.py:162,446-449)

`Reducer` mirrors the manual helper (distributed.py:89-126).
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import DATA_AXIS


class DistributedDataParallel:
    """Gradient-averaging policy over the data axis.

    Use inside a shard_map/pjit training step::

        ddp = DistributedDataParallel(gradient_average=True)
        grads = jax.grad(loss_fn)(params, batch_shard)
        grads = ddp.allreduce_grads(grads)

    (ref: apex.parallel.DistributedDataParallel(module, message_size=...,
    delay_allreduce=...) — bucketing/stream knobs have no TPU analog and
    are intentionally absent; XLA owns comm/compute overlap.)
    """

    def __init__(
        self,
        axis_name: str = DATA_AXIS,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
        prof: bool = False,
        check_reduction: bool = False,
    ):
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.axis_index_groups = axis_index_groups
        self.prof = prof
        self.check_reduction = check_reduction

    def allreduce_grads(self, grads: Any) -> Any:
        """All-reduce a grad pytree over the data axis
        (ref allreduce_fallback/comm_ready_buckets semantics,
        distributed.py:426-557)."""
        predivide = self.gradient_predivide_factor

        def reduce_one(g):
            dtype = g.dtype
            if self.allreduce_always_fp32:
                g = g.astype(jnp.float32)
            if predivide != 1.0:
                g = g / predivide
            g = lax.psum(g, self.axis_name,
                         axis_index_groups=self.axis_index_groups)
            if self.gradient_average:
                world = lax.axis_size(self.axis_name)
                post = world / predivide if predivide != 1.0 else world
                g = g / post
            elif predivide != 1.0:
                g = g * predivide
            return g.astype(dtype)

        # named range in HLO metadata/traces (the reference guards
        # nvtx ranges behind the same flag, distributed.py:360-361)
        scope = (jax.named_scope("apex_tpu.ddp.allreduce") if self.prof
                 else contextlib.nullcontext())
        with scope:
            return jax.tree.map(reduce_one, grads)

    # parity alias matching the reference's module-method name
    __call__ = allreduce_grads

    def check_synchronized(self, tree: Any) -> jax.Array:
        """Debug epilogue check: warn (jax.debug.print) unless ``tree``
        is replicated across the data axis, returning the deviation.

        Call it on the grads the OPTIMIZER consumes — a tree that merely
        passed through :meth:`allreduce_grads` is replicated by
        construction; the hazard is a leaf that bypassed the reduction
        (the reference's epilogue asserts catch exactly that class, ref
        apex/parallel/distributed.py:336-349; torch DDP calls the knob
        ``check_reduction``). Gated on ``check_reduction=True`` so the
        call can stay in the step permanently and cost nothing when the
        debug flag is off (use :func:`sync_deviation` directly for an
        unconditional measurement); inside jit/shard_map.
        """
        if not self.check_reduction:
            return jnp.float32(0.0)
        dev = sync_deviation(tree, self.axis_name, self.axis_index_groups)

        def warn(_):
            jax.debug.print(
                "apex_tpu DDP check_reduction: grads DIVERGE across "
                "{a} (max dev {d}) — an unsynced or rank-dependent "
                "grad is reaching the optimizer",
                a=self.axis_name, d=dev)
            return 0

        # ~(dev <= 0) so a NaN deviation (inf/NaN leaves — genuinely
        # diverged or overflowed grads) also warns
        lax.cond(jnp.logical_not(dev <= 0), warn, lambda _: 0, None)
        return dev


def sync_deviation(tree: Any, axis_name: str = DATA_AXIS,
                   axis_index_groups=None) -> jax.Array:
    """Max |x - first_rank(x)| over ``axis_name`` across all leaves —
    exactly 0 iff the (finite) pytree is replicated on the axis; +inf
    if any leaf holds inf/NaN anywhere (a collective max would swallow
    NaN, so non-finite local deviations are sanitized to +inf).

    The runtime defensive check replacing the reference's DDP epilogue
    asserts + 2-GPU race test (ref: apex/parallel/distributed.py:336-349,
    tests/distributed/DDP/ddp_race_condition_test.py): after grad sync,
    every rank must hold identical grads; a nonzero (or NaN) deviation
    means an unsynced (rank-dependent) value is about to reach the
    optimizer. Call inside shard_map on the tree the optimizer consumes;
    assert on the (replicated) result outside jit, or gate on it with
    ``lax.cond`` / :meth:`DistributedDataParallel.check_synchronized`.
    """
    leaves = [l for l in jax.tree.leaves(tree) if l.size]
    if not leaves:
        return jnp.float32(0.0)

    # first rank of (the local group of) the axis, computed once for
    # the whole tree; statically rank 0 without groups
    idx = lax.axis_index(axis_name)
    if axis_index_groups is None:
        first = (idx == 0).astype(jnp.float32)
    else:
        min_idx = lax.pmin(idx, axis_name,
                           axis_index_groups=axis_index_groups)
        first = (idx == min_idx).astype(jnp.float32)

    def dev(x):
        x = x.astype(jnp.float32)
        # compare against the first rank's copy via a masked psum (one
        # nonzero contribution -> bitwise exact), not pmean: summing N
        # identical fp32 values rounds at the ulp level, which would
        # report a spurious nonzero deviation for replicated trees
        ref = lax.psum(x * first, axis_name,
                       axis_index_groups=axis_index_groups)
        d = jnp.max(jnp.abs(x - ref))
        # inf inputs poison the masked psum with NaN; report them as
        # +inf so the cross-rank pmax can't swallow the signal
        return jnp.where(jnp.isfinite(d), d, jnp.inf)

    # one cross-rank collective for the whole tree: local max first
    return lax.pmax(jnp.max(jnp.stack([dev(l) for l in leaves])),
                    axis_name, axis_index_groups=axis_index_groups)


class Reducer:
    """Manual all-reduce helper (ref: apex.parallel.Reducer,
    distributed.py:89-126): call ``.reduce(tree)`` whenever you choose —
    no implicit hooks."""

    def __init__(self, axis_name: str = DATA_AXIS,
                 axis_index_groups=None):
        self.axis_name = axis_name
        self.axis_index_groups = axis_index_groups

    def reduce(self, tree: Any, average: bool = True) -> Any:
        def f(x):
            y = lax.psum(x, self.axis_name,
                         axis_index_groups=self.axis_index_groups)
            if average:
                y = y / lax.axis_size(self.axis_name)
            return y.astype(x.dtype)

        return jax.tree.map(f, tree)

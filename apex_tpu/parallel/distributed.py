"""Data-parallel gradient synchronization.

TPU re-design of the reference's DistributedDataParallel
(ref: apex/parallel/distributed.py). The reference's machinery —
per-grad-accumulator hooks, arrival-order bucket construction, side
streams, flatten/unflatten (distributed.py:254-557) — exists to overlap
NCCL all-reduce with backward compute. Under XLA, the *scheduler* does
that: gradients are averaged with one `psum`/`pmean` over the mesh's
data axis inside the jitted step, and XLA overlaps the collectives with
the backward automatically. What remains of DDP's surface is its
*policy* knobs, kept here with reference semantics:

  gradient_average          -> mean instead of sum     (distributed.py:166)
  gradient_predivide_factor -> divide by f before, by world/f after
                               (distributed.py:170-175,451-457)
  allreduce_always_fp32     -> cast grads fp32 for the reduction
                               (distributed.py:162,446-449)

`Reducer` mirrors the manual helper (distributed.py:89-126).
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import DATA_AXIS


class DistributedDataParallel:
    """Gradient-averaging policy over the data axis.

    Use inside a shard_map/pjit training step::

        ddp = DistributedDataParallel(gradient_average=True)
        grads = jax.grad(loss_fn)(params, batch_shard)
        grads = ddp.allreduce_grads(grads)

    (ref: apex.parallel.DistributedDataParallel(module, message_size=...,
    delay_allreduce=...) — bucketing/stream knobs have no TPU analog and
    are intentionally absent; XLA owns comm/compute overlap.)
    """

    def __init__(
        self,
        axis_name: str = DATA_AXIS,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
        prof: bool = False,
    ):
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.axis_index_groups = axis_index_groups
        self.prof = prof

    def allreduce_grads(self, grads: Any) -> Any:
        """All-reduce a grad pytree over the data axis
        (ref allreduce_fallback/comm_ready_buckets semantics,
        distributed.py:426-557)."""
        predivide = self.gradient_predivide_factor

        def reduce_one(g):
            dtype = g.dtype
            if self.allreduce_always_fp32:
                g = g.astype(jnp.float32)
            if predivide != 1.0:
                g = g / predivide
            g = lax.psum(g, self.axis_name,
                         axis_index_groups=self.axis_index_groups)
            if self.gradient_average:
                world = lax.axis_size(self.axis_name)
                post = world / predivide if predivide != 1.0 else world
                g = g / post
            elif predivide != 1.0:
                g = g * predivide
            return g.astype(dtype)

        # named range in HLO metadata/traces (the reference guards
        # nvtx ranges behind the same flag, distributed.py:360-361)
        scope = (jax.named_scope("apex_tpu.ddp.allreduce") if self.prof
                 else contextlib.nullcontext())
        with scope:
            return jax.tree.map(reduce_one, grads)

    # parity alias matching the reference's module-method name
    __call__ = allreduce_grads


class Reducer:
    """Manual all-reduce helper (ref: apex.parallel.Reducer,
    distributed.py:89-126): call ``.reduce(tree)`` whenever you choose —
    no implicit hooks."""

    def __init__(self, axis_name: str = DATA_AXIS,
                 axis_index_groups=None):
        self.axis_name = axis_name
        self.axis_index_groups = axis_index_groups

    def reduce(self, tree: Any, average: bool = True) -> Any:
        def f(x):
            y = lax.psum(x, self.axis_name,
                         axis_index_groups=self.axis_index_groups)
            if average:
                y = y / lax.axis_size(self.axis_name)
            return y.astype(x.dtype)

        return jax.tree.map(f, tree)

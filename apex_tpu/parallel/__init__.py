"""Data-parallel runtime (ref: apex/parallel/__init__.py:9-17).

`DistributedDataParallel` (psum-mean grad sync policy), `Reducer`,
`SyncBatchNorm` + `convert_syncbn_model` + BN process groups, and `LARC`.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    sync_deviation,
)
from apex_tpu.parallel.larc import LARC, larc_transform
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    convert_syncbn_model,
    create_syncbn_group_assignment,
)

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "sync_deviation",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "create_syncbn_group_assignment",
    "LARC",
    "larc_transform",
]

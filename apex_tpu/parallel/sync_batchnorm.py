"""Synchronized BatchNorm over mesh axes.

TPU re-design of the reference's optimized SyncBatchNorm
(ref: apex/parallel/optimized_sync_batchnorm.py,
optimized_sync_batchnorm_kernel.py:10-119, csrc/welford.cu). The CUDA
path computes local Welford stats, all-gathers (mean, var, count) and
merges; on TPU the numerically-equal single-pass form is a `psum` of
(sum, sumsq, count) over the sync axes — the merge tree disappears into
the collective. Backward needs no custom kernel: the stats' psum is in
the graph, so AD produces exactly the reference's reduce-then-allreduce
backward (sum_dy, sum_dy_xmu over the group).

BN process groups of size N (ref: apex/parallel/__init__.py:21-95
create_syncbn_process_group) map to ``axis_index_groups`` on the data
axis via `create_syncbn_group_assignment`.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import DATA_AXIS


def create_syncbn_group_assignment(world_size: int, group_size: int):
    """Partition dp ranks into BN groups of ``group_size``
    (ref: create_syncbn_process_group, apex/parallel/__init__.py:60-95).
    Returns axis_index_groups for lax.psum."""
    if world_size % group_size:
        raise ValueError("world_size must be divisible by group_size")
    return [
        list(range(i, i + group_size))
        for i in range(0, world_size, group_size)
    ]


class SyncBatchNorm(nn.Module):
    """BatchNorm2d/1d synchronized across the data axis
    (ref: apex.parallel.SyncBatchNorm). Channel-last layout (TPU-native;
    the reference's NHWC 'channel_last' variant is the default here).

    Use inside shard_map/pjit with the data axis mapped; pass
    ``axis_name=None`` to run unsynchronized (single-device fallback,
    ref optimized_sync_batchnorm.py:70-75).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = DATA_AXIS
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    fuse_relu: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_stats: bool = False):
        """x: (..., C) with C == num_features. ``use_running_stats``
        selects inference normalization (ref falls back to F.batch_norm
        for eval, optimized_sync_batchnorm.py:76-85)."""
        c = self.num_features
        assert x.shape[-1] == c, "SyncBatchNorm expects channels-last"
        dtype = x.dtype

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )

        if use_running_stats or not self.track_running_stats:
            if use_running_stats:
                mean = ra_mean.value
                var = ra_var.value
            else:
                mean, var = self._batch_stats(x)
        else:
            mean, var = self._batch_stats(x)
            # running-stat update uses unbiased variance like the reference
            # (optimized_sync_batchnorm_kernel.py:53-56)
            n = self._total_count(x)
            unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.eps)
        if self.affine:
            w = self.param("scale", nn.initializers.ones, (c,), self.param_dtype)
            b = self.param("bias", nn.initializers.zeros, (c,), self.param_dtype)
            y = y * w + b
        if self.fuse_relu:
            # (ref optimized_sync_batchnorm.py fuse_relu option)
            y = jax.nn.relu(y)
        return y.astype(dtype)

    def _in_collective(self) -> bool:
        if self.axis_name is None or self.is_initializing():
            return False
        try:
            lax.axis_size(self.axis_name)
            return True
        except NameError:
            return False

    def _total_count(self, x):
        local = 1.0
        for d in x.shape[:-1]:
            local *= d
        if self._in_collective():
            # all groups have equal size; count scales by group size
            g = (
                len(self.axis_index_groups[0])
                if self.axis_index_groups
                else lax.axis_size(self.axis_name)
            )
            local = local * g
        return jnp.float32(local)

    def _batch_stats(self, x):
        xf = x.astype(jnp.float32)
        axes = tuple(range(x.ndim - 1))
        s = jnp.sum(xf, axis=axes)
        ss = jnp.sum(xf * xf, axis=axes)
        if self._in_collective():
            s, ss = lax.psum(
                (s, ss), self.axis_name,
                axis_index_groups=self.axis_index_groups,
            )
        n = self._total_count(x)
        mean = s / n
        var = ss / n - mean * mean
        return mean, var


def convert_syncbn_model(module: nn.Module,
                         axis_name: str = DATA_AXIS,
                         axis_index_groups=None) -> nn.Module:
    """Recursively swap flax BatchNorm for SyncBatchNorm
    (ref: apex.parallel.convert_syncbn_model, __init__.py:21-58).

    Flax modules are frozen dataclasses, so the swap is a structural
    clone: any `nn.BatchNorm` attribute or submodule is replaced by an
    equivalent `SyncBatchNorm`. Works for modules that declare BN
    layers as dataclass fields; @nn.compact-defined BNs should use
    SyncBatchNorm directly.
    """
    if isinstance(module, nn.BatchNorm):
        return SyncBatchNorm(
            num_features=module.num_features
            if hasattr(module, "num_features") else -1,
            eps=module.epsilon,
            momentum=1.0 - module.momentum,
            axis_name=axis_name,
            axis_index_groups=axis_index_groups,
        )
    changes = {}
    for name, value in vars(module).items():
        if isinstance(value, nn.BatchNorm):
            changes[name] = convert_syncbn_model(
                value, axis_name, axis_index_groups
            )
        elif isinstance(value, nn.Module):
            converted = convert_syncbn_model(value, axis_name, axis_index_groups)
            if converted is not value:
                changes[name] = converted
    if changes:
        return module.clone(**changes)
    return module

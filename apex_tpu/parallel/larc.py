"""LARC — layer-wise adaptive rate clipping/scaling.

TPU re-design of ref apex/parallel/LARC.py:5-107: an optimizer *wrapper*
that replaces each tensor's lr with
``trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps)``, either clipped
at the base lr (clip mode) or used directly (scale mode). Provided two
ways:

- `larc_transform(...)` — an optax GradientTransformation to chain
  before any optimizer (grads are rescaled so the downstream lr step
  realizes the adaptive lr).
- `LARC` — wrapper class around a FlatFusedOptimizer mirroring the
  reference's wrap-the-optimizer API.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers.fused import FlatFusedOptimizer, FlatOptState, _resolve_lr


def _adaptive_ratio(p, g, lr, trust_coefficient, clip, eps, weight_decay):
    pn = jnp.linalg.norm(p.astype(jnp.float32))
    gn = jnp.linalg.norm(g.astype(jnp.float32))
    adaptive = trust_coefficient * pn / (gn + weight_decay * pn + eps)
    adaptive = jnp.where((pn > 0) & (gn > 0), adaptive, lr)
    if clip:
        # clip mode: lr <- min(adaptive/lr, 1) (ref LARC.py:91-99)
        return jnp.minimum(adaptive / lr, 1.0)
    return adaptive / lr


def larc_transform(
    learning_rate: float,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Chainable LARC: rescales each leaf's grad by the adaptive-lr /
    base-lr ratio so the following optimizer's step realizes LARC."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("larc_transform requires params")
        lr = jnp.asarray(learning_rate, jnp.float32)

        def scale(g, p):
            r = _adaptive_ratio(p, g, lr, trust_coefficient, clip, eps,
                                weight_decay)
            return (g.astype(jnp.float32) * r).astype(g.dtype)

        return jax.tree.map(scale, updates, params), state

    return optax.GradientTransformation(init_fn, update_fn)


class LARC:
    """Wrap a FlatFusedOptimizer with LARC lr adaptation
    (ref: apex.parallel.LARC(optimizer, trust_coefficient, clip, eps))."""

    def __init__(self, optimizer: FlatFusedOptimizer,
                 trust_coefficient: float = 0.02, clip: bool = True,
                 eps: float = 1e-8):
        self.optimizer = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params) -> FlatOptState:
        return self.optimizer.init(params)

    def step(self, state: FlatOptState, grads, **kwargs):
        lr = _resolve_lr(kwargs.pop("lr", None) or self.optimizer.lr, state.count)
        wd = getattr(self.optimizer, "weight_decay", 0.0)
        params = self.optimizer.master_params(state)

        def scale(g, p):
            r = _adaptive_ratio(p, g, lr, self.trust_coefficient, self.clip,
                                self.eps, wd)
            return (g.astype(jnp.float32) * r).astype(g.dtype)

        grads = jax.tree.map(scale, grads, params)
        return self.optimizer.step(state, grads, lr=lr, **kwargs)

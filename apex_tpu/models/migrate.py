"""Checkpoint migration between scanned and unrolled layer stacks.

The model zoo defaults to ``scan_layers=True`` (one ``nn.scan`` node:
single trace/compile of the layer body, params stacked with a leading
layer axis) — the TPU-right representation. Name-addressed checkpoints
written by the unrolled form (``layer_{i}`` / ``encoder_{i}`` /
``decoder_{i}``) have a different tree structure; these helpers convert
either direction so a ``scan_layers`` flip is never a checkpoint
breakage (ADVICE r3: a default flip is a silent breaking change without
a migration path).

Works on any params subtree following the zoo's naming convention:

=================  ==========================  =====================
model              scanned node                unrolled names
=================  ==========================  =====================
GPT / BERT         ``layers.layer``            ``layer_{i}``
T5 encoder         ``encoder_layers.layer``    ``encoder_{i}``
T5 decoder         ``decoder_layers.layer``    ``decoder_{i}``
=================  ==========================  =====================

Only the *structure* is converted; values are moved bit-for-bit. (Init
RNG streams still differ between the two forms, so freshly-initialized
models differ — migration is for checkpoints, not for matching inits.)
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
import jax.numpy as jnp

# scanned-node name -> unrolled per-layer name pattern
_SCAN_NODES = {
    "layers": "layer_{}",
    "encoder_layers": "encoder_{}",
    "decoder_layers": "decoder_{}",
}
_UNROLLED_RE = re.compile(r"^(layer|encoder|decoder)_(\d+)$")
_STACK_OF = {"layer": "layers", "encoder": "encoder_layers",
             "decoder": "decoder_layers"}


def unstack_scan_params(tree: Any) -> Any:
    """Scanned checkpoint -> unrolled layout (``layers.layer`` with a
    leading layer axis becomes ``layer_0 .. layer_{L-1}``)."""
    if not isinstance(tree, Mapping):
        return tree
    out = {}
    for key, val in tree.items():
        if (key in _SCAN_NODES and isinstance(val, Mapping)
                and set(val) == {"layer"}):
            body = val["layer"]
            leaves = jax.tree.leaves(body)
            if not leaves:
                out[key] = val
                continue
            num_layers = int(leaves[0].shape[0])
            pat = _SCAN_NODES[key]
            for i in range(num_layers):
                out[pat.format(i)] = jax.tree.map(
                    lambda l, i=i: l[i], body)
        else:
            out[key] = unstack_scan_params(val)
    return out


def stack_scan_params(tree: Any) -> Any:
    """Unrolled checkpoint -> scanned layout (``layer_{i}`` groups are
    stacked along a new leading axis under ``layers.layer``)."""
    if not isinstance(tree, Mapping):
        return tree
    groups: dict[str, dict[int, Any]] = {}
    out = {}
    for key, val in tree.items():
        m = _UNROLLED_RE.match(key)
        if m:
            groups.setdefault(m.group(1), {})[int(m.group(2))] = val
        else:
            out[key] = stack_scan_params(val)
    for kind, by_idx in groups.items():
        n = len(by_idx)
        missing = [i for i in range(n) if i not in by_idx]
        if missing:
            raise ValueError(
                f"unrolled {kind}_* params are not contiguous: have "
                f"{sorted(by_idx)}, missing {missing}")
        stacked = jax.tree.map(
            lambda *ls: jnp.stack(ls, axis=0),
            *[by_idx[i] for i in range(n)])
        out[_STACK_OF[kind]] = {"layer": stacked}
    return out


__all__ = ["stack_scan_params", "unstack_scan_params"]

"""ResNet family, NHWC — the imagenet-example model
(ref: examples/imagenet/main_amp.py uses torchvision resnet50;
BASELINE configs[1] is ResNet-50 + amp O2 + FusedSGD + SyncBN).

TPU-first: NHWC end to end, SyncBatchNorm over the data axis (BN
groups optional), bottleneck residual blocks whose conv+scale+relu
chains XLA fuses, optional spatial (H-dim) parallelism via the contrib
halo-exchange conv for the 3x3s.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.bottleneck import conv2d_nhwc
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    block_sizes: Tuple[int, ...] = (3, 4, 6, 3)     # ResNet-50
    width: int = 64
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None   # e.g. "data" for SyncBN
    bn_groups: Optional[Sequence[Sequence[int]]] = None

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(block_sizes=(3, 4, 6, 3), **kw)

    @staticmethod
    def resnet18ish(**kw) -> "ResNetConfig":
        """Small config for tests/CPU smoke."""
        return ResNetConfig(block_sizes=(1, 1), width=16, **kw)


class _BNBlock(nn.Module):
    cfg: ResNetConfig
    features: int
    relu: bool = True

    @nn.compact
    def __call__(self, x, train=True):
        y = SyncBatchNorm(
            num_features=self.features,
            axis_name=self.cfg.bn_axis_name,
            axis_index_groups=self.cfg.bn_groups,
            fuse_relu=self.relu, name="bn",
        )(x, use_running_stats=not train)
        return y


class ResNetBottleneckBlock(nn.Module):
    """conv1x1-BN-relu -> conv3x3-BN-relu -> conv1x1-BN + residual."""

    cfg: ResNetConfig
    filters: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train=True):
        cfg = self.cfg
        init = nn.initializers.he_normal()
        dt, pdt = cfg.dtype, cfg.param_dtype
        f, out_f = self.filters, 4 * self.filters
        w1 = self.param("conv1", init, (1, 1, x.shape[-1], f), pdt)
        w2 = self.param("conv2", init, (3, 3, f, f), pdt)
        w3 = self.param("conv3", init, (1, 1, f, out_f), pdt)

        y = _BNBlock(cfg, f, name="bn1")(
            conv2d_nhwc(x, w1.astype(dt)), train)
        y = _BNBlock(cfg, f, name="bn2")(
            conv2d_nhwc(y, w2.astype(dt), stride=self.stride), train)
        y = _BNBlock(cfg, out_f, relu=False, name="bn3")(
            conv2d_nhwc(y, w3.astype(dt)), train)

        if x.shape[-1] != out_f or self.stride != 1:
            wd = self.param("conv_down", init,
                            (1, 1, x.shape[-1], out_f), pdt)
            x = _BNBlock(cfg, out_f, relu=False, name="bn_down")(
                conv2d_nhwc(x, wd.astype(dt), stride=self.stride), train)
        return jnp.maximum(y + x, 0.0)


class ResNet(nn.Module):
    """NHWC ResNet with bottleneck blocks (50/101/152 by block_sizes)."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train=True):
        cfg = self.cfg
        init = nn.initializers.he_normal()
        dt, pdt = cfg.dtype, cfg.param_dtype
        x = x.astype(dt)
        w0 = self.param("conv_stem", init, (7, 7, x.shape[-1], cfg.width),
                        pdt)
        x = conv2d_nhwc(x, w0.astype(dt), stride=2)
        x = _BNBlock(cfg, cfg.width, name="bn_stem")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, n_blocks in enumerate(cfg.block_sizes):
            filters = cfg.width * (2 ** i)
            for j in range(n_blocks):
                stride = 2 if (j == 0 and i > 0) else 1
                x = ResNetBottleneckBlock(
                    cfg, filters, stride=stride,
                    name=f"stage{i}_block{j}")(x, train)

        x = jnp.mean(x, axis=(1, 2))                      # global avg pool
        wh = self.param("head", nn.initializers.normal(stddev=0.01),
                        (x.shape[-1], cfg.num_classes), pdt)
        bh = self.param("head_bias", nn.initializers.zeros,
                        (cfg.num_classes,), pdt)
        return (x.astype(jnp.float32) @ wh.astype(jnp.float32)
                + bh.astype(jnp.float32))


def cross_entropy_logits(logits, labels):
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
    return jnp.mean(lse - tgt)


__all__ = ["ResNet", "ResNetBottleneckBlock", "ResNetConfig",
           "cross_entropy_logits"]

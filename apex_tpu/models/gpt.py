"""Megatron-style GPT — the flagship model family.

TPU re-design of the reference's standalone GPT test fixture
(ref: apex/transformer/testing/standalone_gpt.py,
standalone_transformer_lm.py — embedding + L x [LN, parallel attention,
LN, parallel MLP] + final LN + tied vocab head, trained with
vocab-parallel cross entropy). Built entirely from apex_tpu parallel
layers, so one module serves:

  - single device (plain apply; layers degrade to dense)
  - tensor parallel (+ sequence parallel) inside shard_map over "tensor"
  - pipeline parallel on the GSPMD mesh's `pipe` axis (the scan-layers
    stack split stage-major by `mesh.pipeline.PipelineSpec`)

`gpt_param_specs` derives the PartitionSpec tree for the step boundary
(the analog of the reference's per-layer process-group wiring).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis
from apex_tpu.mesh import annotate as _gspmd


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    max_seq_len: int = 1024
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    # GQA/MQA: number of shared kv heads; None = num_heads (MHA). The
    # fused QKV projection narrows to h + 2*num_kv_heads*head_dim and
    # the flash kernel shares each kv head across its q-head group
    # without materializing a repeat (ops/attention.py index maps).
    num_kv_heads: Optional[int] = None
    # sliding-window (local) attention: each query sees its last
    # `attention_window` keys up to the diagonal. flash backend only.
    attention_window: Optional[int] = None
    ffn_hidden_size: Optional[int] = None   # default 4*hidden
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    softmax_impl: Optional[str] = None
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # "softmax" (fused masked softmax), "flash" (Pallas flash kernel),
    # or "ring" (context-parallel ring attention over the "context"
    # axis — run the model inside shard_map with tokens sharded along
    # seq and pass global `positions`)
    attention_backend: str = "flash"
    # lax.scan over stacked layer params (one compiled layer body
    # instead of num_layers inlined copies). Compile time and program
    # size become depth-independent — 24 unrolled BERT/GPT-class layers
    # overwhelm the Mosaic compile pipeline (docs/HARDWARE_NOTES.md
    # round-3 bench_bert/gpt compile crashes). False restores per-layer
    # param names ("layer_{i}") for name-addressed checkpoints.
    scan_layers: bool = True
    # Mixture-of-Experts (docs/moe.md): num_experts=0 is the dense
    # model — every knob below is inert and the param tree is
    # byte-identical to a pre-MoE checkpoint. num_experts>0 swaps the
    # dense ParallelMLP for apex_tpu.moe.MoEMLP on designated layers
    # (layer i is MoE iff i % moe_layer_freq == moe_layer_freq - 1;
    # scan_layers needs freq 1 — homogeneous scan bodies).
    num_experts: int = 0
    moe_top_k: int = 2
    moe_layer_freq: int = 1
    # "dropless" (sort + group-GEMM, no drops) or "capacity"
    # (GShard (E, C) buffers; the mesh all-to-all EP path)
    moe_impl: str = "dropless"
    moe_capacity_factor: float = 1.25
    # Switch aux-loss weight folded into the training loss by
    # make_gpt_pretrain_step (0 trains without load balancing)
    moe_aux_loss_weight: float = 0.01

    def __post_init__(self):
        if self.num_kv_heads is not None and self.num_kv_heads < 1:
            raise ValueError(
                f"num_kv_heads must be >= 1 or None, got {self.num_kv_heads}")
        nkv = self.kv_heads
        if self.num_heads % nkv:
            raise ValueError(
                f"num_kv_heads ({nkv}) must divide num_heads "
                f"({self.num_heads})")
        if self.attention_window is not None:
            if self.attention_backend != "flash":
                raise ValueError(
                    "attention_window requires attention_backend='flash' "
                    f"(got {self.attention_backend!r})")
            if self.attention_window < 1:
                raise ValueError("attention_window must be >= 1")
        if nkv != self.num_heads and self.attention_backend == "ring":
            raise ValueError(
                "GQA (num_kv_heads != num_heads) is not supported by the "
                "ring backend")
        if self.num_experts < 0:
            raise ValueError(
                f"num_experts must be >= 0, got {self.num_experts}")
        if self.num_experts > 0:
            if self.moe_impl not in ("dropless", "capacity"):
                raise ValueError(
                    "moe_impl must be 'dropless' or 'capacity', got "
                    f"{self.moe_impl!r}")
            if not (1 <= self.moe_top_k <= self.num_experts):
                raise ValueError(
                    f"moe_top_k ({self.moe_top_k}) must be in "
                    f"[1, num_experts={self.num_experts}]")
            if self.moe_layer_freq < 1:
                raise ValueError(
                    f"moe_layer_freq must be >= 1, got "
                    f"{self.moe_layer_freq}")
            if self.scan_layers and self.moe_layer_freq != 1:
                raise ValueError(
                    "scan_layers requires homogeneous layers: "
                    f"moe_layer_freq={self.moe_layer_freq} needs "
                    "scan_layers=False (or set moe_layer_freq=1)")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    def is_moe_layer(self, i: int) -> bool:
        """Layer ``i`` runs the MoE MLP (every ``moe_layer_freq``-th
        layer, counting so freq 2 puts MoE on the odd layers)."""
        return (self.num_experts > 0
                and i % self.moe_layer_freq == self.moe_layer_freq - 1)

    def moe_cfg(self):
        """The :class:`~apex_tpu.moe.MoEConfig` this config's MoE
        layers run."""
        from apex_tpu.moe import MoEConfig

        return MoEConfig(
            hidden_size=self.hidden_size,
            ffn_hidden_size=self.ffn,
            num_experts=self.num_experts,
            top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            dtype=self.dtype,
            param_dtype=self.param_dtype)

    # GPT-2 345M (BASELINE configs[3]: ref run_gpt_minimal_test.py)
    @staticmethod
    def gpt2_345m(**kw) -> "GPTConfig":
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                         max_seq_len=1024, **kw)


class ParallelAttention(nn.Module):
    """Self attention: column-parallel fused QKV, causal fused softmax,
    row-parallel output projection (ref standalone_transformer_lm.py
    ParallelAttention).

    Serving hooks (apex_tpu/serving, docs/serving.md):

    - ``return_kv=True`` additionally returns this call's K/V in the
      kernel ``(b, kv_local, s, head_dim)`` layout — what a prefill
      step writes into the paged cache.
    - ``kv_ctx=(k_ctx, v_ctx, ctx_mask)`` is the decode path: a
      single-query (s == 1) forward attends over the gathered cache
      context ``k_ctx``/``v_ctx`` (b, kv_local, L, head_dim) plus its
      own K/V, with ``ctx_mask`` (b, L) marking the valid prefix —
      per-sequence lengths ride the flash kernel's segment-id masking,
      so no causal geometry is hard-wired to the input shape.
    - With ``s > 1`` the same hook is the chunk-resumable prefill
      path (chunked prefill, docs/serving.md): the s chunk tokens
      attend the gathered context (``ctx_mask`` marks the
      already-written prefix) PLUS themselves causally, via the flash
      kernel's ``sk > sq`` causal offset — key layout
      ``[ctx | chunk]``, query i sees key slot j iff ``j <= i + L``,
      and the per-lane segment ids drop the unwritten context tail.
    """

    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, positions=None, deterministic=True,
                 kv_ctx=None, return_kv=False):
        cfg = self.config
        h = cfg.hidden_size
        inside = _inside_axis(TENSOR_AXIS)
        tp = lax.axis_size(TENSOR_AXIS) if inside else 1
        if cfg.num_heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"tensor-parallel size {tp} must divide num_heads "
                f"({cfg.num_heads}) and kv heads ({cfg.kv_heads})")
        heads_local = cfg.num_heads // tp
        kv_local = cfg.kv_heads // tp
        head_dim = h // cfg.num_heads

        # Fused QKV projection, GQA-narrowed: full width is
        # h + 2*kv_heads*head_dim, laid out as one chunk per kv group —
        # [q_0..q_{g-1} | k | v] x kv_heads, g = q heads per kv head.
        # A contiguous TP slice of the output dim is then whole kv
        # groups, so the dense and TP-sharded interpretations of the
        # same weights agree exactly (Megatron's fused-QKV slab trick;
        # for MHA this degenerates to the per-head [q|k|v] layout).
        group = heads_local // kv_local
        qkv = ColumnParallelLinear(
            output_size=(cfg.num_heads + 2 * cfg.kv_heads) * head_dim,
            gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="qkv",
        )(x)
        qkv = _gspmd.constrain_column_parallel(qkv)
        s, b = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(s, b, kv_local, (group + 2) * head_dim)
        q, k, v = jnp.split(
            qkv, [group * head_dim, (group + 1) * head_dim], axis=-1)
        # q head g*group+j shares kv head g — matches the flash kernel's
        # `q_head // group` kv index map (ops/attention.py)
        q = q.reshape(s, b, heads_local, head_dim)
        k = k.reshape(s, b, kv_local, head_dim)
        v = v.reshape(s, b, kv_local, head_dim)
        # kernel-layout K/V of THIS call's tokens — the cache payload
        kv_new = (k.transpose(1, 2, 0, 3), v.transpose(1, 2, 0, 3))

        def _out(ctx):
            out = RowParallelLinear(
                output_size=h, input_is_parallel=True,
                sequence_parallel_enabled=cfg.sequence_parallel,
                param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="proj",
            )(ctx)
            out = _gspmd.constrain_hidden(out)
            return (out, kv_new) if return_kv else out

        if kv_ctx is not None:
            # decode: one query per sequence against the gathered cache
            # prefix + itself. Validity is data (ctx_mask), not block
            # geometry, so every sequence in the batch may sit at a
            # different length; masked-out slots are the trash block's
            # garbage and padded tail (serving/kv_cache.py).
            if cfg.attention_backend == "ring":
                raise ValueError(
                    "kv_ctx decode is not supported by the ring backend")
            if cfg.attention_window is not None:
                raise NotImplementedError(
                    "kv_ctx decode with attention_window is not supported")
            from apex_tpu.ops.attention import flash_attention

            k_ctx, v_ctx, ctx_mask = kv_ctx
            qb = q.transpose(1, 2, 0, 3)                  # (b, h, s, d)
            k_all = jnp.concatenate([k_ctx.astype(cfg.dtype), kv_new[0]],
                                    axis=2)
            v_all = jnp.concatenate([v_ctx.astype(cfg.dtype), kv_new[1]],
                                    axis=2)
            if s == 1:
                # decode: one query per sequence. Segment masking only:
                # valid prefix + the token itself = 0, everything else
                # 1 (flash zero-fills q-side segments). Kept exactly as
                # the pre-chunking program — greedy decode stays
                # bitwise-identical.
                kv_seg = jnp.concatenate(
                    [jnp.where(ctx_mask, 0, 1).astype(jnp.int32),
                     jnp.zeros((b, 1), jnp.int32)], axis=1)
                ctx = flash_attention(qb, k_all, v_all, causal=False,
                                      kv_segment_ids=kv_seg,
                                      impl=cfg.softmax_impl)
            else:
                # chunk-resumable prefill: s chunk queries over the
                # [ctx | chunk] key layout. causal=True with sk > sq
                # gives query i the keys j <= i + L (all of ctx + the
                # chunk's own causal prefix); the per-lane segment ids
                # drop ctx slots past the written prefix (ctx_mask) —
                # chunk padding keys sit AFTER every real query, so
                # the causal offset already masks them.
                kv_seg = jnp.concatenate(
                    [jnp.where(ctx_mask, 0, 1).astype(jnp.int32),
                     jnp.zeros((b, s), jnp.int32)], axis=1)
                ctx = flash_attention(qb, k_all, v_all, causal=True,
                                      kv_segment_ids=kv_seg,
                                      impl=cfg.softmax_impl)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(
                s, b, heads_local * head_dim)
            return _out(ctx)

        if cfg.attention_backend in ("flash", "ring"):
            # (s, b, heads, d) -> (b, heads, s, d)
            qb, kb, vb = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
            if cfg.attention_backend == "ring":
                from apex_tpu.transformer.context_parallel import (
                    ring_attention,
                )
                ctx = ring_attention(
                    qb, kb, vb, causal=True,
                    q_positions=positions, kv_positions=positions,
                    impl=cfg.softmax_impl)
            else:
                from apex_tpu.ops.attention import flash_attention
                drop = (cfg.attention_dropout
                        if cfg.attention_dropout > 0.0 and not deterministic
                        else 0.0)
                ctx = flash_attention(
                    qb, kb, vb, causal=True,
                    window_size=cfg.attention_window,
                    dropout_rate=drop,
                    dropout_rng=(self.make_rng("dropout")
                                 if drop > 0.0 else None),
                    impl=cfg.softmax_impl)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(
                s, b, heads_local * head_dim)
            return _out(ctx)

        # softmax backend materializes (s, s) scores; share kv heads by
        # broadcast (the O(S^2) buffer dominates memory here anyway)
        if kv_local != heads_local:
            rep = heads_local // kv_local
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        # (b*heads, s, d)
        def to_bhsd(t):
            return t.transpose(1, 2, 0, 3).reshape(b * heads_local, s, head_dim)

        q, k, v = to_bhsd(q), to_bhsd(k), to_bhsd(v)
        scores = jnp.einsum(
            "bsd,btd->bst", q, k, preferred_element_type=jnp.float32
        ) / jnp.sqrt(head_dim).astype(jnp.float32)
        probs = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.causal, impl=cfg.softmax_impl
        )(scores.reshape(b, heads_local, s, s).astype(cfg.dtype))
        if cfg.attention_dropout > 0.0 and not deterministic:
            probs = nn.Dropout(rate=cfg.attention_dropout)(
                probs, deterministic=False
            )
        ctx = jnp.einsum(
            "bhst,bhtd->bhsd", probs,
            v.reshape(b, heads_local, s, head_dim),
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        # (b, hl, s, d) -> (s, b, hl*d)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, heads_local * head_dim)
        return _out(ctx)


class ParallelMLP(nn.Module):
    """Column(4h, no gather) -> gelu -> Row(h) (ref ParallelMLP)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        hcol = ColumnParallelLinear(
            output_size=cfg.ffn, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc1",
        )(x)
        hcol = _gspmd.constrain_column_parallel(hcol)
        hcol = jax.nn.gelu(hcol, approximate=True)
        return _gspmd.constrain_hidden(RowParallelLinear(
            output_size=cfg.hidden_size, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc2",
        )(hcol))


class GPTLayer(nn.Module):
    """Pre-LN transformer block (ref ParallelTransformerLayer).

    ``kv_ctx``/``return_kv`` pass through to
    :class:`ParallelAttention` (the serving decode/prefill hooks).

    ``moe`` selects the MLP: None lets the config decide (every layer
    when ``num_experts>0`` with ``moe_layer_freq=1`` — the scan case);
    the unrolled :class:`GPTModel` path passes
    ``cfg.is_moe_layer(i)`` explicitly. The MoE MLP keeps the dense
    block's ``mlp`` submodule name, so a dense config's param tree is
    untouched (docs/moe.md)."""

    config: GPTConfig
    moe: Optional[bool] = None

    @nn.compact
    def __call__(self, x, *, positions=None, deterministic=True,
                 kv_ctx=None, return_kv=False):
        cfg = self.config
        a = ParallelAttention(cfg, name="attention")(
            FusedLayerNorm(cfg.hidden_size, name="input_norm")(x),
            positions=positions, deterministic=deterministic,
            kv_ctx=kv_ctx, return_kv=return_kv,
        )
        kv = None
        if return_kv:
            a, kv = a
        if cfg.hidden_dropout > 0.0 and not deterministic:
            a = nn.Dropout(rate=cfg.hidden_dropout)(a, deterministic=False)
        x = x + a
        use_moe = (self.moe if self.moe is not None
                   else cfg.is_moe_layer(0) and cfg.moe_layer_freq == 1)
        if use_moe:
            from apex_tpu.moe import MoEMLP

            m = MoEMLP(cfg.moe_cfg(), impl=cfg.moe_impl, name="mlp")(
                FusedLayerNorm(cfg.hidden_size, name="post_norm")(x)
            )
        else:
            m = ParallelMLP(cfg, name="mlp")(
                FusedLayerNorm(cfg.hidden_size, name="post_norm")(x)
            )
        if cfg.hidden_dropout > 0.0 and not deterministic:
            m = nn.Dropout(rate=cfg.hidden_dropout)(m, deterministic=False)
        y = x + m
        return (y, kv) if return_kv else y


class _GPTScanBlock(nn.Module):
    """scan body: carry = hidden states; broadcast inputs = positions.
    ``deterministic`` is a static module attribute so the dropout
    branch stays Python-level (no traced bool inside the scan)."""

    config: GPTConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, positions):
        y = GPTLayer(self.config, name="layer")(
            x, positions=positions, deterministic=self.deterministic)
        return y, None


class _GPTScanBlockKV(nn.Module):
    """scan body for the serving paths: same ``layers/layer`` param
    tree as :class:`_GPTScanBlock` (the two bodies are
    checkpoint-compatible), but each layer additionally consumes its
    own slice of the gathered cache (scanned input, or None for
    prefill) and emits its new K/V as a stacked scan output."""

    config: GPTConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, kv_ctx, positions, ctx_mask):
        ctx = None if kv_ctx is None else (kv_ctx[0], kv_ctx[1], ctx_mask)
        y, kv = GPTLayer(self.config, name="layer")(
            x, positions=positions, deterministic=self.deterministic,
            kv_ctx=ctx, return_kv=True)
        return y, kv


class GPTModel(nn.Module):
    """Full GPT LM. Input token ids (b, s); returns vocab-parallel
    logits in (s, b, vocab[/tp]) layout (Megatron sbh convention)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, tokens, *, positions=None, deterministic=True,
                 kv_ctx=None, ctx_mask=None, return_kv=False):
        """``positions`` int32 override the default ``arange(s)``:
        shape (s,) for one shared schedule (context-sharded sequences,
        attention_backend="ring") or (b, s) per-sequence (the serving
        decode path, where every sequence sits at its own offset) — a
        single-token forward at position t needs only ``positions`` and
        the cache, never the full prefix.

        ``kv_ctx=(k_ctx, v_ctx)`` (num_layers, b, kv_heads, L, head_dim)
        + ``ctx_mask`` (b, L) runs the cached decode path;
        ``return_kv=True`` additionally returns the per-layer K/V of
        this call, stacked (num_layers, b, kv_heads, s, head_dim) —
        both are the serving tier's hooks (apex_tpu/serving)."""
        cfg = self.config
        b, s = tokens.shape
        emb = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="embedding",
        )
        x = emb(tokens)                                   # (b, s, h)
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(stddev=0.02),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype,
        )
        if positions is None:
            pos_emb = pos[None, :s]
        else:
            positions = jnp.asarray(positions)
            pos_emb = jnp.take(pos, positions, axis=0)
            if positions.ndim == 1:
                pos_emb = pos_emb[None]                   # (1, s, h)
        x = _gspmd.constrain_batch_major(x + pos_emb.astype(cfg.dtype))
        x = _gspmd.constrain_hidden(x.transpose(1, 0, 2))  # (s, b, h)

        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                scatter_to_sequence_parallel_region,
            )
            x = scatter_to_sequence_parallel_region(x)

        serving = return_kv or kv_ctx is not None
        kvs = None
        if cfg.scan_layers:
            if serving:
                scan = nn.scan(
                    _GPTScanBlockKV,
                    variable_axes={"params": 0, "intermediates": 0},
                    split_rngs={"params": True, "dropout": True},
                    length=cfg.num_layers,
                    in_axes=((0 if kv_ctx is not None else nn.broadcast),
                             nn.broadcast, nn.broadcast),
                )
                x, kvs = scan(cfg, deterministic, name="layers")(
                    x, kv_ctx, positions, ctx_mask)
            else:
                scan = nn.scan(
                    _GPTScanBlock,
                    variable_axes={"params": 0, "intermediates": 0},
                    split_rngs={"params": True, "dropout": True},
                    length=cfg.num_layers,
                    in_axes=nn.broadcast,
                )
                x, _ = scan(cfg, deterministic, name="layers")(x, positions)
        else:
            per_layer = []
            for i in range(cfg.num_layers):
                ctx = (None if kv_ctx is None else
                       (kv_ctx[0][i], kv_ctx[1][i], ctx_mask))
                x = GPTLayer(cfg, moe=cfg.is_moe_layer(i),
                             name=f"layer_{i}")(
                    x, positions=positions, deterministic=deterministic,
                    kv_ctx=ctx, return_kv=serving)
                if serving:
                    x, kv = x
                    per_layer.append(kv)
            if serving:
                kvs = (jnp.stack([kv[0] for kv in per_layer]),
                       jnp.stack([kv[1] for kv in per_layer]))
        x = FusedLayerNorm(cfg.hidden_size, name="final_norm")(x)

        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                gather_from_sequence_parallel_region,
            )
            x = gather_from_sequence_parallel_region(
                x, tensor_parallel_output_grad=True
            )

        # tied LM head: logits = x @ E^T over the local vocab shard
        # (ref parallel_lm_logits: copy op so dL/dx is allreduced)
        if _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                copy_to_tensor_model_parallel_region,
            )
            x = copy_to_tensor_model_parallel_region(x)
        table = emb.variables["params"]["embedding"]
        logits = _gspmd.constrain_logits(jnp.einsum(
            "sbh,vh->sbv", x.astype(jnp.float32),
            table.astype(jnp.float32),
        ))
        if return_kv:
            return logits, kvs
        return logits


def gpt_loss_fn(logits, labels, axis_name: str = TENSOR_AXIS):
    """Mean CE over tokens; vocab-parallel when inside the mesh.

    logits: (s, b, vocab[/tp]) ; labels: (b, s)
    """
    labels_sb = labels.transpose(1, 0)
    if _inside_axis(axis_name):
        losses = vocab_parallel_cross_entropy(logits, labels_sb,
                                              axis_name=axis_name)
    else:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels_sb[..., None], -1)[..., 0]
        losses = lse - tgt
    return jnp.mean(losses)


# -- partition specs -------------------------------------------------------


def gpt_param_specs(params: Any) -> Any:
    """PartitionSpec tree for a GPTModel param pytree: column kernels
    split on the output dim, row kernels on the input dim, the embedding
    on the vocab dim, everything else replicated."""

    def spec_for(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        joined = "/".join(names)
        if "embedding" in joined and names[-1] == "embedding":
            spec = P(TENSOR_AXIS, None)
        elif ("qkv" in joined or "fc1" in joined) and names[-1] == "kernel":
            spec = P(TENSOR_AXIS, None)
        elif ("qkv" in joined or "fc1" in joined) and names[-1] == "bias":
            spec = P(TENSOR_AXIS)
        elif ("proj" in joined or "fc2" in joined) and names[-1] == "kernel":
            spec = P(None, TENSOR_AXIS)
        elif names[-1] in ("w1", "w2"):
            # MoE expert weights (E, h, ffn) / (E, ffn, h): shard the
            # EXPERT dim on the model axis — expert parallelism rides
            # the same mesh axis tensor parallelism does (docs/moe.md);
            # the router gate stays replicated (falls through to P())
            spec = P(TENSOR_AXIS, None, None)
        else:
            return P()
        if "layers" in names:
            # scan_layers stacks layer params with a leading layer
            # axis; the TP sharding moves one dim to the right
            spec = P(None, *spec)
        return spec

    return jax.tree_util.tree_map_with_path(spec_for, params)

"""Full pretrain-step composition: DP x TP x SP x PP in one SPMD program.

The TPU answer to the reference's GPT pretraining path (ref:
tests/L0/run_transformer/run_gpt_minimal_test.py +
fwd_bwd_pipelining_without_interleaving.py): one `shard_map` over the
(data, pipe, tensor) mesh containing microbatched pipeline forward,
backward, data-parallel grad reduction, and the fused optimizer step —
XLA schedules all collectives (grad psum over data, TP all-reduces,
pipeline ppermutes) against compute.

Layout:
  - embedding / position / final norm / head: replicated over pipe;
    their grads are psum'd over pipe (only the touching stages
    contribute — the reference's embedding-group allreduce,
    ref parallel_state.py:251-276).
  - transformer layers: stacked (num_layers, ...) pytree, leading dim
    sharded over pipe; each stage scans its local layers.
  - TP sharding per gpt_param_specs; batch sharded over data; optimizer
    state packed from LOCAL shards inside shard_map, so Adam/LAMB state
    is TP/PP-sharded for free.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from apex_tpu._compat import shard_map

from apex_tpu.models.gpt import GPTConfig, GPTLayer, gpt_param_specs
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.optimizers.fused import FlatFusedOptimizer
from apex_tpu.transformer.parallel_state import (
    DATA_AXIS,
    PIPELINE_AXIS,
    TENSOR_AXIS,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_with_interleaving,
    last_stage_value,
    spmd_pipeline,
)
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis


def init_gpt_pretrain_params(cfg: GPTConfig, key) -> Any:
    """Initialize the pipeline-layout GPT param tree (full, unsharded)."""
    k_emb, k_layers, k_norm = jax.random.split(key, 3)
    dummy_tokens = jnp.zeros((1, cfg.max_seq_len), jnp.int32)
    emb = VocabParallelEmbedding(
        num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
        param_dtype=cfg.param_dtype, dtype=cfg.dtype,
    )
    emb_params = emb.init(k_emb, dummy_tokens)["params"]
    pos = (
        jax.random.normal(
            jax.random.fold_in(k_emb, 1),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype,
        )
        * 0.02
    )
    layer = GPTLayer(cfg)
    dummy_x = jnp.zeros((cfg.max_seq_len, 1, cfg.hidden_size), cfg.dtype)
    layer_params = jax.vmap(lambda k: layer.init(k, dummy_x)["params"])(
        jax.random.split(k_layers, cfg.num_layers)
    )
    norm_params = FusedLayerNorm(cfg.hidden_size).init(k_norm, dummy_x)["params"]
    return {
        "embedding": emb_params,
        "position_embedding": pos,
        "layers": layer_params,
        "final_norm": norm_params,
    }


def gpt_pretrain_param_specs(params: Any) -> Any:
    """PartitionSpecs for the pipeline-layout tree: TP specs per
    gpt_param_specs, layers sharded over pipe on the stacked dim."""
    tp = gpt_param_specs({"params": {
        "embedding": params["embedding"],
        "layer_0": params["layers"],
        "final_norm": params["final_norm"],
    }})["params"]
    layers = jax.tree.map(lambda s: P(PIPELINE_AXIS, *s), tp["layer_0"])
    return {
        "embedding": tp["embedding"],
        "position_embedding": P(),
        "layers": layers,
        "final_norm": jax.tree.map(lambda _: P(), params["final_norm"]),
    }


def _local_shapes(params: Any, specs: Any, mesh: Mesh) -> Any:
    """Per-device shard shapes implied by the specs."""

    def one(leaf, spec):
        shape = list(leaf.shape)
        for i, ax in enumerate(spec):
            if ax is None:
                continue
            for nm in (ax if isinstance(ax, tuple) else (ax,)):
                shape[i] //= mesh.shape[nm]
        return jax.ShapeDtypeStruct(tuple(shape), leaf.dtype)

    return jax.tree.map(one, params, specs,
                        is_leaf=lambda x: isinstance(x, P))


def _opt_state_specs(optimizer: FlatFusedOptimizer, local_params: Any) -> Any:
    """Specs for the FlatOptState produced inside shard_map: big flat
    buffers are distinct per device -> sharded jointly over all mesh
    axes on dim 0; scalars (count, found_inf, flags) are replicated."""
    state_shape = jax.eval_shape(optimizer.init, local_params)
    joint = P((DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS))
    return jax.tree.map(
        lambda l: joint if l.ndim >= 1 else P(), state_shape
    )


def interleaved_layer_permutation(num_layers: int, pp: int,
                                  vpp: int) -> np.ndarray:
    """Stacked-layer-dim permutation for the interleaved schedule.

    Virtual stage j holds layers [j*L/(pp*vpp), (j+1)*L/(pp*vpp)); rank s
    hosts virtual stages {c*pp + s}. Sharding the stacked (L, ...) layer
    tree over the pipe axis hands rank s a CONTIGUOUS block, so the
    stack must be pre-permuted so that block is exactly rank s's chunks
    in chunk order — the functional analog of the reference's
    model-chunk list construction (ref schedules/common.py:30-151 with
    virtual_pipeline_model_parallel_size).
    """
    per_vstage = num_layers // (pp * vpp)
    order = []
    for s in range(pp):
        for c in range(vpp):
            v = c * pp + s
            order.extend(range(v * per_vstage, (v + 1) * per_vstage))
    return np.asarray(order)


def make_gpt_pretrain_step(
    cfg: GPTConfig,
    mesh: Mesh,
    optimizer: FlatFusedOptimizer,
    *,
    num_microbatches: int = 1,
    remat: bool = True,
    num_model_chunks: int = 1,
):
    """Build the jitted full-parallel train step.

    Returns (init_opt_fn, step_fn, param_specs):
      init_opt_fn(params_global) -> opt_state (sharded)
      step_fn(params, opt_state, tokens, labels) -> (params, opt_state, loss)
    tokens/labels: (global_batch, seq) int32.

    ``num_model_chunks > 1`` selects the interleaved (virtual-pipeline)
    schedule. The CALLER owns the layer layout: a stacked layer tree in
    global order (e.g. a ported checkpoint) must be permuted with
    :func:`interleaved_layer_permutation` before use so each rank's
    contiguous pipe shard holds its vpp chunks in chunk order —
    ``init_gpt_pretrain_params`` does NOT permute (fresh i.i.d. init
    needs no permutation; ordering only matters for pre-trained
    weights). The returned specs are unchanged either way.
    """
    layer = GPTLayer(cfg)
    emb_mod = VocabParallelEmbedding(
        num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
        param_dtype=cfg.param_dtype, dtype=cfg.dtype,
    )
    norm_mod = FusedLayerNorm(cfg.hidden_size)
    pp = mesh.shape[PIPELINE_AXIS]
    vpp = num_model_chunks
    if cfg.num_layers % (pp * vpp):
        raise ValueError(
            "num_layers must be divisible by pipeline size x model chunks")

    def pre_fn(params, mb_tokens):
        x = emb_mod.apply({"params": params["embedding"]}, mb_tokens)
        s = mb_tokens.shape[1]
        x = x + params["position_embedding"][:s][None].astype(cfg.dtype)
        x = x.transpose(1, 0, 2)  # (s, mb, h)
        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                scatter_to_sequence_parallel_region,
            )
            x = scatter_to_sequence_parallel_region(x)
        return x

    def stage_fn(params, x):
        def body(h, lp):
            return layer.apply({"params": lp}, h), None

        y, _ = lax.scan(body, x, params["layers"])
        return y

    def stage_fn_chunk(params, x, chunk_id):
        # vpp: this rank's local (L/pp)-layer stack is its vpp chunks in
        # chunk order (interleaved_layer_permutation layout); scan the
        # chunk_id-th slice
        per = cfg.num_layers // (pp * vpp)
        chunk_layers = jax.tree.map(
            lambda l: lax.dynamic_slice_in_dim(l, chunk_id * per, per, 0),
            params["layers"])

        def body(h, lp):
            return layer.apply({"params": lp}, h), None

        y, _ = lax.scan(body, x, chunk_layers)
        return y

    def loss_fn_mb(params, y, mb_labels):
        y = norm_mod.apply({"params": params["final_norm"]}, y)
        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                gather_from_sequence_parallel_region,
            )
            y = gather_from_sequence_parallel_region(
                y, tensor_parallel_output_grad=True
            )
        if _inside_axis(TENSOR_AXIS):
            y = copy_to_tensor_model_parallel_region(y)
        table = params["embedding"]["embedding"]
        logits = jnp.einsum(
            "sbh,vh->sbv", y.astype(jnp.float32), table.astype(jnp.float32)
        )
        labels_sb = mb_labels.transpose(1, 0)
        if _inside_axis(TENSOR_AXIS):
            losses = vocab_parallel_cross_entropy(logits, labels_sb)
        else:
            # fused xentropy: saves only the logsumexp residual instead
            # of re-deriving softmax grads through the XLA lse graph
            # (ref apex.contrib.xentropy memory story)
            from apex_tpu.ops import softmax_cross_entropy_loss

            losses = softmax_cross_entropy_loss(logits, labels_sb)
        return jnp.mean(losses)

    def local_loss(params, tokens, labels):
        m = num_microbatches
        mb_tok = tokens.reshape(m, tokens.shape[0] // m, -1)
        mb_lab = labels.reshape(m, labels.shape[0] // m, -1)
        # embedding and loss fold INTO the pipeline ticks (stage-0 /
        # last-stage respectively) and the tick scan is chunk-
        # checkpointed: saved state ~O(pipeline depth), never all-M
        # embeddings or logits (see schedules.spmd_pipeline docstring)
        loss_sum = spmd_pipeline(
            stage_fn, params, mb_tok, axis_name=PIPELINE_AXIS, remat=remat,
            pre_fn=pre_fn,
            loss_fn=lambda y, l: loss_fn_mb(params, y, l),
            loss_batches=mb_lab,
        )
        return loss_sum / m

    def local_loss_vpp(params, tokens, labels):
        """Interleaved (virtual-pipeline) loss+grads via the staggered
        tick-scan schedule; loss head takes params so the tied-embedding
        projection's grads flow."""
        loss, grads = forward_backward_pipelining_with_interleaving(
            stage_fn_chunk,
            lambda p, y, b: loss_fn_mb(p, y, b["labels"]),
            lambda p, b: pre_fn(p, b["tokens"]),
            params, {"tokens": tokens, "labels": labels},
            num_microbatches=num_microbatches, num_model_chunks=vpp,
            remat=remat, loss_takes_params=True,
        )
        return loss, grads

    def step(params, opt_state, tokens, labels):
        if vpp > 1:
            loss, grads = local_loss_vpp(params, tokens, labels)
        else:
            loss, grads = jax.value_and_grad(local_loss)(
                params, tokens, labels)
        for name in ("embedding", "position_embedding", "final_norm"):
            grads[name] = jax.tree.map(
                lambda g: lax.psum(g, PIPELINE_AXIS), grads[name]
            )
        grads = jax.tree.map(lambda g: lax.pmean(g, DATA_AXIS), grads)
        params, opt_state = optimizer.step(opt_state, grads)
        # reported loss: average over data shards, broadcast from the
        # last pipeline stage (ref average_losses_across_data_parallel_group)
        loss = lax.pmean(loss, DATA_AXIS)
        return params, opt_state, last_stage_value(loss, PIPELINE_AXIS)

    def params_specs(params):
        return gpt_pretrain_param_specs(params)

    def build(params):
        specs = params_specs(params)
        local_params = _local_shapes(params, specs, mesh)
        opt_specs = _opt_state_specs(optimizer, local_params)
        init_opt = jax.jit(
            shard_map(
                optimizer.init, mesh=mesh, in_specs=(specs,),
                out_specs=opt_specs, check_vma=False,
            )
        )
        step_fn = jax.jit(
            shard_map(
                step, mesh=mesh,
                in_specs=(specs, opt_specs, P(DATA_AXIS), P(DATA_AXIS)),
                out_specs=(specs, opt_specs, P()),
                check_vma=False,
            )
        )
        return init_opt, step_fn, specs

    return build

"""Full pretrain-step composition on the GSPMD mesh.

The TPU answer to the reference's GPT pretraining path (ref:
tests/L0/run_transformer/run_gpt_minimal_test.py). Pre-PR-16 this
module drove a `shard_map` over the legacy (data, pipe, tensor) mesh
with explicit collectives (grad psum, TP all-reduces, pipeline
ppermutes); it is now a thin composition over the ONE mesh substrate:

- params are the standard scan-layers :class:`GPTModel` variables tree
  (one layout for training, pipelining, and serving — no pipeline-
  specific tree, no layer permutation helpers);
- dp/tp come from the mesh axes via :func:`apex_tpu.mesh.plan_gpt`'s
  NamedShardings and the model's annotate hints;
- pp comes from a :class:`~apex_tpu.mesh.pipeline.PipelineSpec`
  schedule on the ``pipe`` axis, with XLA inserting the stage-boundary
  transfers (no ppermute in sight);
- the optimizer is the fused flat-space step inside the same donated
  program (:class:`~apex_tpu.mesh.mesh.MeshTrainStep`).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers.fused import FlatFusedOptimizer


def init_gpt_pretrain_params(cfg: GPTConfig, key) -> Any:
    """Initialize the GPT param tree for pretraining — the standard
    ``GPTModel.init`` variables dict (``{"params": {embedding,
    position_embedding, layers, final_norm}}``). Since PR-16 there is
    no separate pipeline layout: the SAME tree feeds the plain mesh
    step, every pipeline schedule, and the serving engine."""
    dummy_tokens = jnp.zeros((1, cfg.max_seq_len), jnp.int32)
    return GPTModel(cfg).init(key, dummy_tokens)


def make_gpt_pretrain_step(
    cfg: GPTConfig,
    optimizer: FlatFusedOptimizer,
    *,
    schedule: str = "1f1b",
    num_microbatches: int = 4,
    remat: bool = True,
    num_model_chunks: int = 1,
    mesh=None,
):
    """Build the mesh-native pretrain step factory.

    Returns ``build(params) -> (step, state)``: ``step`` is a
    :class:`~apex_tpu.mesh.mesh.MeshTrainStep` (pipe axis 1) or
    :class:`~apex_tpu.mesh.pipeline.MeshPipelineTrainStep` (pipe axis
    > 1, running ``schedule`` with ``num_microbatches``), and
    ``state`` is its committed, DONATED optimizer state — drive the
    loop as ``state, loss = step(state, tokens, labels)``.

    ``mesh`` defaults to the live GSPMD mesh
    (:func:`apex_tpu.mesh.initialize_mesh` first); with none armed the
    build degenerates to the identity single-device plan — the same
    code path, byte-identical programs (the mesh module's 1-chip
    guarantee). ``num_model_chunks > 1`` selects the interleaved-1F1B
    schedule regardless of ``schedule``.

    MoE configs (``cfg.num_experts > 0``, docs/moe.md) swap in a loss
    that applies the model with ``mutable=["intermediates"]``, folds
    ``cfg.moe_aux_loss_weight x`` the Switch aux loss into the scalar,
    and threads the per-step stats (aux loss, (E,) expert load,
    dropped copies) out as the step's aux — published each step as the
    ``moe_*`` gauges through
    :func:`apex_tpu.telemetry.moe.publish_moe_step` (which also runs
    the ``moe_imbalance`` EWMA latch). MoE + pipe>1 is not wired yet
    and raises.
    """
    from apex_tpu import mesh as gmesh

    model = GPTModel(cfg)

    def build(params) -> Tuple[Any, Any]:
        if mesh is not None:
            plan = gmesh.plan_gpt(params, mesh=mesh)
        elif gmesh.mesh_initialized():
            plan = gmesh.plan_gpt(params)
        else:
            from jax.sharding import Mesh
            import numpy as np

            one = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
                       gmesh.MESH_AXES)
            plan = gmesh.plan_gpt(params, mesh=one)
        sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
        pp = int(sizes.get(gmesh.PIPE_AXIS, 1))
        if pp > 1:
            if cfg.num_experts > 0:
                raise NotImplementedError(
                    "MoE over the pipe axis is not wired yet: run MoE "
                    "configs with pipe=1 (dp x ep/tp on batch x model)")
            spec = gmesh.PipelineSpec(
                schedule=("interleaved_1f1b" if num_model_chunks > 1
                          else schedule),
                num_stages=pp,
                num_microbatches=num_microbatches,
                num_model_chunks=max(num_model_chunks, 1))
            step = gmesh.make_mesh_pipeline_train_step(
                model, optimizer, plan, spec, remat=remat)
        elif cfg.num_experts > 0:
            from apex_tpu.models.gpt import gpt_loss_fn
            from apex_tpu.moe import collect_moe_stats
            from apex_tpu.telemetry import moe as _tmoe

            def moe_loss_fn(p, tokens, labels):
                logits, mut = model.apply(
                    p, tokens, mutable=["intermediates"])
                stats = collect_moe_stats(
                    mut, num_experts=cfg.num_experts)
                lm = gpt_loss_fn(logits, labels)
                total = lm + (cfg.moe_aux_loss_weight
                              * stats["aux_loss"])
                return total, {"lm_loss": lm, **stats}

            step = gmesh.make_mesh_train_step(
                model, optimizer, plan, loss_fn=moe_loss_fn,
                loss_has_aux=True, aux_sink=_tmoe.publish_moe_step)
        else:
            step = gmesh.make_mesh_train_step(model, optimizer, plan)
        state = step.init(params)
        return step, state

    return build

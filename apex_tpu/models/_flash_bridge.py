"""Shared (s, b, heads, head_dim) <-> kernel-layout bridge.

Every model family stores activations in Megatron's sbh convention;
the flash kernel wants (b, heads, s, d). One helper owns the transpose
pair so a kernel-interface change lands once, not per model.
"""

from __future__ import annotations

import jax

from apex_tpu.ops.attention import flash_attention


def flash_sbhd(q: jax.Array, k: jax.Array, v: jax.Array, **kwargs):
    """q (sq, b, h, d), k/v (sk, b, hk, d) -> (sq, b, h*d).

    kwargs pass straight to :func:`flash_attention` (causal, segment
    ids, dropout, window, positions, impl, ...).
    """
    sq, b = q.shape[0], q.shape[1]
    qb, kb, vb = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
    out = flash_attention(qb, kb, vb, **kwargs)
    return out.transpose(2, 0, 1, 3).reshape(sq, b, -1)


__all__ = ["flash_sbhd"]

"""Model zoo — the standalone test/benchmark fixtures as real models
(ref: apex/transformer/testing/standalone_{gpt,bert}.py and the
1574-LoC transformer LM fixture; resnet mirrors examples/imagenet).

Importing the package loads every family (the surface lock and
packaging both want the full tree importable); reach for a submodule
directly if import cost matters.
"""

from apex_tpu.models import bert, gpt, migrate, pretrain, resnet, t5  # noqa: F401
from apex_tpu.models.migrate import (  # noqa: F401
    stack_scan_params,
    unstack_scan_params,
)

__all__ = ["bert", "gpt", "migrate", "pretrain", "resnet", "t5",
           "stack_scan_params", "unstack_scan_params"]

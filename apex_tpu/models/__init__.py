"""Model zoo — the standalone test/benchmark fixtures as real models
(ref: apex/transformer/testing/standalone_{gpt,bert}.py and the
1574-LoC transformer LM fixture; resnet mirrors examples/imagenet).

Submodules import lazily: each model family pulls heavy deps
(flax transformer stack, parallel layers) only when used.
"""

from apex_tpu.models import bert, gpt, pretrain, resnet, t5  # noqa: F401

__all__ = ["bert", "gpt", "pretrain", "resnet", "t5"]

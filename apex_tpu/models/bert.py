"""Megatron-style BERT — second model family.

TPU re-design of the reference's standalone BERT test fixture
(ref: apex/transformer/testing/standalone_bert.py: embedding with
tokentypes + bidirectional padding-mask transformer + pooler +
BertLMHead (dense-gelu-LN-tied-logits+bias) + binary NSP head, MLM loss
via vocab-parallel cross entropy). Built from the same apex_tpu
parallel layers as GPT, so one module covers dense, TP (+SP) inside
shard_map, and pipeline stages.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    max_seq_len: int = 512
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_hidden_size: Optional[int] = None   # default 4*hidden
    num_tokentypes: int = 2
    add_binary_head: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    softmax_impl: Optional[str] = None
    # "softmax": fused scaled-masked softmax over materialized scores
    # (the reference fixture's path); "flash": the Pallas flash kernel
    # with the padding mask as segment ids and fused in-kernel dropout
    attention_backend: str = "softmax"
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    layernorm_epsilon: float = 1e-5
    # lax.scan over stacked layer params — one compiled layer body
    # instead of num_layers inlined copies (see GPTConfig.scan_layers;
    # 24 unrolled layers crash the Mosaic compile helper on chip).
    scan_layers: bool = True

    def __post_init__(self):
        if self.attention_backend not in ("softmax", "flash"):
            raise ValueError(
                f"attention_backend must be 'softmax' or 'flash', got "
                f"{self.attention_backend!r}")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    # BERT-large (BASELINE configs[2]: ref run_bert_minimal_test.py)
    @staticmethod
    def bert_large(**kw) -> "BertConfig":
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          max_seq_len=512, **kw)


def bert_extended_attention_mask(attention_mask: jax.Array) -> jax.Array:
    """(b, s) {0,1} keep-mask -> (b, 1, s, s) boolean, True = masked
    (ref: standalone_bert.py:20-33 — outer product of the key/query
    keep vectors, then inverted to masked-out form)."""
    m = attention_mask.astype(jnp.float32)
    bss = m[:, None, :] * m[:, :, None]
    return (bss < 0.5)[:, None, :, :]


class BertParallelAttention(nn.Module):
    """Bidirectional self attention with padding mask: column-parallel
    fused QKV, fused masked softmax, row-parallel projection (ref
    standalone_transformer_lm.py ParallelAttention with
    AttnMaskType.padding)."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, *, deterministic=True):
        cfg = self.config
        h = cfg.hidden_size
        inside = _inside_axis(TENSOR_AXIS)
        tp = lax.axis_size(TENSOR_AXIS) if inside else 1
        heads_local = cfg.num_heads // tp
        head_dim = h // cfg.num_heads

        qkv = ColumnParallelLinear(
            output_size=3 * h, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="qkv",
        )(x)
        s, b = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(s, b, heads_local, 3 * head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        if cfg.attention_backend == "flash":
            # `mask` is the raw (b, s) keep-mask: as segment ids, real
            # tokens (1) attend real tokens and pads attend pads —
            # identical to the outer-product padding mask on every real
            # row (pad rows are garbage under both conventions and are
            # excluded from the loss). Dropout runs inside the kernel.
            from apex_tpu.models._flash_bridge import flash_sbhd

            drop = (cfg.attention_dropout
                    if cfg.attention_dropout > 0.0 and not deterministic
                    else 0.0)
            ctx = flash_sbhd(
                q, k, v, segment_ids=mask.astype(jnp.int32),
                dropout_rate=drop,
                dropout_rng=(self.make_rng("dropout") if drop > 0.0
                             else None),
                impl=cfg.softmax_impl)
        else:
            def to_bhsd(t):
                return t.transpose(1, 2, 0, 3).reshape(
                    b * heads_local, s, head_dim)

            q, k, v = to_bhsd(q), to_bhsd(k), to_bhsd(v)
            scores = jnp.einsum(
                "bsd,btd->bst", q, k, preferred_element_type=jnp.float32
            ) / jnp.sqrt(head_dim).astype(jnp.float32)
            probs = FusedScaleMaskSoftmax(
                attn_mask_type=AttnMaskType.padding, impl=cfg.softmax_impl
            )(scores.reshape(b, heads_local, s, s).astype(cfg.dtype),
              mask=mask)
            if cfg.attention_dropout > 0.0 and not deterministic:
                probs = nn.Dropout(rate=cfg.attention_dropout)(
                    probs, deterministic=False
                )
            ctx = jnp.einsum(
                "bhst,bhtd->bhsd", probs,
                v.reshape(b, heads_local, s, head_dim),
                preferred_element_type=jnp.float32,
            ).astype(cfg.dtype)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(
                s, b, heads_local * head_dim)
        return RowParallelLinear(
            output_size=h, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="proj",
        )(ctx)


class BertLayer(nn.Module):
    """Pre-LN transformer block with padding-mask attention."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, *, deterministic=True):
        cfg = self.config
        a = BertParallelAttention(cfg, name="attention")(
            FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                           name="input_norm")(x),
            mask, deterministic=deterministic,
        )
        if cfg.hidden_dropout > 0.0 and not deterministic:
            a = nn.Dropout(rate=cfg.hidden_dropout)(a, deterministic=False)
        x = x + a
        hcol = ColumnParallelLinear(
            output_size=cfg.ffn, gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc1",
        )(FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                         name="post_norm")(x))
        hcol = jax.nn.gelu(hcol, approximate=True)
        m = RowParallelLinear(
            output_size=cfg.hidden_size, input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc2",
        )(hcol)
        if cfg.hidden_dropout > 0.0 and not deterministic:
            m = nn.Dropout(rate=cfg.hidden_dropout)(m, deterministic=False)
        return x + m


class BertPooler(nn.Module):
    """dense+tanh over the [CLS] (first) token (ref
    standalone_transformer_lm.py Pooler)."""

    config: BertConfig

    @nn.compact
    def __call__(self, x):          # (s, b, h) -> (b, h)
        cfg = self.config
        first = x[0]
        w = self.param("kernel", nn.initializers.normal(stddev=0.02),
                       (cfg.hidden_size, cfg.hidden_size), cfg.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.hidden_size,), cfg.param_dtype)
        out = first.astype(cfg.dtype) @ w.astype(cfg.dtype) + bias.astype(cfg.dtype)
        return jnp.tanh(out)


class BertLMHead(nn.Module):
    """MLM head: dense -> gelu -> LN -> tied-embedding logits + vocab
    bias (ref: standalone_bert.py:47-92). The bias is sharded over the
    local vocab shard like the embedding table."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, word_embedding_table):
        cfg = self.config
        w = self.param("kernel", nn.initializers.normal(stddev=0.02),
                       (cfg.hidden_size, cfg.hidden_size), cfg.param_dtype)
        b = self.param("dense_bias", nn.initializers.zeros,
                       (cfg.hidden_size,), cfg.param_dtype)
        x = x.astype(cfg.dtype) @ w.astype(cfg.dtype) + b.astype(cfg.dtype)
        x = jax.nn.gelu(x, approximate=True)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                           name="norm")(x)

        if _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                copy_to_tensor_model_parallel_region,
            )
            x = copy_to_tensor_model_parallel_region(x)
            tp = lax.axis_size(TENSOR_AXIS)
        else:
            tp = 1
        vocab_local = divide(cfg.vocab_size, tp)
        vbias = self.param("bias", nn.initializers.zeros,
                           (vocab_local,), cfg.param_dtype)
        logits = jnp.einsum(
            "sbh,vh->sbv", x.astype(jnp.float32),
            word_embedding_table.astype(jnp.float32),
        )
        return logits + vbias.astype(jnp.float32)


class _BertScanBlock(nn.Module):
    """scan body: carry = hidden states; broadcast input = the
    attention mask. ``deterministic`` stays a static attribute."""

    config: BertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, ext_mask):
        y = BertLayer(self.config, name="layer")(
            x, ext_mask, deterministic=self.deterministic)
        return y, None


class BertModel(nn.Module):
    """Full BERT. Inputs: token ids (b, s), attention keep-mask (b, s),
    optional tokentype ids (b, s). Returns (lm_logits (s, b, vocab[/tp]),
    binary_logits (b, 2) | None) — the Megatron sbh convention
    (ref: standalone_bert.py:123-203)."""

    config: BertConfig

    @nn.compact
    def __call__(self, tokens, attention_mask, tokentype_ids=None, *,
                 deterministic=True):
        cfg = self.config
        b, s = tokens.shape
        # the flash backend consumes the raw (b, s) keep-mask (segment
        # ids); the softmax backend the outer-product boolean mask
        ext_mask = (attention_mask
                    if cfg.attention_backend == "flash"
                    else bert_extended_attention_mask(attention_mask))

        emb = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="embedding",
        )
        x = emb(tokens)                                    # (b, s, h)
        pos = self.param(
            "position_embedding", nn.initializers.normal(stddev=0.02),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype,
        )
        x = x + pos[:s][None, :, :].astype(cfg.dtype)
        if cfg.num_tokentypes > 0:
            tt = self.param(
                "tokentype_embedding", nn.initializers.normal(stddev=0.02),
                (cfg.num_tokentypes, cfg.hidden_size), cfg.param_dtype,
            )
            if tokentype_ids is None:
                tokentype_ids = jnp.zeros_like(tokens)
            x = x + jnp.take(tt.astype(cfg.dtype), tokentype_ids, axis=0)
        x = x.transpose(1, 0, 2)                           # (s, b, h)

        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                scatter_to_sequence_parallel_region,
            )
            x = scatter_to_sequence_parallel_region(x)

        if cfg.scan_layers:
            scan = nn.scan(
                _BertScanBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                in_axes=nn.broadcast,
            )
            x, _ = scan(cfg, deterministic, name="layers")(x, ext_mask)
        else:
            for i in range(cfg.num_layers):
                x = BertLayer(cfg, name=f"layer_{i}")(
                    x, ext_mask, deterministic=deterministic)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_epsilon,
                           name="final_norm")(x)

        if cfg.sequence_parallel and _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                gather_from_sequence_parallel_region,
            )
            x = gather_from_sequence_parallel_region(
                x, tensor_parallel_output_grad=True
            )

        binary_logits = None
        if cfg.add_binary_head:
            pooled = BertPooler(cfg, name="pooler")(x)
            wb = self.param("binary_kernel",
                            nn.initializers.normal(stddev=0.02),
                            (cfg.hidden_size, 2), cfg.param_dtype)
            bb = self.param("binary_bias", nn.initializers.zeros,
                            (2,), cfg.param_dtype)
            binary_logits = (pooled.astype(jnp.float32)
                             @ wb.astype(jnp.float32)
                             + bb.astype(jnp.float32))

        table = emb.variables["params"]["embedding"]
        lm_logits = BertLMHead(cfg, name="lm_head")(x, table)
        return lm_logits, binary_logits


def bert_loss_fn(
    lm_logits: jax.Array,
    binary_logits: Optional[jax.Array],
    lm_labels: jax.Array,
    loss_mask: jax.Array,
    nsp_labels: Optional[jax.Array] = None,
    axis_name: str = TENSOR_AXIS,
) -> jax.Array:
    """Masked-LM loss (+ NSP when heads/labels present), the loss used by
    ref run_bert_minimal_test.py: per-token vocab-parallel CE averaged
    over masked positions, plus 2-way CE on the pooled head.

    lm_logits: (s, b, vocab[/tp]); lm_labels/loss_mask: (b, s).
    """
    labels_sb = lm_labels.transpose(1, 0)
    if _inside_axis(axis_name):
        losses = vocab_parallel_cross_entropy(lm_logits, labels_sb,
                                              axis_name=axis_name)
    else:
        lse = jax.scipy.special.logsumexp(lm_logits, axis=-1)
        tgt = jnp.take_along_axis(lm_logits, labels_sb[..., None], -1)[..., 0]
        losses = lse - tgt
    mask_sb = loss_mask.transpose(1, 0).astype(jnp.float32)
    lm_loss = jnp.sum(losses * mask_sb) / jnp.maximum(jnp.sum(mask_sb), 1.0)
    if binary_logits is None or nsp_labels is None:
        return lm_loss
    logp = jax.nn.log_softmax(binary_logits, axis=-1)
    nsp = -jnp.mean(jnp.take_along_axis(logp, nsp_labels[:, None], 1)[:, 0])
    return lm_loss + nsp


def bert_param_specs(params: Any) -> Any:
    """PartitionSpec tree for BertModel params (same rules as
    gpt_param_specs plus the vocab-sharded LM-head bias)."""

    def spec_for(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        joined = "/".join(names)
        if "embedding" in joined and names[-1] == "embedding":
            spec = P(TENSOR_AXIS, None)
        elif ("qkv" in joined or "fc1" in joined) and names[-1] == "kernel":
            spec = P(TENSOR_AXIS, None)
        elif ("qkv" in joined or "fc1" in joined) and names[-1] == "bias":
            spec = P(TENSOR_AXIS)
        elif ("proj" in joined or "fc2" in joined) and names[-1] == "kernel":
            spec = P(None, TENSOR_AXIS)
        elif names[-2:] == ["lm_head", "bias"]:   # the vocab-sharded bias
            spec = P(TENSOR_AXIS)
        else:
            return P()
        if "layers" in names:
            # scan_layers stacks layer params with a leading layer
            # axis; the TP sharding moves one dim to the right
            spec = P(None, *spec)
        return spec

    return jax.tree_util.tree_map_with_path(spec_for, params)

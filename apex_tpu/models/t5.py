"""Megatron-style encoder-decoder LM — third model family.

TPU re-design of the reference's encoder-decoder support
(ref: apex/transformer/testing/standalone_transformer_lm.py:
ParallelAttention with AttnType.cross_attn (:358-583),
ParallelTransformerLayer with LayerType.decoder (:598-778),
get_language_model(add_decoder=True) (:1167-1206); pipeline split rank
parallel_state.py:178-180,423-460). Architecture: shared vocab
embedding, bidirectional encoder over padding masks, decoder with
causal self-attention + cross-attention into the encoder output, tied
LM head, vocab-parallel CE — a T5/Megatron-enc-dec shape built from
the same apex_tpu parallel layers as GPT/BERT, so dense, TP, and
TP+SP-on-the-encoder all come from one definition.

With pipeline parallelism the stage split follows the reference's
``pipeline_model_parallel_split_rank``: encoder layers occupy stages
[0, split) and decoder layers [split, pp) — the per-stage layer counts
are computed by :func:`encoder_decoder_stage_layout`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.enums import AttnMaskType, AttnType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import _inside_axis


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    max_seq_len: int = 512
    hidden_size: int = 768
    num_encoder_layers: int = 12
    num_decoder_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    softmax_impl: Optional[str] = None
    # "softmax": materialized scores + fused masked softmax (the
    # reference fixture's path); "flash": the Pallas kernel — encoder
    # padding as segment ids, decoder causal, cross-attention via
    # key-side-only segment masking
    attention_backend: str = "softmax"
    # lax.scan over stacked encoder/decoder layer params (see
    # GPTConfig.scan_layers — unrolled stacks crash the Mosaic compile
    # helper and compile slowly everywhere)
    scan_layers: bool = True

    def __post_init__(self):
        if self.attention_backend not in ("softmax", "flash"):
            raise ValueError(
                f"attention_backend must be 'softmax' or 'flash', got "
                f"{self.attention_backend!r}")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size


def encoder_decoder_stage_layout(
    num_encoder_layers: int,
    num_decoder_layers: int,
    pipeline_size: int,
    split_rank: int,
) -> Tuple[Tuple[str, int], ...]:
    """Per-stage (kind, n_layers) for enc-dec pipelining (ref
    parallel_state.py:423-460 + get_num_layers,
    standalone_transformer_lm.py:1038-1096): encoder on stages
    [0, split_rank), decoder on [split_rank, pp)."""
    if not (0 < split_rank < pipeline_size):
        raise ValueError(
            f"split_rank {split_rank} must be inside (0, {pipeline_size})")
    if num_encoder_layers % split_rank:
        raise ValueError("encoder layers must divide encoder stages")
    if num_decoder_layers % (pipeline_size - split_rank):
        raise ValueError("decoder layers must divide decoder stages")
    enc_per = num_encoder_layers // split_rank
    dec_per = num_decoder_layers // (pipeline_size - split_rank)
    return tuple(
        ("encoder", enc_per) if s < split_rank else ("decoder", dec_per)
        for s in range(pipeline_size))


class _Attention(nn.Module):
    """Self or cross parallel attention (ref ParallelAttention,
    standalone_transformer_lm.py:358-583): column-parallel projections,
    fused masked softmax, row-parallel output."""

    config: T5Config
    attn_type: Any = AttnType.self_attn
    mask_type: Any = AttnMaskType.padding

    @nn.compact
    def __call__(self, x, kv_source=None, mask=None):
        cfg = self.config
        h = cfg.hidden_size
        inside = _inside_axis(TENSOR_AXIS)
        tp = lax.axis_size(TENSOR_AXIS) if inside else 1
        heads_local = cfg.num_heads // tp
        head_dim = h // cfg.num_heads

        if self.attn_type == AttnType.self_attn:
            qkv = ColumnParallelLinear(
                output_size=3 * h, gather_output=False,
                param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="qkv",
            )(x)
            sq, b = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape(sq, b, heads_local, 3 * head_dim)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            sk = sq
        else:
            # cross attention: Q from decoder hidden, KV from encoder
            # output (ref :406-421 separate query/key_value projections)
            q = ColumnParallelLinear(
                output_size=h, gather_output=False,
                param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="q",
            )(x)
            kv = ColumnParallelLinear(
                output_size=2 * h, gather_output=False,
                param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="kv",
            )(kv_source)
            sq, b = q.shape[0], q.shape[1]
            sk = kv.shape[0]
            q = q.reshape(sq, b, heads_local, head_dim)
            kv = kv.reshape(sk, b, heads_local, 2 * head_dim)
            k, v = jnp.split(kv, 2, axis=-1)

        if cfg.attention_backend == "flash":
            # mask here is the RAW (b, s_kv) keep-mask (or None for the
            # causal decoder): self-attn uses it as segment ids on both
            # sides; cross-attn masks keys only (kv_segment_ids with
            # real keys in segment 0 — the kernel's key-side mode)
            from apex_tpu.models._flash_bridge import flash_sbhd

            kwargs = {}
            causal = self.mask_type == AttnMaskType.causal
            if not causal and mask is not None:
                if self.attn_type == AttnType.self_attn:
                    kwargs["segment_ids"] = mask.astype(jnp.int32)
                else:
                    kwargs["kv_segment_ids"] = (
                        1 - mask.astype(jnp.int32))
            ctx = flash_sbhd(q, k, v, causal=causal,
                             impl=cfg.softmax_impl, **kwargs)
        else:
            def to_bhsd(t, s):
                return t.transpose(1, 2, 0, 3).reshape(
                    b * heads_local, s, head_dim)

            q, k, v = to_bhsd(q, sq), to_bhsd(k, sk), to_bhsd(v, sk)
            scores = jnp.einsum(
                "bsd,btd->bst", q, k, preferred_element_type=jnp.float32
            ) / jnp.sqrt(head_dim).astype(jnp.float32)
            probs = FusedScaleMaskSoftmax(
                attn_mask_type=self.mask_type, impl=cfg.softmax_impl
            )(scores.reshape(b, heads_local, sq, sk).astype(cfg.dtype),
              mask=mask)
            ctx = jnp.einsum(
                "bhst,bhtd->bhsd", probs,
                v.reshape(b, heads_local, sk, head_dim),
                preferred_element_type=jnp.float32,
            ).astype(cfg.dtype)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(
                sq, b, heads_local * head_dim)
        return RowParallelLinear(
            output_size=h, input_is_parallel=True,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="proj",
        )(ctx)


class _MLP(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        y = ColumnParallelLinear(
            output_size=cfg.ffn, gather_output=False,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc1",
        )(x)
        y = jax.nn.gelu(y, approximate=True)
        return RowParallelLinear(
            output_size=cfg.hidden_size, input_is_parallel=True,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="fc2",
        )(y)


class _EncScanBlock(nn.Module):
    """scan body for the encoder stack (see GPTConfig.scan_layers)."""

    config: "T5Config"

    @nn.compact
    def __call__(self, x, enc_mask):
        return EncoderLayer(self.config, name="layer")(x, enc_mask), None


class _DecScanBlock(nn.Module):
    """scan body for the decoder stack: broadcast inputs are the
    encoder output and the cross-attention mask."""

    config: "T5Config"

    @nn.compact
    def __call__(self, x, enc_out, cross_mask):
        return DecoderLayer(self.config, name="layer")(
            x, enc_out, cross_mask), None


class EncoderLayer(nn.Module):
    """Pre-LN: bidirectional self-attn + MLP (ref
    ParallelTransformerLayer with LayerType.encoder)."""

    config: T5Config

    @nn.compact
    def __call__(self, x, enc_mask):
        cfg = self.config
        x = x + _Attention(cfg, AttnType.self_attn, AttnMaskType.padding,
                           name="self_attention")(
            FusedLayerNorm(cfg.hidden_size, name="input_norm")(x),
            mask=enc_mask)
        x = x + _MLP(cfg, name="mlp")(
            FusedLayerNorm(cfg.hidden_size, name="post_norm")(x))
        return x


class DecoderLayer(nn.Module):
    """Pre-LN: causal self-attn + cross-attn + MLP (ref
    ParallelTransformerLayer with LayerType.decoder, :690-778)."""

    config: T5Config

    @nn.compact
    def __call__(self, x, enc_out, cross_mask):
        cfg = self.config
        x = x + _Attention(cfg, AttnType.self_attn, AttnMaskType.causal,
                           name="self_attention")(
            FusedLayerNorm(cfg.hidden_size, name="input_norm")(x))
        x = x + _Attention(cfg, AttnType.cross_attn, AttnMaskType.padding,
                           name="inter_attention")(
            FusedLayerNorm(cfg.hidden_size, name="post_attn_norm")(x),
            kv_source=enc_out, mask=cross_mask)
        x = x + _MLP(cfg, name="mlp")(
            FusedLayerNorm(cfg.hidden_size, name="post_norm")(x))
        return x


class T5Model(nn.Module):
    """Encoder-decoder LM. Inputs: encoder tokens (b, s_enc) + keep
    mask (b, s_enc), decoder tokens (b, s_dec). Returns vocab[/tp]
    logits (s_dec, b, v) in Megatron sbh convention."""

    config: T5Config

    @nn.compact
    def __call__(self, enc_tokens, enc_mask, dec_tokens):
        cfg = self.config
        b, s_enc = enc_tokens.shape
        s_dec = dec_tokens.shape[1]

        emb = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size, embedding_dim=cfg.hidden_size,
            param_dtype=cfg.param_dtype, dtype=cfg.dtype, name="embedding",
        )
        pos = self.param(
            "position_embedding", nn.initializers.normal(stddev=0.02),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype,
        )

        if cfg.attention_backend == "flash":
            # the kernel consumes the raw keep-mask
            enc_attn_mask = enc_mask
            cross_mask = enc_mask
        else:
            # (b, 1, sq, sk) True = masked
            m = enc_mask.astype(jnp.float32)
            enc_attn_mask = (m[:, None, :] * m[:, :, None] < 0.5)[:, None]
            cross_mask = (m[:, None, :] < 0.5)[:, None].repeat(s_dec, axis=2)

        x = emb(enc_tokens) + pos[:s_enc][None].astype(cfg.dtype)
        x = x.transpose(1, 0, 2)
        if cfg.scan_layers:
            enc_scan = nn.scan(
                _EncScanBlock, variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_encoder_layers, in_axes=nn.broadcast,
            )
            x, _ = enc_scan(cfg, name="encoder_layers")(x, enc_attn_mask)
        else:
            for i in range(cfg.num_encoder_layers):
                x = EncoderLayer(cfg, name=f"encoder_{i}")(x, enc_attn_mask)
        enc_out = FusedLayerNorm(cfg.hidden_size, name="encoder_norm")(x)

        y = emb(dec_tokens) + pos[:s_dec][None].astype(cfg.dtype)
        y = y.transpose(1, 0, 2)
        if cfg.scan_layers:
            dec_scan = nn.scan(
                _DecScanBlock, variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_decoder_layers, in_axes=nn.broadcast,
            )
            y, _ = dec_scan(cfg, name="decoder_layers")(
                y, enc_out, cross_mask)
        else:
            for i in range(cfg.num_decoder_layers):
                y = DecoderLayer(cfg, name=f"decoder_{i}")(
                    y, enc_out, cross_mask)
        y = FusedLayerNorm(cfg.hidden_size, name="decoder_norm")(y)

        # tied LM head (ref parallel_lm_logits :1130-1164)
        if _inside_axis(TENSOR_AXIS):
            from apex_tpu.transformer.tensor_parallel import (
                copy_to_tensor_model_parallel_region,
            )
            y = copy_to_tensor_model_parallel_region(y)
        table = emb.variables["params"]["embedding"]
        return jnp.einsum("sbh,vh->sbv", y.astype(jnp.float32),
                          table.astype(jnp.float32))


def t5_loss_fn(logits, labels, loss_mask, axis_name: str = TENSOR_AXIS):
    """Masked mean CE over decoder tokens; vocab-parallel under TP.
    logits (s_dec, b, v[/tp]); labels/loss_mask (b, s_dec)."""
    labels_sb = labels.transpose(1, 0)
    if _inside_axis(axis_name):
        losses = vocab_parallel_cross_entropy(logits, labels_sb,
                                              axis_name=axis_name)
    else:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels_sb[..., None], -1)[..., 0]
        losses = lse - tgt
    mask_sb = loss_mask.transpose(1, 0).astype(jnp.float32)
    return jnp.sum(losses * mask_sb) / jnp.maximum(jnp.sum(mask_sb), 1.0)


def t5_param_specs(params: Any) -> Any:
    """PartitionSpec tree (same rules as gpt_param_specs, plus the
    cross-attention q/kv columns)."""

    def spec_for(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        joined = "/".join(names)
        if "embedding" in joined and names[-1] == "embedding":
            return P(TENSOR_AXIS, None)
        col = any(f"/{n}/" in f"/{joined}/" or joined.endswith(f"/{n}")
                  for n in ("qkv", "fc1", "q", "kv"))
        row = any(f"/{n}/" in f"/{joined}/" for n in ("proj", "fc2"))
        if col and names[-1] == "kernel":
            spec = P(TENSOR_AXIS, None)
        elif col and names[-1] == "bias":
            spec = P(TENSOR_AXIS)
        elif row and names[-1] == "kernel":
            spec = P(None, TENSOR_AXIS)
        else:
            return P()
        if any(n.endswith("_layers") for n in names):
            # scan_layers stacks layer params (leading layer axis)
            spec = P(None, *spec)
        return spec

    return jax.tree_util.tree_map_with_path(spec_for, params)

"""Tracing / profiling hooks (SURVEY.md §5 "Tracing / profiling").

The reference marks hot regions with NVTX ranges behind ``prof`` flags
(ref: apex/parallel/distributed.py:360-361,403-404,517-518,556-557;
examples/imagenet/main_amp.py:401 ``--prof``). The TPU equivalents:

- :func:`range` / :func:`mark_range` — ``jax.named_scope``: names the
  enclosing ops in HLO metadata so they show up in XLA/perfetto traces
  exactly where nvtx ranges would in nsight.
- :func:`start_trace` / :func:`stop_trace` / :func:`trace` —
  ``jax.profiler`` capture to a TensorBoard-loadable directory
  (replaces ``torch.cuda.profiler.start/stop`` + nsys).
- Host-side timing lives in
  :class:`apex_tpu.transformer.pipeline_parallel.Timers`, whose
  start/stop block on device work the way the reference's timers
  ``torch.cuda.synchronize()`` (ref _timers.py:6-83).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

# jax.named_scope is itself a context manager AND decorator
range = jax.named_scope  # noqa: A001 — mirrors the nvtx range concept
mark_range = jax.named_scope


def start_trace(log_dir: str = "/tmp/apex_tpu_trace") -> None:
    """Begin a profiler capture (ref: --prof windows around iterations)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/apex_tpu_trace",
          enabled: bool = True) -> Iterator[None]:
    """``with profiler.trace(...):`` capture window; ``enabled=False``
    makes it a no-op so callers can keep the reference's prof-flag
    pattern (``if args.prof and i == start_iter: ...``) inline."""
    if not enabled:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: Optional[str] = None):
    """Decorator form: name a function's ops in traces
    (ref: nvtx.range_push/pop pairs around functions)."""
    def wrap(fn):
        return jax.named_scope(name or fn.__qualname__)(fn)
    return wrap


def optimizer_step_cache_stats() -> dict:
    """Hit/miss counters of the fused train-step compile cache
    (optimizers/train_step.py): ``factory_*`` are `make_train_step`
    lookups, ``layout_*`` are distinct static FlatSpace layouts (each
    layout miss paid one XLA compile). The observability hook for the
    donation-aware step path — a training loop that keeps missing here
    is re-compiling its hot path every step."""
    from apex_tpu.optimizers.train_step import step_cache_stats

    return step_cache_stats()


# ``range`` stays importable as an attribute for nvtx-name parity, but
# is deliberately NOT in __all__: star-importing this module must not
# shadow the ``range`` builtin in user code (advisor finding, round 1).
__all__ = ["mark_range", "start_trace", "stop_trace", "trace", "annotate",
           "optimizer_step_cache_stats"]

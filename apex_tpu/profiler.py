"""Tracing / profiling hooks (SURVEY.md §5 "Tracing / profiling").

The reference marks hot regions with NVTX ranges behind ``prof`` flags
(ref: apex/parallel/distributed.py:360-361,403-404,517-518,556-557;
examples/imagenet/main_amp.py:401 ``--prof``). The TPU equivalents:

- ``profiler.range`` / :func:`mark_range` — ``jax.named_scope``: names
  the enclosing ops in HLO metadata so they show up in XLA/perfetto
  traces exactly where nvtx ranges would in nsight. (``range`` is
  served via module ``__getattr__`` for nvtx-name parity; it is never
  a module-level binding, so no code in this module — or star-import
  of it — can shadow the ``range`` builtin.)
- :func:`start_trace` / :func:`stop_trace` / :func:`trace` —
  ``jax.profiler`` capture to a TensorBoard-loadable directory
  (replaces ``torch.cuda.profiler.start/stop`` + nsys).
- :func:`annotate` — named_scope as a decorator; when the global
  telemetry timeline is enabled it ALSO records each call as a
  host-side span, so one decorator feeds both the XLA trace and the
  :class:`~apex_tpu.telemetry.StepTimeline` spine.
- Host-side step timing lives in ``apex_tpu.telemetry.timeline``
  (:class:`StepTimeline`); the legacy
  :class:`apex_tpu.transformer.pipeline_parallel.Timers` publishes
  into the same spine (see docs/observability.md).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Iterator, Optional

import jax

mark_range = jax.named_scope


def __getattr__(name: str):
    # nvtx-name parity: ``profiler.range`` works, but ``range`` never
    # exists in the module dict — intra-module code and star-imports
    # cannot pick up a shadowed builtin (advisor finding, round 1;
    # regression test: tests/test_profiler.py)
    if name == "range":
        return jax.named_scope
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def start_trace(log_dir: str = "/tmp/apex_tpu_trace") -> None:
    """Begin a profiler capture (ref: --prof windows around iterations)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/apex_tpu_trace",
          enabled: bool = True) -> Iterator[None]:
    """``with profiler.trace(...):`` capture window; ``enabled=False``
    makes it a no-op so callers can keep the reference's prof-flag
    pattern (``if args.prof and i == start_iter: ...``) inline."""
    if not enabled:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: Optional[str] = None):
    """Decorator form: name a function's ops in traces (ref:
    nvtx.range_push/pop pairs around functions) AND — when the global
    telemetry timeline is on — record each call as a host-side span,
    so `annotate`d regions appear in ``export_trace()`` output next to
    the step phases. The timeline-off path adds one boolean check."""
    def wrap(fn):
        scoped = jax.named_scope(name or fn.__qualname__)(fn)
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            from apex_tpu.telemetry import timeline as _timeline

            if not _timeline.global_enabled():
                return scoped(*args, **kwargs)
            tl = _timeline.get_timeline()
            t0 = tl.clock()
            try:
                return scoped(*args, **kwargs)
            finally:
                tl.record_span(span_name, t0, tl.clock() - t0,
                               category="annotate")
        return inner
    return wrap


def optimizer_step_cache_stats() -> dict:
    """Hit/miss counters of the fused train-step compile cache
    (optimizers/train_step.py): ``factory_*`` are `make_train_step`
    lookups, ``layout_*`` are distinct static FlatSpace layouts (each
    layout miss paid one XLA compile). The observability hook for the
    donation-aware step path — a training loop that keeps missing here
    is re-compiling its hot path every step."""
    from apex_tpu.optimizers.train_step import step_cache_stats

    return step_cache_stats()


# ``range`` stays importable as an attribute for nvtx-name parity
# (served by __getattr__ above), but is deliberately NOT in __all__:
# star-importing this module must not shadow the ``range`` builtin in
# user code (advisor finding, round 1).
__all__ = ["mark_range", "start_trace", "stop_trace", "trace", "annotate",
           "optimizer_step_cache_stats"]

"""Persistent on-chip measurement records (``bench_records/``).

Three rounds of hardware evidence were lost because the TPU tunnel was
down exactly when the driver ran ``bench.py``: every number measured in
a healthy chip window earlier in the round lived only in prose
(docs/HARDWARE_NOTES.md) and the official artifact fell back to CPU
with nothing attached. This module makes measurement persistence a
side effect of measuring:

- every tool that successfully measures on hardware calls
  :func:`write_record` — a dated, git-stamped JSON file under
  ``bench_records/`` at the repo root;
- ``bench.py`` attaches the newest matching TPU record (clearly
  labeled, with its timestamp and SHA) to any record it is forced to
  produce on a fallback backend, so a tunnel-dead artifact still
  carries the latest real-chip evidence with provenance.

The reference has no analog (its benches assume the GPU is always
there); this is infrastructure for the tunneled-TPU environment.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import time
from typing import Any, Dict, Optional

RECORDS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bench_records")


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(RECORDS_DIR), capture_output=True,
            text=True, timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — records must never break a bench
        return "unknown"


def write_record(kind: str, payload: Dict[str, Any],
                 backend: Optional[str] = None,
                 captured: bool = True) -> Optional[str]:
    """Persist one measurement under ``bench_records/``.

    ``kind`` groups records for retrieval (e.g. ``"headline"``,
    ``"attn"``, ``"smoke"``, ``"optdiag"``, ``"tune_ln"``,
    ``"resilience"``).
    ``captured=False`` marks a hand-transcribed record (evidence copied
    from session notes, not written by the measuring process itself);
    it is stored top-level so consumers cannot miss it. Returns the
    written path, or None if persistence failed (never raises — a
    failed disk write must not kill a measurement run).

    The filename stamp has 1-second resolution, so same-second writes
    collide: the name is claimed with ``O_CREAT|O_EXCL`` (an
    exists-then-open check is a TOCTOU race across processes) and
    collisions fall back to a ``time.monotonic_ns()`` disambiguator —
    strictly increasing, so ``latest_record``'s uniquifier tiebreak
    still orders same-second records by write order. The content is
    ``fsync``'d and then the records DIRECTORY is ``fsync``'d (site
    ``record_fsync``): the O_EXCL claim creates a directory entry, and
    a crash — or the preemption kill that resilience records precede —
    immediately after the write could otherwise lose the entry (and
    with it the record) even though the data hit the platter. Transient
    disk errors are absorbed by a short deadline-bounded retry
    (apex_tpu/resilience/retry.py) before giving up; a failed attempt
    unlinks its claim, so a retried attempt's disambiguator name never
    collides with a truncated ghost.
    """
    try:
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        rec = {
            "kind": kind,
            "utc": stamp,
            "git_sha": _git_sha(),
            **({"backend": backend} if backend else {}),
            "captured": bool(captured),
            "payload": payload,
        }
        base = f"{kind}_{stamp}_{rec['git_sha']}"
        body = json.dumps(rec, indent=1, sort_keys=True)

        def attempt() -> str:
            from apex_tpu.resilience import faults

            faults.check("record_write")
            os.makedirs(RECORDS_DIR, exist_ok=True)
            path = os.path.join(RECORDS_DIR, f"{base}.json")
            while True:
                try:
                    fd = os.open(path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                                 0o644)
                    break
                except FileExistsError:
                    path = os.path.join(
                        RECORDS_DIR,
                        f"{base}.{time.monotonic_ns()}.json")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(body)
                    f.flush()
                    os.fsync(f.fileno())
                # the claim is a directory entry: fsync the directory
                # too, or a crash right after this return can erase a
                # record the caller was told exists
                faults.check("record_fsync")
                dfd = os.open(RECORDS_DIR, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except BaseException:
                try:
                    os.unlink(path)      # never leave a truncated claim
                except OSError:
                    pass
                raise
            return path

        from apex_tpu.resilience.retry import retry_call

        return retry_call(attempt, retries=3, base_delay=0.02,
                          max_delay=0.25, deadline=2.0,
                          retry_on=(OSError,), site="record_write")
    except Exception:  # noqa: BLE001
        return None


def _uniquifier(name: str) -> int:
    # "kind_stamp_sha.3.json" -> 3; "kind_stamp_sha.json" -> 0.
    parts = name[:-len(".json")].rsplit(".", 1)
    return int(parts[1]) if len(parts) == 2 and parts[1].isdigit() else 0


# what follows "{kind}_" in a write_record filename: the UTC stamp.
# Used to recognize legacy records that predate the top-level ``kind``
# field without re-introducing the filename-prefix cross-match bug
# ("tune" must still not swallow "tune_ln_<stamp>..." files).
_STAMP_RE = re.compile(r"\d{8}T\d{6}Z_")


def is_transcribed(rec: Dict[str, Any]) -> bool:
    """True when a record is hand-transcribed evidence, not written by
    the measuring process itself (top-level ``captured: false`` or the
    legacy ``"tpu-transcribed"`` backend tag)."""
    return (rec.get("captured") is False
            or str(rec.get("backend", "")).endswith("-transcribed"))


def prune_records(kind: str, keep: int) -> list:
    """Keep only the newest ``keep`` records of ``kind``; returns the
    removed paths. Never raises.

    Retention for record kinds that a failure loop can write without
    bound — the flight recorder's ``flightrec`` bundles are the
    motivating case (a crash-looping process dumps one black box per
    crash; without pruning it fills the disk that the NEXT checkpoint
    needs). Ordering matches :func:`latest_record`'s recency rule
    (record ``utc``, filename uniquifier as the same-second tiebreak),
    kind-matching matches its ``kind``-field-first semantics, and
    corrupt files are left in place (``latest_record`` already names
    them via ``record_corrupt_skipped`` — deleting evidence of disk
    trouble during disk trouble helps nobody). ``keep <= 0`` prunes
    nothing (the checkpoint manager's retention convention).

    Records stamped in the CURRENT second are never pruned: deleting
    one frees its ``O_CREAT|O_EXCL`` claim name, and a same-second
    writer would re-claim it with the bare (uniquifier-0) name —
    sorting BELOW its older same-second siblings and breaking
    ``latest_record``'s write-order tiebreak. One second later the
    stamp is unreachable and the record prunable, so a crash loop is
    still bounded at ``keep`` plus the current second's writes.
    """
    removed: list = []
    if keep <= 0:
        return removed
    now_stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    try:
        names = [n for n in os.listdir(RECORDS_DIR)
                 if n.startswith(f"{kind}_") and n.endswith(".json")]
    except OSError:
        return removed
    matches = []
    for name in names:
        path = os.path.join(RECORDS_DIR, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if "kind" in rec:
            if rec["kind"] != kind:
                continue
        elif not _STAMP_RE.match(name[len(kind) + 1:]):
            continue
        matches.append((str(rec.get("utc", "")), _uniquifier(name), path))
    matches.sort()
    for utc, _, path in matches[:-keep]:
        if utc == now_stamp:
            continue
        try:
            os.unlink(path)
        except OSError:
            continue
        removed.append(path)
    return removed


def latest_record(kind: str,
                  require_backend: Optional[str] = "tpu",
                  allow_transcribed: bool = True
                  ) -> Optional[Dict[str, Any]]:
    """Newest record of ``kind``, optionally filtered to a backend.

    The kind is matched against the *loaded* record's ``kind`` field
    (never the filename, which would cross-match kinds that are
    prefixes of other kinds). Legacy records with no top-level ``kind``
    match through their filename instead — the exact ``{kind}_{stamp}``
    shape ``write_record`` produces, so prefix kinds still cannot
    cross-match. Recency comes from the record's
    ``utc`` field with the filename uniquifier as tiebreaker.
    Driver-captured records always win over transcribed ones of the
    same kind regardless of age; ``allow_transcribed=False`` excludes
    transcribed records entirely. ``require_backend="tpu"`` also admits
    the ``"tpu-transcribed"`` tag (subject to ``allow_transcribed``).
    None when there is no matching record.
    """
    try:
        # filename prefix is a cheap pre-filter only (write_record names
        # files '{kind}_...'); the authoritative match is rec['kind']
        # below, so prefix-of-another-kind files just parse and drop out
        names = [n for n in os.listdir(RECORDS_DIR)
                 if n.startswith(f"{kind}_") and n.endswith(".json")]
    except OSError:
        return None
    matches = []
    for name in names:
        try:
            with open(os.path.join(RECORDS_DIR, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            # corrupt/unreadable record files are skipped, but never
            # silently: a structured telemetry event + counter names
            # each one once per lookup (the bench-record analog of
            # latest_valid's corrupt_checkpoint record)
            try:
                from apex_tpu.telemetry import metrics as _metrics

                reg = _metrics.registry()
                reg.counter("records_corrupt_skipped",
                            "unreadable bench_records files skipped by "
                            "latest_record").inc()
                reg.event("record_corrupt_skipped", file=name,
                          kind=kind, error=f"{type(e).__name__}: {e}")
            except Exception:  # noqa: BLE001 — lookup must never fail
                pass
            continue
        if "kind" in rec:
            if rec["kind"] != kind:
                continue
        elif not _STAMP_RE.match(name[len(kind) + 1:]):
            # legacy driver-captured records lack the top-level field;
            # accept them when the filename is exactly this kind plus a
            # stamp (ADVICE round 5: they silently vanished before)
            continue
        transcribed = is_transcribed(rec)
        if transcribed and not allow_transcribed:
            continue
        if require_backend:
            accepted = {require_backend, f"{require_backend}-transcribed"}
            if rec.get("backend") not in accepted:
                continue
        matches.append((not transcribed, str(rec.get("utc", "")),
                        _uniquifier(name), rec))
    if not matches:
        return None
    return max(matches, key=lambda t: t[:3])[3]


__all__ = ["write_record", "latest_record", "prune_records",
           "is_transcribed", "RECORDS_DIR"]

"""Persistent on-chip measurement records (``bench_records/``).

Three rounds of hardware evidence were lost because the TPU tunnel was
down exactly when the driver ran ``bench.py``: every number measured in
a healthy chip window earlier in the round lived only in prose
(docs/HARDWARE_NOTES.md) and the official artifact fell back to CPU
with nothing attached. This module makes measurement persistence a
side effect of measuring:

- every tool that successfully measures on hardware calls
  :func:`write_record` — a dated, git-stamped JSON file under
  ``bench_records/`` at the repo root;
- ``bench.py`` attaches the newest matching TPU record (clearly
  labeled, with its timestamp and SHA) to any record it is forced to
  produce on a fallback backend, so a tunnel-dead artifact still
  carries the latest real-chip evidence with provenance.

The reference has no analog (its benches assume the GPU is always
there); this is infrastructure for the tunneled-TPU environment.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Dict, Optional

RECORDS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bench_records")


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(RECORDS_DIR), capture_output=True,
            text=True, timeout=10).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — records must never break a bench
        return "unknown"


def write_record(kind: str, payload: Dict[str, Any],
                 backend: Optional[str] = None) -> Optional[str]:
    """Persist one measurement under ``bench_records/``.

    ``kind`` groups records for retrieval (e.g. ``"headline"``,
    ``"attn"``, ``"smoke"``, ``"optdiag"``, ``"tune_ln"``). Returns the
    written path, or None if persistence failed (never raises — a
    failed disk write must not kill a measurement run).
    """
    try:
        os.makedirs(RECORDS_DIR, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        rec = {
            "kind": kind,
            "utc": stamp,
            "git_sha": _git_sha(),
            **({"backend": backend} if backend else {}),
            "payload": payload,
        }
        base = f"{kind}_{stamp}_{rec['git_sha']}"
        path = os.path.join(RECORDS_DIR, f"{base}.json")
        n = 1
        while os.path.exists(path):      # same kind+second+sha: uniquify
            path = os.path.join(RECORDS_DIR, f"{base}.{n}.json")
            n += 1
        with open(path, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        return path
    except Exception:  # noqa: BLE001
        return None


def latest_record(kind: str,
                  require_backend: Optional[str] = "tpu"
                  ) -> Optional[Dict[str, Any]]:
    """Newest record of ``kind`` (by filename timestamp), optionally
    filtered to a backend. None when there is no matching record."""
    try:
        names = sorted(
            n for n in os.listdir(RECORDS_DIR)
            if n.startswith(f"{kind}_") and n.endswith(".json"))
    except OSError:
        return None
    for name in reversed(names):
        try:
            with open(os.path.join(RECORDS_DIR, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if require_backend and rec.get("backend") not in (require_backend,):
            continue
        return rec
    return None


__all__ = ["write_record", "latest_record", "RECORDS_DIR"]

"""Fused dense layers (ref: apex/fused_dense/fused_dense.py:6-111).

The reference fuses GEMM+bias and GEMM+bias+GELU+GEMM via cublasLt
epilogues (csrc/fused_dense_cuda.cu). On TPU, XLA fuses bias and GELU
into the matmul epilogue natively, so the *functional* forms below are
the fused implementation — they exist to pin the op boundary (single
dot_general with fp32 accumulation, bf16-friendly) and to give the
reference's API surface.
"""

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def fused_dense_function(x, weight, bias=None):
    """y = x @ W^T + b (ref fused_dense.py FusedDenseFunc). Weight is
    (out, in) like the reference's torch layout."""
    y = jax.lax.dot_general(
        x, weight,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2):
    """linear -> gelu -> linear in one fused region
    (ref fused_dense.py FusedDenseGeluDenseFunc)."""
    h = fused_dense_function(x, weight1, bias1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense_function(h, weight2, bias2)


class FusedDense(nn.Module):
    """Linear with fused bias epilogue (ref: apex.fused_dense.FusedDense)."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "kernel", self.kernel_init, (self.features, x.shape[-1]),
            self.param_dtype,
        )
        b = (
            self.param("bias", nn.initializers.zeros, (self.features,),
                       self.param_dtype)
            if self.use_bias
            else None
        )
        dtype = self.dtype or x.dtype
        return fused_dense_function(
            x.astype(dtype), w.astype(dtype),
            b.astype(dtype) if b is not None else None,
        )


class FusedDenseGeluDense(nn.Module):
    """linear+gelu+linear block (ref: apex.fused_dense.FusedDenseGeluDense)."""

    intermediate_features: int
    out_features: int
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        w1 = self.param("kernel1", self.kernel_init,
                        (self.intermediate_features, d_in), self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("kernel2", self.kernel_init,
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
        dtype = self.dtype or x.dtype
        return fused_dense_gelu_dense_function(
            x.astype(dtype), w1.astype(dtype), b1.astype(dtype),
            w2.astype(dtype), b2.astype(dtype),
        )


__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]

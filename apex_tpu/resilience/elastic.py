"""Elastic resharding: resume quorum checkpoints on a different host
count and mesh shape.

The base quorum protocol (resilience/checkpoint.py multi-host mode)
writes one FULL replicated copy of the train state per host — correct,
but rigid: every byte is written ``n_processes`` times, and the commit
is only consumable by worlds that can read full copies. TorchTitan's
pattern (PAPERS.md) is the robustness primitive a preemptible fleet
actually needs: checkpoints written as *logically-indexed shards* that
the restore path re-partitions onto whatever mesh is alive. This
module is that layer, over the existing quorum machinery:

- **save** — :class:`ElasticCheckpointManager` slices every big train
  buffer (flat fp32 master + each optimizer slot, the same flat
  parameter space the segmented slot maps index) into per-host
  *logical element ranges* (``partition_ranges`` — contiguous,
  alignment-multiple, deterministic), so each host writes ``1/N`` of
  the state under the unchanged tmp→fsync→rename + verify-before-
  commit discipline. The coordinator's ``COMMIT.json`` gains a
  ``layout`` manifest: saved world size, per-host ranges, the leaf
  tree signature, and the state's bitwise per-leaf fingerprint
  (``guard.state_fingerprint`` — the segmented per-leaf checksums).
  Hosts whose save-time fingerprints disagree abort the commit:
  replicas that already diverged must never become a checkpoint.
- **restore** — :class:`ElasticRestorePlanner` maps the committed
  ranges onto the CURRENT world (any N±k): the new world re-partitions
  ``[0, total)`` into ``M`` read assignments, each new host performs
  the minimal set of (shard, slice) disk reads for ITS assignment, and
  the missing ranges travel over the PR-3 ``Collective``
  (``KVStoreCollective`` on CPU clusters, ``ProcessCollective`` on
  real fleets, ``LocalCollective`` in the threaded sim) — hosts that
  hold a range serve it to hosts that need it. The reassembled state
  is verified BITWISE against the layout manifest's per-leaf
  fingerprint before training resumes; a mismatch raises
  :class:`ElasticRestoreError` and dumps a flight-recorder bundle
  (trigger ``elastic_restore_error``) carrying the layout, the
  computed plan, and per-range fetch/verify status.
- **compat** — a pre-elastic ``COMMIT.json`` (no layout manifest)
  restores through the legacy full-copy path unchanged, and a legacy
  manager scanning past an elastic commit reports it as a structured
  ``elastic_candidate`` instead of "no checkpoint found"
  (checkpoint.py ``latest_valid``).

Fault sites (resilience/faults.py): ``shard_truncate=<steps>`` rots
one committed shard after the commit lands, ``world_mismatch=<steps>``
records an inconsistent layout the planner must detect, and
``range_fetch_timeout=<idx>`` times out peer fetches so the planner's
disk fallback is drillable. The end-to-end drill is
``tools/elastic_drill.py`` (save on 2 ``jax.distributed`` processes,
SIGTERM, resume on 1 and on 3 — bitwise vs an uninterrupted run),
orchestrated by ``tools/check_resilience.sh``; the single-process
``LocalCollective`` simulation lives in tests/test_elastic.py.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.checkpoint import (
    PAYLOAD,
    CheckpointError,
    CheckpointManager,
    _np_dtype,
    host_dirname,
)

ELASTIC_FORMAT = 1


class ElasticLayoutError(CheckpointError):
    """A commit's layout manifest is inconsistent (claimed world vs
    committed ranges, ranges that do not tile the flat space) — the
    checkpoint cannot be planned onto ANY world."""


class ElasticRestoreError(CheckpointError):
    """An elastic restore failed after planning: a range could not be
    read/fetched, or the reassembled state's bitwise fingerprint does
    not match the layout manifest."""


class ElasticRestoredState(NamedTuple):
    """:meth:`ElasticCheckpointManager.restore`'s return value — the
    base ``RestoredState`` fields plus the verified fingerprint (the
    guard's post-restore baseline, see
    ``ConsistencyGuard.verify_restore``) and the executed plan."""

    step: int
    opt_state: Any
    scaler_state: Any
    rng_state: Any
    extra: Any
    fingerprint: Any        # (n_buffers, num_leaves) uint32, verified
    plan: Any               # dict: what this host read/fetched


def partition_ranges(total: int, n_hosts: int,
                     align: int) -> List[Tuple[int, int]]:
    """Deterministically partition ``[0, total)`` into ``n_hosts``
    contiguous element ranges, every boundary a multiple of ``align``
    (so no range splits a lane tile). Trailing hosts may get empty
    ranges when there are fewer alignment units than hosts — legal:
    they write an empty shard and fetch everything on restore."""
    total, n_hosts, align = int(total), int(n_hosts), int(align)
    if n_hosts < 1:
        raise ValueError(f"n_hosts must be >= 1, got {n_hosts}")
    if align < 1 or total % align:
        raise ValueError(
            f"total {total} must be a positive multiple of the "
            f"alignment {align}")
    units = total // align
    base, rem = divmod(units, n_hosts)
    out: List[Tuple[int, int]] = []
    lo = 0
    for h in range(n_hosts):
        hi = lo + (base + (1 if h < rem else 0)) * align
        out.append((lo, hi))
        lo = hi
    return out


def space_signature(space) -> str:
    """sha256 of a ``FlatSpace``'s complete static layout. Two spaces
    sign equal iff element ``i`` means the same (leaf, position) in
    both — the precondition for range-indexed shards to be
    reassembled under a template from a different process."""
    blob = json.dumps({
        "shapes": [list(s) for s in space.shapes],
        "dtypes": [str(d) for d in space.dtypes],
        "offsets": list(space.offsets),
        "sizes": list(space.sizes),
        "padded": list(space.padded_sizes),
        "total": int(space.total),
        "align": int(space.align),
    }, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


class ElasticRestorePlanner:
    """Map a committed elastic layout onto the CURRENT world.

    Validates the layout manifest (claimed world matches the committed
    ranges; the ranges tile ``[0, total)`` exactly — the
    ``world_mismatch`` fault clause forges exactly this inconsistency),
    re-partitions the flat space into ``n_new`` read assignments with
    the same deterministic :func:`partition_ranges`, and answers, for
    any span, the minimal set of (saved shard, slice) reads that
    cover it.
    """

    def __init__(self, layout: Dict[str, Any], n_new: int):
        if not isinstance(layout, dict) \
                or layout.get("format") != ELASTIC_FORMAT:
            raise ElasticLayoutError(
                f"unsupported elastic layout format "
                f"{None if not isinstance(layout, dict) else layout.get('format')!r}")
        self.layout = layout
        self.total = int(layout["total"])
        self.align = int(layout["align"])
        self.n_saved = int(layout.get("world", -1))
        ranges = layout.get("ranges") or {}
        if self.n_saved != len(ranges):
            raise ElasticLayoutError(
                f"layout claims world {self.n_saved} but commits "
                f"{len(ranges)} ranges — the manifest is inconsistent "
                "(corrupt commit, or the world_mismatch drill)")
        saved = sorted(((h, int(lo), int(hi))
                        for h, (lo, hi) in ranges.items()),
                       key=lambda t: (t[1], t[2], t[0]))
        cur = 0
        for h, lo, hi in saved:
            if lo != cur or hi < lo:
                raise ElasticLayoutError(
                    f"committed ranges do not tile [0, {self.total}): "
                    f"shard {h} covers [{lo}, {hi}) but {cur} is the "
                    "next uncovered element")
            cur = hi
        if cur != self.total:
            raise ElasticLayoutError(
                f"committed ranges cover [0, {cur}) of "
                f"[0, {self.total})")
        self.saved: List[Tuple[str, int, int]] = saved
        self.n_new = int(n_new)
        self.assignments = partition_ranges(self.total, self.n_new,
                                            self.align)

    def reads_for_span(self, lo: int,
                       hi: int) -> List[Tuple[str, int, int, int]]:
        """``[(shard_dirname, shard_lo, read_lo, read_hi)]`` covering
        ``[lo, hi)`` — ``shard_lo`` is the shard's own range start, so
        ``read_lo - shard_lo`` is the element offset into its
        payload."""
        out = []
        for h, slo, shi in self.saved:
            a, b = max(lo, slo), min(hi, shi)
            if b > a:
                out.append((h, slo, a, b))
        if sum(b - a for _, _, a, b in out) != hi - lo:
            raise ElasticLayoutError(
                f"span [{lo}, {hi}) is not covered by the committed "
                "ranges")
        return out

    def reads_for(self, new_host: int) -> List[Tuple[str, int, int, int]]:
        lo, hi = self.assignments[int(new_host)]
        if hi <= lo:
            return []
        return self.reads_for_span(lo, hi)

    def describe(self, me: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready summary (what the flight bundle carries)."""
        out = {
            "saved_world": self.n_saved,
            "new_world": self.n_new,
            "total": self.total,
            "align": self.align,
            "saved_ranges": [[h, lo, hi] for h, lo, hi in self.saved],
            "assignments": [[lo, hi] for lo, hi in self.assignments],
        }
        if me is not None:
            out["replica_id"] = int(me)
            out["reads"] = [[h, slo, a, b]
                            for h, slo, a, b in self.reads_for(me)]
        return out


class ElasticCheckpointManager(CheckpointManager):
    """Quorum checkpoints written as logically-indexed range shards.

    Same constructor, same atomic/quorum discipline, same
    ``latest_valid`` scan as :class:`CheckpointManager` — but in
    multi-host mode each host's payload holds only ITS element range
    of every big buffer, the commit manifest carries the layout, and
    :meth:`restore` reassembles the full state on ANY world size::

        mgr = ElasticCheckpointManager(dir, process_id=col.replica_id,
                                       n_processes=col.n_replicas)
        mgr.save(step, state)                    # every host, as before
        ...                                      # later, any world:
        restored = mgr.restore(template=opt.init(params),
                               collective=col)   # fetches missing ranges
        guard.verify_restore(restored.opt_state,
                             baseline=restored.fingerprint)

    Single-host managers (``n_processes=1``) write the plain legacy
    layout; legacy quorum commits (no layout manifest) restore through
    the inherited full-copy path — both directions of backward compat
    are pinned in tests/test_quorum_checkpoint.py.
    """

    def __init__(self, directory: str, **kwargs):
        if kwargs.get("compress_master"):
            raise ValueError(
                "elastic checkpoints are bitwise by contract "
                "(fingerprint-verified reassembly); compress_master is "
                "unsupported")
        super().__init__(directory, **kwargs)

    # elastic commits are first-class here (the base class skips them)
    def _layout_usable(self, commit: Dict[str, Any]) -> Tuple[bool, str]:
        return True, ""

    # -- save --------------------------------------------------------------

    def _snapshot(self, opt_state):
        if not self.multihost:
            return super()._snapshot(opt_state)
        from apex_tpu.resilience.guard import state_fingerprint

        space = opt_state.space
        lo, hi = partition_ranges(space.total, self.n_processes,
                                  space.align)[self.process_id]
        fp = state_fingerprint(opt_state)
        master = np.asarray(opt_state.master)
        names, arrays = ["master"], [master[lo:hi]]
        buffers = [{"name": "master", "dtype": str(master.dtype)}]
        for k in sorted(opt_state.slots):
            arr = np.asarray(opt_state.slots[k])
            names.append(f"slot:{k}")
            arrays.append(arr[lo:hi])
            buffers.append({"name": f"slot:{k}", "dtype": str(arr.dtype)})
        names += ["count", "found_inf"]
        arrays += [np.asarray(opt_state.count),
                   np.asarray(opt_state.found_inf)]
        meta = {
            "master_compressed": False,
            "master_dtype": str(master.dtype),
            "elastic": {
                "format": ELASTIC_FORMAT,
                "range": [int(lo), int(hi)],
                "total": int(space.total),
                "align": int(space.align),
                "num_leaves": int(space.num_leaves),
                "tree_sig": space_signature(space),
                "buffers": buffers,
                "fingerprint": np.asarray(fp.sums, np.uint32).tolist(),
                "count": int(opt_state.count),
                "found_inf": float(opt_state.found_inf),
            },
        }
        return names, arrays, meta

    def _commit_extra(self, step: int, final: str,
                      shas: Dict[str, str]) -> Dict[str, Any]:
        """The layout manifest, assembled from every verified shard's
        own elastic metadata — with a cross-host consistency gate: a
        save where replicas' fingerprints already disagree is a
        divergence, not a checkpoint, and must never commit."""
        ranges: Dict[str, Any] = {}
        ref: Optional[Dict[str, Any]] = None
        ref_host = None
        for h in sorted(shas):
            el = self.read_manifest(os.path.join(final, h)).get("elastic")
            if el is None:
                raise CheckpointError(
                    f"quorum commit aborted: host shard {h} carries no "
                    "elastic metadata — mixed elastic/legacy savers in "
                    "one world")
            ranges[h] = [int(el["range"][0]), int(el["range"][1])]
            if ref is None:
                ref, ref_host = el, h
                continue
            if el["tree_sig"] != ref["tree_sig"]:
                raise CheckpointError(
                    f"quorum commit aborted: host {h} saved a different "
                    f"parameter tree than {ref_host} (tree_sig differs)")
            if el["fingerprint"] != ref["fingerprint"]:
                raise CheckpointError(
                    f"quorum commit aborted: host {h}'s save-time state "
                    f"fingerprint disagrees with {ref_host}'s — replicas "
                    "diverged before the save; refusing to commit "
                    "corrupted state")
        world = len(ranges)
        if faults.should_world_mismatch(step):
            # forge the inconsistency the restore planner must detect
            world += 1
        return {"layout": {
            "format": ELASTIC_FORMAT,
            "world": world,
            "total": int(ref["total"]),
            "align": int(ref["align"]),
            "num_leaves": int(ref["num_leaves"]),
            "tree_sig": ref["tree_sig"],
            "buffers": ref["buffers"],
            "ranges": ranges,
            "fingerprint": ref["fingerprint"],
            "count": int(ref["count"]),
            "found_inf": float(ref["found_inf"]),
        }}

    def _commit_quorum(self, step: int, final: str) -> None:
        super()._commit_quorum(step, final)
        tgt = faults.shard_truncate_target(step)
        if tgt is not None:
            # committed-but-rotten drill: chop one shard AFTER the
            # commit landed, so validate()/restore must catch it
            ppath = os.path.join(final, host_dirname(int(tgt)), PAYLOAD)
            try:
                size = os.path.getsize(ppath)
            except OSError:
                return
            with open(ppath, "r+b") as f:
                f.truncate(max(1, size // 2))

    # -- restore -----------------------------------------------------------

    def restore(self, path: Optional[str] = None, *, template,
                host: Optional[int] = None, collective=None):
        """Reassemble the full train state onto THIS world.

        Legacy layouts (single-host dirs, quorum commits without a
        layout manifest) go through the inherited full-copy path.
        Elastic commits are planned onto ``collective.n_replicas``
        hosts (1 when no collective is given — every range read from
        disk, the shared-filesystem mode); each host disk-reads its
        assignment and the rest arrives over the collective. All hosts
        of the current world must call this together (the fetch is a
        collective). Returns :class:`ElasticRestoredState`.
        """
        t0 = time.perf_counter()
        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self.directory}")
        if not self._is_multihost_layout(path):
            return super().restore(path, template=template, host=host)
        try:
            commit = self.read_commit(path)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"{path}: no commit manifest: {type(e).__name__}")
        if commit.get("layout") is None:
            # pre-elastic quorum bundle: the legacy full-copy path
            return super().restore(path, template=template, host=host)
        return self._restore_elastic(path, commit, template, collective,
                                     t0)

    def _restore_elastic(self, path, commit, template, collective, t0):
        from apex_tpu.telemetry import comms as _comms

        layout = commit["layout"]
        # range fetches are the fattest payloads any collective in the
        # repo moves — route them through the comms plane (identity
        # when it is disabled, so the raw collective stays raw)
        collective = _comms.instrument(collective)
        n_new = collective.n_replicas if collective is not None else 1
        me = collective.replica_id if collective is not None else 0
        planner = None
        status: List[Dict[str, Any]] = []
        try:
            ok, reason = self.validate(path)
            if not ok:
                raise ElasticRestoreError(f"{path}: {reason}")
            planner = ElasticRestorePlanner(layout, n_new)
            sig = space_signature(template.space)
            if sig != layout.get("tree_sig"):
                raise CheckpointError(
                    f"{path}: checkpoint was written against a different "
                    "parameter tree (layout signature differs from the "
                    "template's)")
            names = (["master"]
                     + [f"slot:{k}" for k in sorted(template.slots)])
            if [b["name"] for b in layout["buffers"]] != names:
                raise CheckpointError(
                    f"{path}: checkpoint buffers "
                    f"{[b['name'] for b in layout['buffers']]} do not "
                    f"match the template's {names} — written by a "
                    "different optimizer")
            dtypes = [_np_dtype(b["dtype"]) for b in layout["buffers"]]
            opt_state, fetched, remapped = self._reassemble(
                path, planner, me, names, dtypes, layout, template,
                collective, status)
            sums = self._verify_fingerprint(opt_state, layout, template,
                                            status)
            first = sorted(commit["hosts"])[0]
            man0 = self.read_manifest(os.path.join(path, first))
        except BaseException as e:
            self._restore_failed(e, path, layout, planner, me, status)
            raise
        from apex_tpu.resilience.checkpoint import _decode_rng, \
            _decode_scaler
        seconds = time.perf_counter() - t0
        self._publish_elastic(seconds, planner, fetched, remapped,
                              int(man0["step"]))
        out = ElasticRestoredState(
            step=int(man0["step"]),
            opt_state=opt_state,
            scaler_state=_decode_scaler(man0.get("scaler")),
            rng_state=_decode_rng(man0.get("rng")),
            extra=man0.get("extra"),
            fingerprint=sums,
            plan={**planner.describe(me), "ranges": status},
        )
        from apex_tpu.resilience.checkpoint import _goodput_restored

        _goodput_restored(out)
        return out

    def _reassemble(self, path, planner, me, names, dtypes, layout,
                    template, collective, status):
        """Disk-read this host's assignment, exchange ranges over the
        collective, and rebuild the full ``FlatOptState``."""
        import jax.numpy as jnp

        from apex_tpu.optimizers.fused import FlatOptState

        total = planner.total
        full = [np.empty((total,), dt) for dt in dtypes]
        hspaces: Dict[str, Any] = {}

        def read_span(lo, hi):
            """Per-buffer bytes for global span [lo, hi), as uint8."""
            from apex_tpu.runtime import HostFlatSpace

            parts: List[List[np.ndarray]] = [[] for _ in names]
            for hostname, slo, a, b in planner.reads_for_span(lo, hi):
                if hostname not in hspaces:
                    man = self.read_manifest(os.path.join(path, hostname))
                    entries = man["arrays"]
                    hspaces[hostname] = (
                        HostFlatSpace(
                            [tuple(e["shape"]) for e in entries],
                            [_np_dtype(e["dtype"]) for e in entries],
                            align=man["align"]),
                        {e["name"]: i for i, e in enumerate(entries)})
                hs, index = hspaces[hostname]
                ppath = os.path.join(path, hostname, PAYLOAD)
                with open(ppath, "rb") as f:
                    for j, (name, dt) in enumerate(zip(names, dtypes)):
                        f.seek(hs.offsets[index[name]]
                               + (a - slo) * dt.itemsize)
                        nb = (b - a) * dt.itemsize
                        data = f.read(nb)
                        if len(data) != nb:
                            raise ElasticRestoreError(
                                f"{ppath}: short read of {name} "
                                f"[{a}, {b}) — shard truncated after "
                                "commit")
                        parts[j].append(np.frombuffer(data, np.uint8))
            return [np.concatenate(p) if len(p) != 1 else p[0]
                    for p in parts]

        my_lo, my_hi = planner.assignments[me]
        mine = None
        if my_hi > my_lo:
            mine = read_span(my_lo, my_hi)
            status.append({"range": [my_lo, my_hi], "source": "disk",
                           "status": "ok"})
        fetch_idx = 0
        fetched = 0
        remapped = 0
        for r, (lo, hi) in enumerate(planner.assignments):
            if hi <= lo:
                continue
            if r == me:
                got = mine
            else:
                # receivers pass same-shaped placeholders (the plan is
                # deterministic, so every host knows every shape); the
                # exchange travels as raw bytes, dtype-agnostic
                got = [np.zeros(((hi - lo) * dt.itemsize,), np.uint8)
                       for dt in dtypes]
            if planner.n_new > 1:
                got = collective.broadcast_from(r, got)
            if r != me:
                timed_out = faults.should_range_timeout(fetch_idx)
                fetch_idx += 1
                if timed_out:
                    # peer did not serve the range in time: fall back
                    # to the committed shards on disk (shared storage)
                    got = read_span(lo, hi)
                    status.append({"range": [lo, hi],
                                   "source": "disk_fallback",
                                   "status": "range_fetch_timeout"})
                    self._count("elastic_range_fetch_timeouts",
                                "elastic range fetches that timed out "
                                "and fell back to disk")
                else:
                    fetched += 1
                    status.append({"range": [lo, hi],
                                   "source": f"peer_{r}",
                                   "status": "ok"})
            for j, dt in enumerate(dtypes):
                full[j][lo:hi] = np.frombuffer(
                    np.ascontiguousarray(got[j]), dt)
                remapped += int(got[j].nbytes)

        master = full[0]
        slots = {k: jnp.asarray(full[1 + i])
                 for i, k in enumerate(sorted(template.slots))}
        opt_state = FlatOptState(
            space=template.space,
            master=jnp.asarray(master),
            slots=slots,
            count=jnp.asarray(int(layout["count"]), jnp.int32),
            found_inf=jnp.asarray(float(layout["found_inf"]),
                                  jnp.float32),
            seg_meta=template.seg_meta,
        )
        return opt_state, fetched, remapped

    def _verify_fingerprint(self, opt_state, layout, template, status):
        """Bitwise per-leaf verification of the reassembled state
        against the layout manifest — the guard's own checksum, so a
        passing restore IS a valid fingerprint baseline."""
        from apex_tpu.resilience.guard import state_fingerprint
        from apex_tpu.resilience.watchdog import leaf_names

        sums = np.asarray(state_fingerprint(opt_state).sums, np.uint32)
        want = np.asarray(layout["fingerprint"], np.uint32)
        if sums.shape != want.shape or not np.array_equal(sums, want):
            bad = []
            if sums.shape == want.shape:
                nm = leaf_names(template.space)
                for b, leaf in zip(*np.nonzero(sums != want)):
                    bad.append(f"buffer {int(b)} leaf {nm[int(leaf)]}")
            status.append({"verify": "fingerprint_mismatch",
                           "sites": bad[:16]})
            raise ElasticRestoreError(
                "reassembled state does not match the layout "
                "manifest's bitwise fingerprint "
                f"({len(bad) or 'shape'} mismatching sites: "
                f"{bad[:4] or sums.shape}) — a range was corrupted or "
                "mis-mapped; refusing to resume on this state")
        status.append({"verify": "fingerprint_match"})
        return sums

    # -- reporting ---------------------------------------------------------

    @staticmethod
    def _count(name: str, help_: str, n: float = 1.0, **labels) -> None:
        try:
            from apex_tpu.telemetry import metrics as _metrics

            _metrics.registry().counter(name, help_).inc(n, **labels)
        except Exception:  # noqa: BLE001 — telemetry never breaks restore
            pass

    def _publish_elastic(self, seconds, planner, fetched, remapped,
                         step) -> None:
        from apex_tpu.resilience.checkpoint import _publish_io

        _publish_io("restore", time.perf_counter() - seconds, seconds,
                    mode="elastic")
        try:
            from apex_tpu.telemetry import metrics as _metrics

            reg = _metrics.registry()
            reg.histogram(
                "elastic_restore_ms",
                "wall milliseconds per elastic restore").observe(
                seconds * 1000.0, new_world=str(planner.n_new),
                saved_world=str(planner.n_saved))
            reg.counter(
                "elastic_ranges_fetched",
                "ranges fetched from peers during elastic "
                "restores").inc(fetched)
            reg.counter(
                "elastic_bytes_remapped",
                "bytes remapped onto the new world during elastic "
                "restores").inc(remapped)
            reg.event("elastic_restore", step=step,
                      saved_world=planner.n_saved,
                      new_world=planner.n_new, ranges_fetched=fetched,
                      bytes_remapped=remapped,
                      ms=round(seconds * 1000.0, 3))
        except Exception:  # noqa: BLE001
            pass

    def _restore_failed(self, err, path, layout, planner, me,
                        status) -> None:
        """Every failed elastic restore leaves a flight bundle behind:
        the layout manifest, the computed plan, and per-range
        fetch/verify status — the postmortem an operator reads before
        retrying on yet another world."""
        self._count("elastic_restore_errors", "failed elastic restores")
        try:
            from apex_tpu.telemetry import metrics as _metrics

            _metrics.registry().event(
                "elastic_restore_error", path=path,
                error=f"{type(err).__name__}: {err}")
        except Exception:  # noqa: BLE001
            pass
        from apex_tpu.telemetry import flight as _flight

        _flight.notify(
            "elastic_restore_error", error=err, fleet=False,
            extra={
                "path": path,
                "layout": layout,
                "plan": (planner.describe(me)
                         if planner is not None else None),
                "ranges": status,
            })


__all__ = [
    "ELASTIC_FORMAT",
    "ElasticCheckpointManager",
    "ElasticLayoutError",
    "ElasticRestoreError",
    "ElasticRestoredState",
    "ElasticRestorePlanner",
    "partition_ranges",
    "space_signature",
]

"""Native atomic checkpointing of the full train state.

The reference's README prescribes "save model + optimizer + amp state,
restore all three, continue bitwise" — in this repo that recipe lived
only as an orbax-based test. This module makes it a runtime subsystem
with no external dependency, built on the flat host buffers in
``apex_tpu.runtime``:

- **payload**: every train-state array (flat fp32 master, optimizer
  slot buffers, step counters) is flattened into ONE aligned host
  buffer via ``HostFlatSpace`` (thread-pooled memcpys, one disk write
  instead of dozens), with an optional bf16-compressed master
  (``cast_f32_bf16`` / ``cast_bf16_f32`` — halves the payload, costs
  bitwise resume, so it is opt-in).
- **atomicity**: write into a temp directory, ``fsync`` payload +
  manifest + directory, then ``os.rename`` into place. A crash at any
  point leaves either the previous checkpoints untouched or a stale
  ``*.tmp-*`` directory that no reader ever considers.
- **manifest**: ``manifest.json`` records the array layout (names,
  shapes, dtypes), a sha256 of the payload, the step, the serialized
  ``ScalerState``, host RNG state, and caller extras. ``validate``
  re-hashes the payload against it, so truncation/corruption anywhere
  is detected before a single byte is deserialized.
- **retention**: ``keep``-last-k; older checkpoints are pruned after
  each successful finalize (never before).
- **overlap**: ``async_save=True`` fetches arrays to host
  synchronously (safe with the donation-aware train step — the device
  buffers may be reused the moment ``save`` returns) and runs the
  flatten + disk I/O on a background thread; ``wait()`` joins and
  re-raises any failure.
- **recovery**: ``latest_valid()`` scans newest -> oldest, skips
  truncated/corrupt checkpoints (emitting a structured ``resilience``
  record per corrupt one), and returns the newest that verifies.

Fault-injection hooks (apex_tpu/resilience/faults.py): the disk write
checks the ``checkpoint_write`` site, and a finalized checkpoint is
truncated in place when the active plan says so — which is exactly the
corruption ``latest_valid`` must survive.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.retry import retry_call

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
PAYLOAD = "payload.bin"
_STEP_RE = re.compile(r"^step_(\d{12})$")


class CheckpointError(RuntimeError):
    """Unusable checkpoint (missing, corrupt, or layout-mismatched)."""


class RestoredState(NamedTuple):
    """What :meth:`CheckpointManager.restore` hands back."""

    step: int
    opt_state: Any                 # FlatOptState over the template's layout
    scaler_state: Any              # ScalerState or None
    rng_state: Any                 # whatever was passed to save, or None
    extra: Any                     # caller extras, or None


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _encode_rng(rng_state: Any) -> Any:
    """JSON-encode host RNG state. Supports ``np.random.RandomState``
    (and its ``get_state()`` tuple) plus anything already
    JSON-serializable — never pickle, so a checkpoint can't smuggle
    code."""
    if rng_state is None:
        return None
    if isinstance(rng_state, np.random.RandomState):
        rng_state = rng_state.get_state()
    if (isinstance(rng_state, tuple) and len(rng_state) == 5
            and rng_state[0] == "MT19937"):
        name, keys, pos, has_gauss, cached = rng_state
        return {"kind": "numpy_legacy", "name": name,
                "keys": np.asarray(keys, np.uint32).tolist(),
                "pos": int(pos), "has_gauss": int(has_gauss),
                "cached_gaussian": float(cached)}
    json.dumps(rng_state)          # raises TypeError if not serializable
    return {"kind": "json", "value": rng_state}


def _decode_rng(enc: Any) -> Any:
    if enc is None:
        return None
    if enc.get("kind") == "numpy_legacy":
        state = (enc["name"], np.asarray(enc["keys"], np.uint32),
                 enc["pos"], enc["has_gauss"], enc["cached_gaussian"])
        rng = np.random.RandomState()
        rng.set_state(state)
        return rng
    return enc.get("value")


def _encode_scaler(scaler_state: Any) -> Optional[Dict[str, float]]:
    if scaler_state is None:
        return None
    return {"loss_scale": float(scaler_state.loss_scale),
            "unskipped": int(scaler_state.unskipped),
            "found_inf": float(scaler_state.found_inf)}


def _decode_scaler(enc: Optional[Dict[str, float]]):
    if enc is None:
        return None
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import ScalerState

    return ScalerState(
        loss_scale=jnp.asarray(enc["loss_scale"], jnp.float32),
        unskipped=jnp.asarray(enc["unskipped"], jnp.int32),
        found_inf=jnp.asarray(enc.get("found_inf", 0.0), jnp.float32))


class CheckpointManager:
    """Atomic, self-validating, keep-last-k checkpoints of a fused
    train state (``FlatOptState`` + ``ScalerState`` + step + host RNG).

    ::

        mgr = CheckpointManager(dir, keep=3)
        mgr.save(step, state, scaler_state=sstate, rng_state=rng)
        ...
        path = mgr.latest_valid()
        restored = mgr.restore(path, template=opt.init(params))
        state, sstate = restored.opt_state, restored.scaler_state
        # resume the loop at restored.step — trajectory is bitwise
        # identical to the uninterrupted run (tests/test_resilience.py)
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 compress_master: bool = False, async_save: bool = False,
                 fsync: bool = True):
        self.directory = str(directory)
        self.keep = int(keep)
        self.compress_master = bool(compress_master)
        self.async_save = bool(async_save)
        self.fsync = bool(fsync)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._reported_corrupt: set = set()
        os.makedirs(self.directory, exist_ok=True)
        # stale temp dirs from a previous crashed process: no reader
        # considers them, but they hold disk — sweep at startup
        for name in os.listdir(self.directory):
            if ".tmp-" in name:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- naming ------------------------------------------------------------

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):012d}")

    def all_steps(self) -> List[int]:
        """Recorded checkpoint steps, oldest -> newest (no validation)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save --------------------------------------------------------------

    def save(self, step: int, opt_state, *, scaler_state=None,
             rng_state=None, extra=None) -> str:
        """Checkpoint the train state; returns the (final) path.

        Arrays are fetched to HOST memory before this returns — with
        ``async_save`` only the flatten + disk I/O runs on the
        background thread, so the caller may immediately feed
        ``opt_state`` back into a donating train step.
        """
        self.wait()                      # one in-flight save, surface errors
        names, arrays, meta = self._snapshot(opt_state)
        manifest_extra = {
            "scaler": _encode_scaler(scaler_state),
            "rng": _encode_rng(rng_state),
            "extra": extra,
            **meta,
        }
        if extra is not None:
            json.dumps(extra)            # fail fast, not on the save thread
        final = self.path_for(step)
        if not self.async_save:
            self._write(int(step), final, names, arrays, manifest_extra)
            return final

        def run():
            try:
                self._write(int(step), final, names, arrays, manifest_extra)
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._error = e

        self._thread = threading.Thread(
            target=run, name=f"ckpt-save-{int(step)}", daemon=True)
        self._thread.start()
        return final

    def wait(self) -> None:
        """Join any in-flight async save; re-raise its failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def _snapshot(self, opt_state) -> Tuple[List[str], List[np.ndarray],
                                            Dict[str, Any]]:
        """Device -> host fetch of every train-state array, in a fixed
        name order (master, sorted slots, count, found_inf)."""
        from apex_tpu.runtime import cast_f32_bf16

        master = np.asarray(opt_state.master)
        meta: Dict[str, Any] = {"master_compressed": False,
                                "master_dtype": str(master.dtype)}
        if self.compress_master and master.dtype == np.float32:
            master = np.asarray(cast_f32_bf16(master))
            meta["master_compressed"] = True
        names, arrays = ["master"], [master]
        for k in sorted(opt_state.slots):
            names.append(f"slot:{k}")
            arrays.append(np.asarray(opt_state.slots[k]))
        names += ["count", "found_inf"]
        arrays += [np.asarray(opt_state.count),
                   np.asarray(opt_state.found_inf)]
        return names, arrays, meta

    def _write(self, step: int, final: str, names, arrays, manifest_extra):
        from apex_tpu.runtime import HostFlatSpace

        space = HostFlatSpace.for_arrays(arrays)
        buf = space.flatten(arrays)
        manifest = {
            "format": FORMAT_VERSION,
            "step": step,
            "utc": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
            "align": space.align,
            "payload_bytes": int(space.total_bytes),
            "sha256": hashlib.sha256(buf).hexdigest(),
            "arrays": [
                {"name": n, "shape": list(s), "dtype": str(d)}
                for n, s, d in zip(names, space.shapes, space.dtypes)
            ],
            **manifest_extra,
        }
        # transient disk errors (incl. injected FaultError) are retried
        # under a deadline; a permanently dead disk surfaces as the
        # original OSError
        retry_call(self._write_once, final, buf, manifest,
                   retries=3, base_delay=0.05, max_delay=0.5, deadline=5.0,
                   retry_on=(OSError,))
        if faults.should_truncate(step):
            # simulated on-disk corruption of the FINALIZED checkpoint
            # (what latest_valid must skip): chop the payload in half
            with open(os.path.join(final, PAYLOAD), "r+b") as f:
                f.truncate(max(1, space.total_bytes // 2))
        self._prune()

    def _write_once(self, final: str, buf: np.ndarray,
                    manifest: Dict[str, Any]) -> None:
        faults.check("checkpoint_write")
        tmp = f"{final}.tmp-{os.getpid()}-{time.monotonic_ns()}"
        os.makedirs(tmp)
        try:
            with open(os.path.join(tmp, PAYLOAD), "wb") as f:
                f.write(memoryview(buf))
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            if self.fsync:
                self._fsync_dir(tmp)
            if os.path.exists(final):
                # re-checkpoint of the same step: replace (brief window
                # with neither; older checkpoints stay untouched)
                shutil.rmtree(final)
            os.rename(tmp, final)
            if self.fsync:
                self._fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _prune(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self.path_for(s), ignore_errors=True)

    # -- validation / recovery ---------------------------------------------

    def validate(self, path: str) -> Tuple[bool, str]:
        """(ok, reason). Re-hashes the payload against the manifest, so
        truncation or bit-rot anywhere in the payload is caught."""
        mpath = os.path.join(path, MANIFEST)
        ppath = os.path.join(path, PAYLOAD)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"manifest unreadable: {type(e).__name__}"
        if manifest.get("format") != FORMAT_VERSION:
            return False, f"unsupported format {manifest.get('format')!r}"
        try:
            size = os.path.getsize(ppath)
        except OSError:
            return False, "payload missing"
        if size != manifest.get("payload_bytes"):
            return False, (f"payload truncated: {size} bytes, manifest "
                           f"says {manifest.get('payload_bytes')}")
        h = hashlib.sha256()
        try:
            with open(ppath, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        except OSError as e:
            return False, f"payload unreadable: {type(e).__name__}"
        if h.hexdigest() != manifest.get("sha256"):
            return False, "sha256 mismatch"
        return True, ""

    def latest_valid(self, *, record_events: bool = True) -> Optional[str]:
        """Newest checkpoint that passes :meth:`validate`, scanning
        newest -> oldest. Each corrupt checkpoint found on the way is
        reported once per process as a structured ``resilience`` record
        (event ``corrupt_checkpoint``) and skipped."""
        for step in reversed(self.all_steps()):
            path = self.path_for(step)
            ok, reason = self.validate(path)
            if ok:
                return path
            if record_events and path not in self._reported_corrupt:
                self._reported_corrupt.add(path)
                from apex_tpu import records

                records.write_record("resilience", {
                    "event": "corrupt_checkpoint",
                    "path": path,
                    "step": step,
                    "reason": reason,
                })
        return None

    def read_manifest(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, MANIFEST)) as f:
            return json.load(f)

    def restore(self, path: Optional[str] = None, *,
                template) -> RestoredState:
        """Load a checkpoint into the layout of ``template`` (a
        ``FlatOptState`` from ``opt.init(params)`` — its static
        ``space``/``seg_meta`` nodes are reused, so a restored state is
        immediately compatible with the compiled train step).

        ``path=None`` restores from :meth:`latest_valid`. Raises
        :class:`CheckpointError` when nothing valid exists or the
        checkpoint's layout does not match the template.
        """
        import jax.numpy as jnp

        from apex_tpu.runtime import HostFlatSpace, cast_bf16_f32

        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self.directory}")
        ok, reason = self.validate(path)
        if not ok:
            raise CheckpointError(f"{path}: {reason}")
        manifest = self.read_manifest(path)
        entries = manifest["arrays"]
        space = HostFlatSpace(
            [tuple(e["shape"]) for e in entries],
            [_np_dtype(e["dtype"]) for e in entries],
            align=manifest["align"])
        buf = np.fromfile(os.path.join(path, PAYLOAD), np.uint8)
        host = dict(zip((e["name"] for e in entries),
                        space.unflatten(buf)))

        master = host["master"]
        if manifest.get("master_compressed"):
            master = cast_bf16_f32(master).astype(
                _np_dtype(manifest["master_dtype"]))
        if master.size != template.space.total:
            raise CheckpointError(
                f"{path}: master has {master.size} elements, template "
                f"layout needs {template.space.total} — checkpoint was "
                "written against a different parameter tree")
        slots = {}
        for k in template.slots:
            key = f"slot:{k}"
            if key not in host:
                raise CheckpointError(
                    f"{path}: missing optimizer slot {k!r} — checkpoint "
                    "was written by a different optimizer")
            slots[k] = jnp.asarray(host[key])

        from apex_tpu.optimizers.fused import FlatOptState

        opt_state = FlatOptState(
            space=template.space,
            master=jnp.asarray(master),
            slots=slots,
            count=jnp.asarray(host["count"], jnp.int32),
            found_inf=jnp.asarray(host["found_inf"], jnp.float32),
            seg_meta=template.seg_meta,
        )
        return RestoredState(
            step=int(manifest["step"]),
            opt_state=opt_state,
            scaler_state=_decode_scaler(manifest.get("scaler")),
            rng_state=_decode_rng(manifest.get("rng")),
            extra=manifest.get("extra"),
        )


__all__ = ["CheckpointError", "CheckpointManager", "RestoredState",
           "FORMAT_VERSION", "MANIFEST", "PAYLOAD"]

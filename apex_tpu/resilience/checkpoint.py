"""Native atomic checkpointing of the full train state.

The reference's README prescribes "save model + optimizer + amp state,
restore all three, continue bitwise" — in this repo that recipe lived
only as an orbax-based test. This module makes it a runtime subsystem
with no external dependency, built on the flat host buffers in
``apex_tpu.runtime``:

- **payload**: every train-state array (flat fp32 master, optimizer
  slot buffers, step counters) is flattened into ONE aligned host
  buffer via ``HostFlatSpace`` (thread-pooled memcpys, one disk write
  instead of dozens), with an optional bf16-compressed master
  (``cast_f32_bf16`` / ``cast_bf16_f32`` — halves the payload, costs
  bitwise resume, so it is opt-in).
- **atomicity**: write into a temp directory, ``fsync`` payload +
  manifest + directory, then ``os.rename`` into place. A crash at any
  point leaves either the previous checkpoints untouched or a stale
  ``*.tmp-*`` directory that no reader ever considers.
- **manifest**: ``manifest.json`` records the array layout (names,
  shapes, dtypes), a sha256 of the payload, the step, the serialized
  ``ScalerState``, host RNG state, and caller extras. ``validate``
  re-hashes the payload against it, so truncation/corruption anywhere
  is detected before a single byte is deserialized.
- **retention**: ``keep``-last-k; older checkpoints are pruned after
  each successful finalize (never before).
- **overlap**: ``async_save=True`` fetches arrays to host
  synchronously (safe with the donation-aware train step — the device
  buffers may be reused the moment ``save`` returns) and runs the
  flatten + disk I/O on a background thread; ``wait()`` joins and
  re-raises any failure.
- **recovery**: ``latest_valid()`` scans newest -> oldest, skips
  truncated/corrupt checkpoints (emitting a structured ``resilience``
  record per corrupt one), and returns the newest that verifies.

Fault-injection hooks (apex_tpu/resilience/faults.py): the disk write
checks the ``checkpoint_write`` site, and a finalized checkpoint is
truncated in place when the active plan says so — which is exactly the
corruption ``latest_valid`` must survive.

Multi-host (quorum) mode — ``n_processes > 1``: every host writes its
OWN shard ``step_X/host_{pid:04d}/{payload.bin,manifest.json}`` with
the same tmp→fsync→rename protocol, and the coordinator (process 0)
records ``COMMIT.json`` — the quorum manifest naming every host shard
and its sha256 — only after ALL hosts' shards are present and verify.
A checkpoint without a commit manifest (a host died mid-save, the
coordinator was preempted before commit) is never valid, no matter how
many intact shards it holds: ``latest_valid()`` demands the complete
host-set, so resume can never mix step-N state on some hosts with
step-M on others. ``restore()`` prefers this process's own shard but
accepts ANY committed host's copy — data-parallel-replicated state is
bit-identical across hosts, so a slice that restarts with fewer
processes (or as a single process) still resumes. The
``crash_before_commit`` fault site (faults.py) kills a host between
its shard write and the commit, which is exactly the partial host-set
``latest_valid`` must refuse.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.retry import retry_call

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
PAYLOAD = "payload.bin"
COMMIT = "COMMIT.json"
_STEP_RE = re.compile(r"^step_(\d{12})$")
_HOST_RE = re.compile(r"^host_(\d{4})$")


def _publish_io(kind: str, t0: float, seconds: float, **labels) -> None:
    """Checkpoint save/restore latency into the telemetry registry
    (histogram + counter) and, when the global timeline is on, a
    ``checkpoint`` span in the step timeline. Never raises."""
    try:
        from apex_tpu.telemetry import metrics as _metrics
        from apex_tpu.telemetry import timeline as _timeline

        reg = _metrics.registry()
        reg.counter(f"checkpoint_{kind}s",
                    f"checkpoint {kind} operations").inc(**labels)
        reg.histogram(f"checkpoint_{kind}_seconds",
                      f"wall seconds per checkpoint {kind}").observe(
            seconds, **labels)
        # kind rides the span args so the goodput ledger can route
        # save vs restore into distinct buckets
        _timeline.record_global_span("checkpoint", t0, seconds,
                                     args={"kind": kind})
    except Exception:  # noqa: BLE001 — telemetry must never break a save
        pass


def _goodput_extra(extra, step):
    """Fold the armed goodput ledger's cumulative state into a save's
    ``extra`` payload; identity when disarmed. Never raises."""
    try:
        from apex_tpu.telemetry import goodput as _goodput

        return _goodput.merge_into_extra(extra, step=int(step))
    except Exception:  # noqa: BLE001 — telemetry must never break a save
        return extra


def _goodput_restored(out) -> None:
    """Feed a restored checkpoint's ``extra`` back into the armed
    goodput ledger (restart survival + rework window). Never raises."""
    try:
        from apex_tpu.telemetry import goodput as _goodput

        _goodput.note_restored(getattr(out, "extra", None),
                               restored_step=getattr(out, "step", None))
    except Exception:  # noqa: BLE001 — telemetry must never break a restore
        pass


def host_dirname(process_id: int) -> str:
    return f"host_{int(process_id):04d}"


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory entry (the rename is not
    durable until its parent directory is)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_files(final: str, files: Dict[str, bytes], *,
                       fsync: bool = True) -> None:
    """The checkpoint write discipline as a reusable primitive: stage
    ``files`` (name -> bytes) into ``<final>.tmp-<pid>-<ns>``, fsync
    each file and the temp dir, then ``os.rename`` the directory into
    place (replacing any previous ``final``) and fsync the parent. A
    crash at any point leaves either the previous ``final`` untouched
    or a stale ``*.tmp-*`` directory no reader ever considers — the
    serving drain snapshot (serving/resilience.py) commits through
    here."""
    tmp = f"{final}.tmp-{os.getpid()}-{time.monotonic_ns()}"
    os.makedirs(tmp)
    try:
        for name, data in files.items():
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(data)
                f.flush()
                if fsync:
                    os.fsync(f.fileno())
        if fsync:
            fsync_dir(tmp)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        if fsync:
            fsync_dir(os.path.dirname(final) or ".")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


class CheckpointError(RuntimeError):
    """Unusable checkpoint (missing, corrupt, or layout-mismatched)."""


class RestoredState(NamedTuple):
    """What :meth:`CheckpointManager.restore` hands back."""

    step: int
    opt_state: Any                 # FlatOptState over the template's layout
    scaler_state: Any              # ScalerState or None
    rng_state: Any                 # whatever was passed to save, or None
    extra: Any                     # caller extras, or None


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _encode_rng(rng_state: Any) -> Any:
    """JSON-encode host RNG state. Supports ``np.random.RandomState``
    (and its ``get_state()`` tuple) plus anything already
    JSON-serializable — never pickle, so a checkpoint can't smuggle
    code."""
    if rng_state is None:
        return None
    if isinstance(rng_state, np.random.RandomState):
        rng_state = rng_state.get_state()
    if (isinstance(rng_state, tuple) and len(rng_state) == 5
            and rng_state[0] == "MT19937"):
        name, keys, pos, has_gauss, cached = rng_state
        return {"kind": "numpy_legacy", "name": name,
                "keys": np.asarray(keys, np.uint32).tolist(),
                "pos": int(pos), "has_gauss": int(has_gauss),
                "cached_gaussian": float(cached)}
    json.dumps(rng_state)          # raises TypeError if not serializable
    return {"kind": "json", "value": rng_state}


def _decode_rng(enc: Any) -> Any:
    if enc is None:
        return None
    if enc.get("kind") == "numpy_legacy":
        state = (enc["name"], np.asarray(enc["keys"], np.uint32),
                 enc["pos"], enc["has_gauss"], enc["cached_gaussian"])
        rng = np.random.RandomState()
        rng.set_state(state)
        return rng
    return enc.get("value")


def _encode_scaler(scaler_state: Any) -> Optional[Dict[str, float]]:
    if scaler_state is None:
        return None
    return {"loss_scale": float(scaler_state.loss_scale),
            "unskipped": int(scaler_state.unskipped),
            "found_inf": float(scaler_state.found_inf)}


def _decode_scaler(enc: Optional[Dict[str, float]]):
    if enc is None:
        return None
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import ScalerState

    return ScalerState(
        loss_scale=jnp.asarray(enc["loss_scale"], jnp.float32),
        unskipped=jnp.asarray(enc["unskipped"], jnp.int32),
        found_inf=jnp.asarray(enc.get("found_inf", 0.0), jnp.float32))


class CheckpointManager:
    """Atomic, self-validating, keep-last-k checkpoints of a fused
    train state (``FlatOptState`` + ``ScalerState`` + step + host RNG).

    ::

        mgr = CheckpointManager(dir, keep=3)
        mgr.save(step, state, scaler_state=sstate, rng_state=rng)
        ...
        path = mgr.latest_valid()
        restored = mgr.restore(path, template=opt.init(params))
        state, sstate = restored.opt_state, restored.scaler_state
        # resume the loop at restored.step — trajectory is bitwise
        # identical to the uninterrupted run (tests/test_resilience.py)
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 compress_master: bool = False, async_save: bool = False,
                 fsync: bool = True, process_id: int = 0,
                 n_processes: int = 1, quorum_timeout: float = 120.0):
        self.directory = str(directory)
        self.keep = int(keep)
        self.compress_master = bool(compress_master)
        self.async_save = bool(async_save)
        self.fsync = bool(fsync)
        self.process_id = int(process_id)
        self.n_processes = int(n_processes)
        self.quorum_timeout = float(quorum_timeout)
        if not (0 <= self.process_id < max(self.n_processes, 1)):
            raise ValueError(
                f"process_id {self.process_id} out of range for "
                f"n_processes {self.n_processes}")
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._reported_corrupt: set = set()
        os.makedirs(self.directory, exist_ok=True)
        # stale temp dirs from a previous crashed process: no reader
        # considers them, but they hold disk — sweep at startup (one
        # level into step dirs too, where multi-host shard tmps live)
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if ".tmp-" in name:
                shutil.rmtree(path, ignore_errors=True)
            elif _STEP_RE.match(name) and os.path.isdir(path):
                for sub in os.listdir(path):
                    if ".tmp-" in sub:
                        shutil.rmtree(os.path.join(path, sub),
                                      ignore_errors=True)

    @property
    def multihost(self) -> bool:
        return self.n_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    # -- naming ------------------------------------------------------------

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):012d}")

    def all_steps(self) -> List[int]:
        """Recorded checkpoint steps, oldest -> newest (no validation)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save --------------------------------------------------------------

    def save(self, step: int, opt_state, *, scaler_state=None,
             rng_state=None, extra=None) -> str:
        """Checkpoint the train state; returns the (final) path.

        Arrays are fetched to HOST memory before this returns — with
        ``async_save`` only the flatten + disk I/O runs on the
        background thread, so the caller may immediately feed
        ``opt_state`` back into a donating train step.
        """
        self.wait()                      # one in-flight save, surface errors
        # when the goodput ledger is armed its cumulative state rides
        # the manifest extra (tmp→fsync→rename like everything else),
        # so a killed-and-resumed run reports run-level goodput
        extra = _goodput_extra(extra, step)
        names, arrays, meta = self._snapshot(opt_state)
        manifest_extra = {
            "scaler": _encode_scaler(scaler_state),
            "rng": _encode_rng(rng_state),
            "extra": extra,
            **meta,
        }
        if self.multihost:
            manifest_extra["process_id"] = self.process_id
            manifest_extra["n_processes"] = self.n_processes
        if extra is not None:
            json.dumps(extra)            # fail fast, not on the save thread
        final = self.path_for(step)
        if not self.async_save:
            t0 = time.perf_counter()
            self._write(int(step), final, names, arrays, manifest_extra)
            _publish_io("save", t0, time.perf_counter() - t0, mode="sync")
            return final

        def run():
            t0 = time.perf_counter()
            try:
                self._write(int(step), final, names, arrays, manifest_extra)
                _publish_io("save", t0, time.perf_counter() - t0,
                            mode="async")
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._error = e

        self._thread = threading.Thread(
            target=run, name=f"ckpt-save-{int(step)}", daemon=True)
        self._thread.start()
        return final

    def wait(self) -> None:
        """Join any in-flight async save; re-raise its failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def _snapshot(self, opt_state) -> Tuple[List[str], List[np.ndarray],
                                            Dict[str, Any]]:
        """Device -> host fetch of every train-state array, in a fixed
        name order (master, sorted slots, count, found_inf)."""
        from apex_tpu.runtime import cast_f32_bf16

        master = np.asarray(opt_state.master)
        meta: Dict[str, Any] = {"master_compressed": False,
                                "master_dtype": str(master.dtype)}
        if self.compress_master and master.dtype == np.float32:
            master = np.asarray(cast_f32_bf16(master))
            meta["master_compressed"] = True
        names, arrays = ["master"], [master]
        for k in sorted(opt_state.slots):
            names.append(f"slot:{k}")
            arrays.append(np.asarray(opt_state.slots[k]))
        names += ["count", "found_inf"]
        arrays += [np.asarray(opt_state.count),
                   np.asarray(opt_state.found_inf)]
        return names, arrays, meta

    def _write(self, step: int, final: str, names, arrays, manifest_extra):
        from apex_tpu.runtime import HostFlatSpace

        space = HostFlatSpace.for_arrays(arrays)
        buf = space.flatten(arrays)
        manifest = {
            "format": FORMAT_VERSION,
            "step": step,
            "utc": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
            "align": space.align,
            "payload_bytes": int(space.total_bytes),
            "sha256": hashlib.sha256(buf).hexdigest(),
            "arrays": [
                {"name": n, "shape": list(s), "dtype": str(d)}
                for n, s, d in zip(names, space.shapes, space.dtypes)
            ],
            **manifest_extra,
        }
        target = final
        if self.multihost:
            os.makedirs(final, exist_ok=True)
            target = os.path.join(final, host_dirname(self.process_id))
            # a host dying here (step dir claimed, shard not yet
            # landed) leaves a partial host-set: the coordinator MUST
            # time out and refuse the commit, and latest_valid() must
            # keep answering the previous quorum checkpoint (fault
            # site: crash_before_commit — the two-process drill in
            # tools/check_resilience.sh)
            faults.maybe_crash_before_commit(step)
        # transient disk errors (incl. injected FaultError) are retried
        # under a deadline; a permanently dead disk surfaces as the
        # original OSError
        retry_call(self._write_once, target, buf, manifest,
                   retries=3, base_delay=0.05, max_delay=0.5, deadline=5.0,
                   retry_on=(OSError,), site="checkpoint_write")
        if faults.should_truncate(step):
            # simulated on-disk corruption of the FINALIZED checkpoint
            # (what latest_valid must skip): chop the payload in half
            with open(os.path.join(target, PAYLOAD), "r+b") as f:
                f.truncate(max(1, space.total_bytes // 2))
        if self.multihost:
            if not self.is_coordinator:
                return
            self._commit_quorum(step, final)
        self._prune()

    # -- quorum commit (multi-host) ----------------------------------------

    def _commit_quorum(self, step: int, final: str) -> None:
        """Coordinator: wait for every host's shard to land and verify,
        then atomically record the commit manifest. No COMMIT.json ->
        the whole step is invisible to every reader, forever."""
        deadline = time.monotonic() + self.quorum_timeout
        hosts = [host_dirname(h) for h in range(self.n_processes)]
        pending = set(hosts)
        shas: Dict[str, str] = {}
        while pending:
            for h in sorted(pending):
                hp = os.path.join(final, h)
                if not os.path.exists(os.path.join(hp, MANIFEST)):
                    continue
                ok, reason = self._validate_leaf(hp)
                if not ok:
                    raise CheckpointError(
                        f"quorum commit aborted: host shard {hp} is "
                        f"invalid ({reason})")
                shas[h] = self.read_manifest(hp)["sha256"]
                pending.discard(h)
            if not pending:
                break
            if time.monotonic() >= deadline:
                raise CheckpointError(
                    f"quorum timeout after {self.quorum_timeout:.0f}s: "
                    f"missing host shards {sorted(pending)} under {final} "
                    "— no commit recorded; the previous quorum "
                    "checkpoint remains the newest valid one")
            time.sleep(0.05)
        commit = {
            "format": FORMAT_VERSION,
            "step": int(step),
            "n_hosts": self.n_processes,
            "utc": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
            "hosts": shas,
        }
        commit.update(self._commit_extra(step, final, shas))
        retry_call(self._write_commit_once, final, commit,
                   retries=3, base_delay=0.05, max_delay=0.5, deadline=5.0,
                   retry_on=(OSError,), site="checkpoint_commit")

    def _commit_extra(self, step: int, final: str,
                      shas: Dict[str, str]) -> Dict[str, Any]:
        """Extra coordinator-side fields merged into ``COMMIT.json``
        after every shard verified. The elastic manager
        (resilience/elastic.py) overrides this to record the layout
        manifest; the base quorum protocol adds nothing."""
        return {}

    def _write_commit_once(self, final: str, commit: Dict[str, Any]) -> None:
        faults.check("quorum_commit")
        tmp = os.path.join(
            final, f"{COMMIT}.tmp-{os.getpid()}-{time.monotonic_ns()}")
        try:
            with open(tmp, "w") as f:
                json.dump(commit, f, indent=1, sort_keys=True)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, os.path.join(final, COMMIT))
            if self.fsync:
                self._fsync_dir(final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def read_commit(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, COMMIT)) as f:
            return json.load(f)

    def _write_once(self, final: str, buf: np.ndarray,
                    manifest: Dict[str, Any]) -> None:
        faults.check("checkpoint_write")
        stall = faults.ckpt_stall_s()
        if stall:
            # goodput drill: slow checkpoint storage — inside the
            # timed save, so the stall lands in checkpoint_save
            time.sleep(stall)
        tmp = f"{final}.tmp-{os.getpid()}-{time.monotonic_ns()}"
        os.makedirs(tmp)
        try:
            with open(os.path.join(tmp, PAYLOAD), "wb") as f:
                f.write(memoryview(buf))
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            if self.fsync:
                self._fsync_dir(tmp)
            if os.path.exists(final):
                # re-checkpoint of the same step: replace (brief window
                # with neither; older checkpoints stay untouched)
                shutil.rmtree(final)
            os.rename(tmp, final)
            if self.fsync:
                self._fsync_dir(os.path.dirname(final) or ".")
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    # module-level fsync_dir, kept as a method for existing callers
    _fsync_dir = staticmethod(fsync_dir)

    def _prune(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self.path_for(s), ignore_errors=True)

    # -- validation / recovery ---------------------------------------------

    @staticmethod
    def _is_multihost_layout(path: str) -> bool:
        """A step dir holding host shards (or a commit manifest) uses
        the quorum layout — decided from DISK, not from this manager's
        ``n_processes``, so a shrunken/single-process slice still
        recognizes (and restores from) a multi-host checkpoint."""
        if os.path.exists(os.path.join(path, COMMIT)):
            return True
        try:
            return any(_HOST_RE.match(n) for n in os.listdir(path))
        except OSError:
            return False

    def validate(self, path: str) -> Tuple[bool, str]:
        """(ok, reason). Single-host checkpoints re-hash the payload
        against the manifest. Quorum checkpoints additionally demand
        the commit manifest and the COMPLETE host-set it names, each
        shard re-hashed and matched against the commit's recorded
        sha256 — a partial host-set (host died before commit) or a
        swapped shard is never valid."""
        if self._is_multihost_layout(path):
            return self._validate_quorum(path)
        return self._validate_leaf(path)

    def _validate_quorum(self, path: str) -> Tuple[bool, str]:
        try:
            commit = self.read_commit(path)
        except (OSError, ValueError) as e:
            return False, ("no commit manifest (host died before commit, "
                           f"or coordinator crashed): {type(e).__name__}")
        if commit.get("format") != FORMAT_VERSION:
            return False, f"unsupported commit format {commit.get('format')!r}"
        hosts = commit.get("hosts") or {}
        if len(hosts) != commit.get("n_hosts"):
            return False, (f"commit names {len(hosts)} hosts, expected "
                           f"{commit.get('n_hosts')}")
        for h, sha in sorted(hosts.items()):
            hp = os.path.join(path, h)
            ok, reason = self._validate_leaf(hp)
            if not ok:
                return False, f"host shard {h}: {reason}"
            if self.read_manifest(hp).get("sha256") != sha:
                return False, f"host shard {h}: sha256 differs from commit"
        return True, ""

    def _validate_leaf(self, path: str) -> Tuple[bool, str]:
        mpath = os.path.join(path, MANIFEST)
        ppath = os.path.join(path, PAYLOAD)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"manifest unreadable: {type(e).__name__}"
        if manifest.get("format") != FORMAT_VERSION:
            return False, f"unsupported format {manifest.get('format')!r}"
        try:
            size = os.path.getsize(ppath)
        except OSError:
            return False, "payload missing"
        if size != manifest.get("payload_bytes"):
            return False, (f"payload truncated: {size} bytes, manifest "
                           f"says {manifest.get('payload_bytes')}")
        h = hashlib.sha256()
        try:
            with open(ppath, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
        except OSError as e:
            return False, f"payload unreadable: {type(e).__name__}"
        if h.hexdigest() != manifest.get("sha256"):
            return False, "sha256 mismatch"
        return True, ""

    def _layout_usable(self, commit: Dict[str, Any]) -> Tuple[bool, str]:
        """Whether THIS manager's restore path can consume a validated
        quorum checkpoint with this commit manifest. The base manager
        restores replicated full-copy shards only; an elastic commit
        (range-sharded payloads, resilience/elastic.py) verifies fine
        but cannot be reassembled here."""
        layout = commit.get("layout")
        if layout is not None:
            return False, (
                f"elastic layout (saved world {layout.get('world')}, "
                f"{len(layout.get('ranges') or {})} ranges) — "
                "range-sharded payloads need "
                "resilience.elastic.ElasticCheckpointManager to "
                "reassemble")
        return True, ""

    def _report_elastic_candidate(self, path: str, step: int,
                                  commit: Dict[str, Any],
                                  reason: str) -> None:
        """A checkpoint that VERIFIES but this manager cannot restore
        (an elastic layout under a legacy manager) is resumable, not
        corrupt — name it, so the operator sees a
        resumable-but-mismatched candidate instead of "no checkpoint
        found"."""
        if path in self._reported_corrupt:
            return
        self._reported_corrupt.add(path)
        layout = commit.get("layout") or {}
        from apex_tpu import records
        from apex_tpu.telemetry import metrics as _metrics

        records.write_record("resilience", {
            "event": "elastic_candidate",
            "path": path,
            "step": step,
            "reason": reason,
            "layout": {"world": layout.get("world"),
                       "total": layout.get("total"),
                       "ranges": layout.get("ranges")},
        })
        reg = _metrics.registry()
        reg.counter("checkpoint_elastic_candidates",
                    "valid-but-unrestorable elastic checkpoints seen by "
                    "a legacy latest_valid scan").inc()
        reg.event("elastic_candidate", path=path, step=step,
                  world=layout.get("world"))

    def latest_valid(self, *, record_events: bool = True) -> Optional[str]:
        """Newest checkpoint that passes :meth:`validate` AND this
        manager can restore, scanning newest -> oldest. Each corrupt
        checkpoint found on the way is reported once per process as a
        structured ``resilience`` record (event ``corrupt_checkpoint``)
        and skipped; a checkpoint that verifies but needs the elastic
        restore path (and this manager lacks it) is reported as an
        ``elastic_candidate`` — resumable elsewhere, skipped here."""
        for step in reversed(self.all_steps()):
            path = self.path_for(step)
            ok, reason = self.validate(path)
            if ok:
                if self._is_multihost_layout(path):
                    try:
                        commit = self.read_commit(path)
                    except (OSError, ValueError):
                        commit = {}
                    usable, why = self._layout_usable(commit)
                    if not usable:
                        if record_events:
                            self._report_elastic_candidate(
                                path, step, commit, why)
                        continue
                return path
            if record_events and path not in self._reported_corrupt:
                self._reported_corrupt.add(path)
                from apex_tpu import records
                from apex_tpu.telemetry import metrics as _metrics

                records.write_record("resilience", {
                    "event": "corrupt_checkpoint",
                    "path": path,
                    "step": step,
                    "reason": reason,
                })
                reg = _metrics.registry()
                reg.counter("checkpoint_corrupt_skipped",
                            "corrupt checkpoints skipped by "
                            "latest_valid").inc()
                reg.event("corrupt_checkpoint", path=path, step=step,
                          reason=reason)
        return None

    def read_manifest(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, MANIFEST)) as f:
            return json.load(f)

    def restore(self, path: Optional[str] = None, *,
                template, host: Optional[int] = None) -> RestoredState:
        """Load a checkpoint into the layout of ``template`` (a
        ``FlatOptState`` from ``opt.init(params)`` — its static
        ``space``/``seg_meta`` nodes are reused, so a restored state is
        immediately compatible with the compiled train step).

        ``path=None`` restores from :meth:`latest_valid`. Raises
        :class:`CheckpointError` when nothing valid exists or the
        checkpoint's layout does not match the template.

        On a quorum (multi-host) checkpoint, this process's own shard
        is preferred, falling back to any committed host's copy — the
        state is data-parallel replicated, so every shard is the same
        bits and a slice resuming with FEWER processes (or one) still
        restores. ``host`` pins a specific shard instead.
        """
        t0 = time.perf_counter()
        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self.directory}")
        ok, reason = self.validate(path)
        if not ok:
            raise CheckpointError(f"{path}: {reason}")
        if self._is_multihost_layout(path):
            commit = self.read_commit(path)
            usable, why = self._layout_usable(commit)
            if not usable:
                raise CheckpointError(f"{path}: {why}")
            named = sorted(commit.get("hosts") or {})
            if host is not None:
                order = [host_dirname(host)]
                if order[0] not in named:
                    raise CheckpointError(
                        f"{path}: host shard {order[0]} not in the commit "
                        f"manifest (hosts: {named})")
            else:
                own = host_dirname(self.process_id)
                order = ([own] + [h for h in named if h != own]
                         if own in named else named)
            # validate() already verified every shard; any one works
            out = self._restore_leaf(os.path.join(path, order[0]),
                                     template)
        else:
            out = self._restore_leaf(path, template)
        _publish_io("restore", t0, time.perf_counter() - t0)
        _goodput_restored(out)
        return out

    def _restore_leaf(self, path: str, template) -> RestoredState:
        import jax.numpy as jnp

        from apex_tpu.runtime import HostFlatSpace, cast_bf16_f32

        manifest = self.read_manifest(path)
        entries = manifest["arrays"]
        space = HostFlatSpace(
            [tuple(e["shape"]) for e in entries],
            [_np_dtype(e["dtype"]) for e in entries],
            align=manifest["align"])
        buf = np.fromfile(os.path.join(path, PAYLOAD), np.uint8)
        host = dict(zip((e["name"] for e in entries),
                        space.unflatten(buf)))

        master = host["master"]
        if manifest.get("master_compressed"):
            master = cast_bf16_f32(master).astype(
                _np_dtype(manifest["master_dtype"]))
        if master.size != template.space.total:
            raise CheckpointError(
                f"{path}: master has {master.size} elements, template "
                f"layout needs {template.space.total} — checkpoint was "
                "written against a different parameter tree")
        slots = {}
        for k in template.slots:
            key = f"slot:{k}"
            if key not in host:
                raise CheckpointError(
                    f"{path}: missing optimizer slot {k!r} — checkpoint "
                    "was written by a different optimizer")
            slots[k] = jnp.asarray(host[key])

        from apex_tpu.optimizers.fused import FlatOptState

        opt_state = FlatOptState(
            space=template.space,
            master=jnp.asarray(master),
            slots=slots,
            count=jnp.asarray(host["count"], jnp.int32),
            found_inf=jnp.asarray(host["found_inf"], jnp.float32),
            seg_meta=template.seg_meta,
        )
        return RestoredState(
            step=int(manifest["step"]),
            opt_state=opt_state,
            scaler_state=_decode_scaler(manifest.get("scaler")),
            rng_state=_decode_rng(manifest.get("rng")),
            extra=manifest.get("extra"),
        )


__all__ = ["CheckpointError", "CheckpointManager", "RestoredState",
           "FORMAT_VERSION", "MANIFEST", "PAYLOAD", "COMMIT",
           "atomic_write_files", "fsync_dir", "host_dirname"]

"""Nonfinite-gradient watchdog with segment localization and rollback.

The amp contract already *skips* overflowed steps (the fused train
step gates the update on ``found_inf`` and the scaler halves), which
is the right response to the occasional fp16/bf16 overflow. It is the
WRONG response to persistent NaNs (poisoned batch, diverged layer,
bad-math kernel): the scaler halves every step until it pins at
``min_loss_scale`` and the run spins forever, burning a chip while
updating nothing. :class:`NonfiniteWatchdog` is the escalation ladder
on top of the skip:

1. **count** — consecutive skipped steps, reset by any good step.
2. **localize** (past ``threshold``) — name WHICH parameters produced
   nonfinite gradients. When the inner step already reports per-tensor
   grad norms (``with_grad_norm=True`` rides the segmented kernel's
   phase-0 one-hot accumulators at zero extra passes), the names come
   straight from the step's aux; otherwise one cold-path reduction over
   the flat gradient runs through the same per-segment slot machinery
   (``multi_tensor.segmented.segmented_per_leaf_sumsq``).
3. **report** — a structured ``resilience`` record via
   ``records.write_record`` (event ``nonfinite_escalation``) carrying
   the suspects, scale trajectory, and the action taken.
4. **roll back** — restore the last valid checkpoint with a
   RE-INITIALIZED loss scale (not the ground-down one — a rolled-back
   run at ``min_loss_scale`` would immediately re-skip everything),
   or, with no checkpoint manager attached, reset just the scaler.
5. **give up loudly** — more than ``max_rollbacks`` escalations raises
   :class:`RollbackLimitExceeded` with the suspects attached, instead
   of looping a rollback<->NaN cycle forever.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np


class RollbackLimitExceeded(RuntimeError):
    """The watchdog escalated more than ``max_rollbacks`` times — the
    nonfinite source survives rollback (deterministically poisoned
    data or a genuine divergence) and needs a human."""

    def __init__(self, msg: str, suspects=None):
        super().__init__(msg)
        self.suspects = suspects or []


class RollbackUnavailable(RollbackLimitExceeded):
    """Escalation demanded a rollback but the attached manager has NO
    valid checkpoint (cold start: empty or absent directory). Raised
    immediately — looping scaler resets against a persistent NaN
    source and then reporting "survived N rollbacks" would blame
    rollbacks that never happened. The message names the directory so
    the operator can tell a wrong path from a genuinely cold run."""


def leaf_names(space) -> List[str]:
    """Human-readable key paths for every leaf of a ``FlatSpace``, in
    flat-buffer order (``['w']`` -> ``"['w']"`` etc.)."""
    dummy = space.treedef.unflatten(list(range(space.num_leaves)))
    flat = jax.tree_util.tree_flatten_with_path(dummy)[0]
    out = [""] * space.num_leaves
    for path, idx in flat:
        out[int(idx)] = jax.tree_util.keystr(path)
    return out


def localize_nonfinite(space, flat_grads, seg_meta=None,
                       per_tensor_norms=None) -> List[Dict[str, Any]]:
    """Suspects list: one ``{"leaf", "name", "norm"}`` per parameter
    whose gradient norm is nonfinite. ``per_tensor_norms`` (e.g. from a
    ``with_grad_norm=True`` step's aux) is used verbatim when given;
    otherwise the reduction runs over ``flat_grads`` — through the
    segmented layout's per-segment slot accumulators when ``seg_meta``
    is present, else the subtile-partial path."""
    if per_tensor_norms is not None:
        norms = np.asarray(per_tensor_norms)
    elif seg_meta is not None:
        from apex_tpu.multi_tensor.segmented import segmented_per_leaf_sumsq

        norms = np.sqrt(np.asarray(
            segmented_per_leaf_sumsq(flat_grads, space, seg_meta)))
    else:
        from apex_tpu.multi_tensor.ops import per_tensor_l2norm

        norms = np.asarray(per_tensor_l2norm(flat_grads, space))
    names = leaf_names(space)
    out = []
    for i in np.nonzero(~np.isfinite(norms))[0]:
        n = float(norms[int(i)])
        out.append({"leaf": int(i), "name": names[int(i)],
                    "norm": n if np.isfinite(n) else str(n)})
    return out


class NonfiniteWatchdog:
    """Wrap a compiled ``TrainStep`` with the escalation ladder above.

    Call-compatible with the wrapped step (same donation contract:
    rebind state/scaler_state to the returned values). The HOST-side
    read of ``aux.found_inf`` each step is the one sync the ladder
    costs; a training loop that already fetches the loss pays nothing
    extra.

    After a rollback the returned state IS the restored checkpoint
    state — the loop should consult :attr:`last_restored_step` to
    rewind its data cursor (see tests/test_watchdog.py for the shape
    of such a loop).
    """

    def __init__(self, step, *, manager=None, scaler=None, threshold: int = 3,
                 max_rollbacks: int = 8, record_kind: str = "resilience",
                 on_event=None):
        self.step = step
        self.manager = manager
        self.scaler = scaler if scaler is not None else step.scaler
        self.threshold = int(threshold)
        self.max_rollbacks = int(max_rollbacks)
        self.record_kind = record_kind
        self.on_event = on_event
        self.consecutive_skips = 0
        self.escalations = 0
        self.last_event: Optional[Dict[str, Any]] = None
        self.last_restored_step: Optional[int] = None

    def __call__(self, state, flat_grads, scaler_state=None, *, lr=None):
        outs = self.step(state, flat_grads, scaler_state, lr=lr)
        if self.step.scaler is not None:
            new_state, new_sstate, aux = outs
        else:
            new_state, aux = outs
            new_sstate = None
        if float(aux.found_inf) == 0.0:
            self.consecutive_skips = 0
            return outs
        # cold path from here (a skipped step): registry lookups are
        # dict hits, invisible next to the escalation machinery
        from apex_tpu.telemetry import metrics as _metrics

        _metrics.registry().counter(
            "resilience_nonfinite_skips",
            "train steps skipped on nonfinite gradients").inc()
        self.consecutive_skips += 1
        if self.consecutive_skips < self.threshold:
            return outs                      # a plain amp skip
        return self._escalate(new_state, flat_grads, new_sstate, aux)

    # -- escalation --------------------------------------------------------

    def _escalate(self, state, flat_grads, scaler_state, aux):
        from apex_tpu import records

        self.escalations += 1
        # escalation wall starts HERE: localization compiles its
        # segmented norm kernels on first use, and that diagnosis time
        # is rollback cost, not unattributed residue
        t_esc0 = time.perf_counter()
        suspects = self._localize(state, flat_grads, aux)
        scale_before = (float(scaler_state.loss_scale)
                        if scaler_state is not None else None)

        restore_s = 0.0
        action = "none"
        restored = None
        if self.manager is not None:
            path = self.manager.latest_valid()
            if path is None:
                raise RollbackUnavailable(
                    "nonfinite gradients escalated past the skip "
                    f"threshold ({self.consecutive_skips} consecutive "
                    "skips) but the checkpoint directory "
                    f"{self.manager.directory!r} holds no valid "
                    "checkpoint to roll back to (cold start, or the "
                    "wrong directory); suspects: "
                    f"{[s['name'] for s in suspects] or 'unlocalized'}",
                    suspects=suspects)
            t_r0 = time.perf_counter()
            restored = self.manager.restore(path, template=state)
            restore_s = time.perf_counter() - t_r0
            action = "rollback"
        new_sstate = scaler_state
        if self.scaler is not None:
            # re-initialized loss scale: the ground-down (or pinned-at-
            # min) scale is part of the failure state being discarded
            new_sstate = self.scaler.init()
            if action == "none":
                action = "scaler_reset"

        event = {
            "event": "nonfinite_escalation",
            "consecutive_skips": self.consecutive_skips,
            "threshold": self.threshold,
            "escalations": self.escalations,
            "suspects": suspects,
            "action": action,
            "restored_step": restored.step if restored else None,
            "loss_scale_before": scale_before,
            "loss_scale_after": (float(new_sstate.loss_scale)
                                 if new_sstate is not None else None),
        }
        self.last_event = event
        self.last_restored_step = restored.step if restored else None
        records.write_record(self.record_kind, event)
        from apex_tpu.telemetry import metrics as _metrics

        reg = _metrics.registry()
        reg.counter("resilience_watchdog_escalations",
                    "nonfinite escalations past the skip threshold").inc(
            action=action)
        reg.event("nonfinite_escalation",
                  consecutive_skips=self.consecutive_skips,
                  action=action,
                  suspects=[s["name"] for s in suspects],
                  restored_step=event["restored_step"])
        # flight recorder: an escalation is a postmortem moment even
        # when the rollback succeeds — the bundle catches the timeline
        # and event tail that led here. Host-local trigger (found_inf
        # is this host's view): no collective is issued.
        from apex_tpu.telemetry import flight as _flight

        _flight.notify("watchdog_rollback", fleet=False, extra=event)
        # goodput ledger: the escalation wall (net of the restore I/O,
        # which its own span attributed to checkpoint_restore) is
        # rollback cost, and the restored->current step range re-trains
        # as rework
        from apex_tpu.telemetry import goodput as _goodput

        _goodput.note_rollback(
            time.perf_counter() - t_esc0, restore_seconds=restore_s,
            restored_step=restored.step if restored else None)
        if self.on_event is not None:
            self.on_event(event)

        if self.escalations > self.max_rollbacks:
            raise RollbackLimitExceeded(
                f"nonfinite gradients survived {self.escalations - 1} "
                f"rollbacks (suspects: "
                f"{[s['name'] for s in suspects] or 'unlocalized'})",
                suspects=suspects)

        self.consecutive_skips = 0
        new_state = restored.opt_state if restored else state
        if self.step.scaler is not None:
            return new_state, new_sstate, aux
        return new_state, aux

    def _localize(self, state, flat_grads, aux):
        if self.step.options.get("donate_grads"):
            # the compiled step consumed the grad buffer; per-tensor
            # norms from the step's aux are the only safe source
            if aux.grad_norm_per_tensor is None:
                return []
            return localize_nonfinite(
                state.space, None,
                per_tensor_norms=aux.grad_norm_per_tensor)
        return localize_nonfinite(
            state.space, flat_grads, seg_meta=state.seg_meta,
            per_tensor_norms=aux.grad_norm_per_tensor)


__all__ = ["NonfiniteWatchdog", "RollbackLimitExceeded",
           "RollbackUnavailable", "leaf_names", "localize_nonfinite"]

"""Deadline-aware exponential backoff with jitter.

The runtime's own history (apex_tpu/records.py:3-17) is three rounds of
measurements lost to transient tunnel/disk failures with zero retry
machinery anywhere. This module is that machinery: one policy,
expressed once, applied to every I/O edge that can transiently fail —
``PrefetchLoader``'s host->device transfers, ``records`` disk writes,
and checkpoint I/O.

Design points:

- **deadline-aware**: ``deadline`` bounds the TOTAL time spent
  (attempts + sleeps) from the first call, so a retry loop can never
  outlive the budget of the operation it serves (a checkpoint save
  that retries past the next save interval is worse than a failed one).
  The last sleep is clamped to the remaining budget.
- **decorrelated jitter**: each delay is scaled by a factor drawn from
  ``[1-jitter, 1+jitter]`` so N workers hitting the same dead disk
  don't retry in lockstep. The jitter source is an injectable
  ``random.Random`` — tests pass a seeded instance (or ``jitter=0``)
  and get bit-identical schedules.
- **injectable clock/sleep**: ``sleep`` and ``monotonic`` are
  parameters, so tests run the full schedule in microseconds.
- **non-retryable allowlist**: exceptions in ``give_up_on`` (plus the
  module default ``NON_RETRYABLE``) pass through IMMEDIATELY even when
  they match ``retry_on`` — a ctrl-C, an interpreter shutdown, or a
  checkpoint that failed VALIDATION (``CheckpointError`` is
  deterministic: the bytes on disk will hash the same on every
  attempt) must not burn the deadline pretending to be a transient
  disk hiccup.
- **named sites are visible**: passing ``site="..."`` publishes every
  retry sleep as ``retry_attempts{site=}`` plus a structured ``retry``
  event (which rides the flight ring into bundles), and the terminal
  outcomes as ``retry_exhausted{site=}`` / ``retry_give_up{site=}`` —
  all on the process-default registry, all best-effort: telemetry can
  never turn a retried call into a failed one. Without ``site`` the
  call is as silent (and as cheap) as before.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

_RNG = random.Random()

# Never retried, whatever retry_on says: retrying cannot change the
# outcome (deterministic failures) or actively fights the user/runtime
# (interrupts, shutdown). Extended per call via ``give_up_on``.
NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    KeyboardInterrupt, SystemExit)


def _note_retry(site: str, attempt: int, exc: BaseException,
                delay: float) -> None:
    """One retry sleep at a named site: counter + flight-ring event.
    Best-effort — telemetry must never fail the retried call."""
    try:
        from apex_tpu.telemetry import metrics as _metrics

        reg = _metrics.registry()
        reg.counter("retry_attempts",
                    "retry_call sleeps (re-attempts) by site").inc(
                        site=site)
        reg.event("retry", site=site, attempt=int(attempt),
                  delay_s=round(float(delay), 6),
                  error=f"{type(exc).__name__}: {exc}")
    except Exception:  # noqa: BLE001 — observability is best-effort
        pass


def _note_terminal(site: str, outcome: str, exc: BaseException) -> None:
    """A retry loop's terminal failure at a named site: ``outcome`` is
    ``"exhausted"`` (budget burned) or ``"give_up"`` (non-retryable
    pass-through). Best-effort, like :func:`_note_retry`."""
    try:
        from apex_tpu.telemetry import metrics as _metrics

        reg = _metrics.registry()
        reg.counter(f"retry_{outcome}",
                    f"retry_call {outcome} terminal failures by "
                    "site").inc(site=site)
        reg.event(f"retry_{outcome}", site=site,
                  error=f"{type(exc).__name__}: {exc}")
    except Exception:  # noqa: BLE001 — observability is best-effort
        pass


def backoff_delays(retries: int, *, base_delay: float = 0.05,
                   factor: float = 2.0, max_delay: float = 2.0,
                   jitter: float = 0.5, rng: Optional[random.Random] = None):
    """The delay schedule ``retry_call`` sleeps through, as a list —
    exposed so tests (and capacity planning) can inspect the exact
    schedule a policy produces."""
    rng = rng if rng is not None else _RNG
    out = []
    for i in range(retries):
        d = min(max_delay, base_delay * (factor ** i))
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        out.append(max(0.0, d))
    return out


def retry_call(
    fn: Callable,
    *args,
    retries: int = 4,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    deadline: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    give_up_on: Tuple[Type[BaseException], ...] = (),
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    monotonic: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
    site: Optional[str] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying ``retry_on`` exceptions up
    to ``retries`` times (``retries + 1`` attempts total) with
    exponential backoff, jitter, and an optional total ``deadline`` in
    seconds. The last exception is re-raised unchanged when the budget
    is exhausted (callers keep catching the original type).
    ``on_retry(attempt, exc, delay)`` fires before each sleep.

    ``give_up_on`` exceptions (always including :data:`NON_RETRYABLE`)
    re-raise from the FIRST attempt even when they also match
    ``retry_on`` — the escape hatch for deterministic failures dressed
    as I/O errors (e.g. a ``CheckpointError`` raised on validation:
    the same bytes fail the same way on every retry).

    ``site`` names the call site for telemetry (module docstring):
    ``retry_attempts{site=}`` per sleep plus a ``retry`` event, and
    ``retry_exhausted{site=}`` / ``retry_give_up{site=}`` on terminal
    failure. ``None`` (the default) publishes nothing."""
    rng = rng if rng is not None else _RNG
    no_retry = NON_RETRYABLE + tuple(give_up_on)
    start = monotonic()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if isinstance(e, no_retry):
                if site is not None:
                    _note_terminal(site, "give_up", e)
                raise
            if attempt >= retries:
                if site is not None:
                    _note_terminal(site, "exhausted", e)
                raise
            delay = min(max_delay, base_delay * (factor ** attempt))
            if jitter:
                delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
            delay = max(0.0, delay)
            if deadline is not None:
                remaining = deadline - (monotonic() - start)
                if remaining <= 0:
                    if site is not None:
                        _note_terminal(site, "exhausted", e)
                    raise
                delay = min(delay, remaining)
            if site is not None:
                _note_retry(site, attempt, e, delay)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
            attempt += 1


def retry(**policy):
    """Decorator form of :func:`retry_call`::

        @retry(retries=3, deadline=2.0)
        def flaky_io(...): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, **policy, **kwargs)
        return wrapped
    return deco


__all__ = ["NON_RETRYABLE", "backoff_delays", "retry", "retry_call"]

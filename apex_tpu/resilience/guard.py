"""Distributed consistency guard: cross-replica divergence detection,
majority repair, and preemption-safe shutdown.

PR 2's resilience stack makes ONE host survive crashes and NaNs; the
failure modes that dominate large TPU fleets are distributed. Silent
data corruption bit-flips one replica's optimizer state and the fleet
trains on quietly diverged weights; the scheduler SIGTERMs the slice
mid-step and the last minutes of training evaporate. This module is
the distributed tier (TorchTitan treats exactly this — replicated-
state integrity plus interruptible checkpointing — as table stakes):

- **fingerprints** — :func:`state_fingerprint` reduces the master +
  every slot buffer to per-leaf BITWISE uint32 checksums
  (``multi_tensor.segmented.segmented_per_leaf_checksum`` — the words
  of the buffer reinterpreted as integers and summed mod 2^32 through
  the segmented layout's slot maps). Data-parallel replicas hold
  bit-identical state by construction, so fingerprints must match
  exactly; integer addition is order-independent, so they DO match
  when the state does. The fused train step computes the same
  reduction in-jit every ``fingerprint_every`` steps
  (``TrainStep.with_options(fingerprint_every=N)``) so the donation
  path stays zero-copy and monitoring costs one gated extra read.
- **detection + repair** — :class:`ConsistencyGuard` wraps a compiled
  ``TrainStep`` (call-compatible, like the NonfiniteWatchdog). At each
  fingerprint boundary the local fingerprint is all-gathered over the
  replica set and compared bitwise. A mismatch is localized to the
  offending (parameter leaf, buffer, replica), reported as a
  structured ``resilience`` record, and **repaired**: the state of the
  agreeing majority is broadcast to the minority, after which the run
  is bit-identical to an undamaged one. With no majority (1v1 split,
  three-way disagreement) the guard falls back to the PR-2 rollback
  ladder — every replica restores the last quorum checkpoint — or
  raises :class:`DivergenceError` when no manager is attached.
- **collectives** — the guard talks to its peers through a tiny
  :class:`Collective` interface: :class:`ProcessCollective` rides
  ``jax.experimental.multihost_utils`` on a real multi-process
  deployment; :class:`LocalCollective` runs the identical protocol
  between threads of one process (the simulated-fleet analog of the
  8-device CPU mesh the multichip drills use); the default
  :class:`NullCollective` makes a single replica a no-op.
- **preemption** — :func:`install_preemption_handler` registers an
  async-signal-safe SIGTERM/SIGINT handler (it only sets a flag — no
  allocation, no locks, no I/O in signal context). The step loop
  drains the flag via :meth:`PreemptionHandler.should_stop`, which
  runs a cross-host agreement reduction (ANY flagged host stops the
  fleet — a half-shut-down slice is worse than a stopped one), and
  :func:`graceful_shutdown` writes a priority final checkpoint behind
  a barrier and records the event, so a fresh process auto-resumes
  from the very step the SIGTERM landed on.

Fault sites (apex_tpu/resilience/faults.py): ``bit_flip=<steps>`` +
``bit_flip_replica``/``bit_flip_leaf`` flips one mantissa bit of one
replica's master; ``sigterm=<steps>`` delivers a real SIGTERM to the
process at those steps — both deterministic, both driven from the
``APEX_TPU_FAULTS`` env grammar.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np


class DivergenceError(RuntimeError):
    """Cross-replica state divergence that could not be repaired (no
    agreeing majority and no checkpoint manager to roll back with)."""

    def __init__(self, msg: str, report=None):
        super().__init__(msg)
        self.report = report


# ---------------------------------------------------------------------------
# Fingerprints
# ---------------------------------------------------------------------------


class Fingerprint(NamedTuple):
    """Per-leaf bitwise checksums of one replica's train state."""

    names: Tuple[str, ...]      # buffer names, checkpoint _snapshot order
    sums: Any                   # (n_buffers, num_leaves) uint32
    count: int                  # the state's applied-step counter


def fingerprint_buffer_names(state) -> Tuple[str, ...]:
    """Buffer-row names of a state's fingerprint, in the exact order
    :func:`state_fingerprint_array` stacks them (the checkpoint
    module's ``_snapshot`` order: master, then sorted slots)."""
    return ("master",) + tuple(f"slot:{k}" for k in sorted(state.slots))


def state_fingerprint_array(state):
    """JIT-traceable core: (n_buffers, num_leaves) uint32 checksums of
    ``state.master`` and every slot buffer, reduced through
    ``segmented_per_leaf_checksum`` (slot maps when the state carries
    ``seg_meta``, padded-extent routing otherwise)."""
    import jax.numpy as jnp

    from apex_tpu.multi_tensor.segmented import segmented_per_leaf_checksum

    rows = [segmented_per_leaf_checksum(state.master, state.space,
                                        state.seg_meta)]
    for k in sorted(state.slots):
        rows.append(segmented_per_leaf_checksum(state.slots[k], state.space,
                                                state.seg_meta))
    return jnp.stack(rows)


_FP_JITTED = None


def state_fingerprint(state) -> Fingerprint:
    """Host-side fingerprint of a ``FlatOptState`` (one jitted
    reduction over master + slots; cold path — the in-jit variant
    rides the train step's aux, see ``fingerprint_every``)."""
    global _FP_JITTED
    if _FP_JITTED is None:
        import jax

        _FP_JITTED = jax.jit(state_fingerprint_array)
    # compile-plane: fingerprint boundaries are cold, so the observe +
    # label cost nothing measurable; a layout change mid-run (the
    # checksum program re-tracing) surfaces as a recompile event
    from apex_tpu.telemetry import compiled as _compiled

    if _compiled.get_tracker() is not None:
        _compiled.observe("state_fingerprint", {
            "total": int(state.space.total),
            "num_leaves": int(state.space.num_leaves),
            "n_buffers": 1 + len(state.slots),
            "segmented": state.seg_meta is not None})
    with _compiled.label("state_fingerprint"):
        sums = np.asarray(_FP_JITTED(state))
    return Fingerprint(names=fingerprint_buffer_names(state),
                       sums=sums, count=int(state.count))


class DivergenceReport(NamedTuple):
    """Outcome of comparing one fingerprint per replica."""

    divergent: bool
    has_quorum: bool                    # a strict majority agrees
    majority_replica: Optional[int]     # lowest-id member of the majority
    minority_replicas: Tuple[int, ...]  # replicas needing repair
    # (replica, buffer_row, leaf) triples that disagree with the majority
    sites: Tuple[Tuple[int, int, int], ...]


def compare_fingerprints(stacked: np.ndarray) -> DivergenceReport:
    """Compare replicas' fingerprints bitwise.

    ``stacked`` is ``(n_replicas, n_buffers, num_leaves)`` uint32. The
    majority is the most common full-fingerprint value (ties broken
    toward the lowest replica id holding it); a *quorum* is a strict
    majority of the replica set. Pure and deterministic, so every
    replica computes the identical report from the identical gather.
    """
    stacked = np.asarray(stacked)
    n = stacked.shape[0]
    groups: Dict[bytes, List[int]] = {}
    for r in range(n):
        groups.setdefault(stacked[r].tobytes(), []).append(r)
    if len(groups) == 1:
        return DivergenceReport(False, True, 0, (), ())
    # most members, then lowest leader id
    best = max(groups.values(), key=lambda ms: (len(ms), -ms[0]))
    has_quorum = len(best) * 2 > n
    majority = best[0] if has_quorum else None
    minority = tuple(r for r in range(n) if r not in best)
    sites: List[Tuple[int, int, int]] = []
    ref = stacked[best[0]]
    for r in minority:
        for b, leaf in zip(*np.nonzero(stacked[r] != ref)):
            sites.append((int(r), int(b), int(leaf)))
    return DivergenceReport(True, has_quorum, majority, minority,
                            tuple(sites))


# ---------------------------------------------------------------------------
# Collectives
# ---------------------------------------------------------------------------


class Collective:
    """The minimal replica-set interface the guard needs. Replicas are
    the members of the data axis that hold (supposedly) bit-identical
    state — one per host process on a multi-host deployment.

    Observability: route instances through
    ``telemetry.comms.instrument()`` to trace every op (per-op
    counters/bytes/ms, timeline spans, the wire bandwidth ledger);
    with the comms plane disabled that call returns the raw object
    unchanged. :meth:`impl_name` is the ``impl=`` label the traced
    metrics carry."""

    n_replicas: int = 1
    replica_id: int = 0

    def impl_name(self) -> str:
        """Implementation label for comms tracing (telemetry/comms)."""
        return type(self).__name__

    def all_gather(self, arr: np.ndarray) -> np.ndarray:
        """(n_replicas, *arr.shape) — every replica's copy, by id."""
        raise NotImplementedError

    def broadcast_from(self, src: int,
                       arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Every replica returns replica ``src``'s ``arrays``. A
        collective op: ALL replicas must call it."""
        raise NotImplementedError

    def barrier(self) -> None:
        """Block until every replica has arrived."""
        raise NotImplementedError

    def agree_any(self, flag: bool) -> bool:
        """True on every replica iff ANY replica passed True."""
        out = self.all_gather(np.asarray([1 if flag else 0], np.int32))
        return bool(np.any(out))


class NullCollective(Collective):
    """Single replica: gathers are identity, broadcasts echo."""

    def all_gather(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(arr)[None]

    def broadcast_from(self, src, arrays):
        return [np.asarray(a) for a in arrays]

    def barrier(self) -> None:
        pass


class ProcessCollective(Collective):
    """Real multi-process replica set over
    ``jax.experimental.multihost_utils`` (one replica per host process;
    requires ``jax.distributed.initialize`` — see
    ``apex_tpu.parallel.multiproc.initialize_distributed``)."""

    def __init__(self):
        import jax

        self.n_replicas = jax.process_count()
        self.replica_id = jax.process_index()

    def all_gather(self, arr: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray(arr)))

    def broadcast_from(self, src, arrays):
        from jax.experimental import multihost_utils

        out = multihost_utils.broadcast_one_to_all(
            tuple(np.asarray(a) for a in arrays),
            is_source=self.replica_id == src)
        return [np.asarray(a) for a in out]

    def barrier(self) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("apex_tpu_guard_barrier")


class KVStoreCollective(Collective):
    """Replica set over the ``jax.distributed`` coordination service's
    key-value store (the same service ``initialize_distributed``
    brings up) instead of device collectives.

    ``ProcessCollective`` rides ``multihost_utils``, whose gathers are
    device computations — unavailable on a multi-process CPU cluster
    ("Multiprocess computations aren't implemented on the CPU
    backend"), which is exactly where the two-process drills run. The
    guard's payloads are tiny host arrays (fingerprints, flags,
    repaired buffers), so the coordination service is the right
    transport: each op uses a fresh monotonic key namespace (every
    replica issues collectives in lockstep — the Collective contract —
    so sequence numbers agree), values travel as raw ``.npy`` bytes,
    and barriers are the service's own.
    ``parallel.multiproc.process_collective()`` picks this class
    automatically when the cluster's backend is CPU."""

    def __init__(self, *, timeout: float = 60.0,
                 prefix: str = "apex_tpu_kvc"):
        import jax
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized (no coordination "
                "client); call initialize_distributed() first")
        self._client = client
        self.n_replicas = jax.process_count()
        self.replica_id = jax.process_index()
        self.timeout_ms = int(timeout * 1000)
        self._prefix = prefix
        self._seq = 0

    def _op(self) -> str:
        self._seq += 1
        return f"{self._prefix}/{self._seq}"

    @staticmethod
    def _encode(arr: np.ndarray) -> bytes:
        import io

        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
        return buf.getvalue()

    @staticmethod
    def _decode(data: bytes) -> np.ndarray:
        import io

        return np.load(io.BytesIO(data), allow_pickle=False)

    def all_gather(self, arr: np.ndarray) -> np.ndarray:
        op = self._op()
        self._client.key_value_set_bytes(
            f"{op}/{self.replica_id}", self._encode(np.asarray(arr)))
        out = [self._decode(self._client.blocking_key_value_get_bytes(
            f"{op}/{r}", self.timeout_ms))
            for r in range(self.n_replicas)]
        return np.stack(out)

    def broadcast_from(self, src, arrays):
        op = self._op()
        if self.replica_id == src:
            for i, a in enumerate(arrays):
                self._client.key_value_set_bytes(
                    f"{op}/{i}", self._encode(np.asarray(a)))
            return [np.asarray(a) for a in arrays]
        return [self._decode(self._client.blocking_key_value_get_bytes(
            f"{op}/{i}", self.timeout_ms))
            for i in range(len(arrays))]

    def barrier(self) -> None:
        self._client.wait_at_barrier(self._op().replace("/", "_"),
                                     self.timeout_ms)


class LocalCollective:
    """An in-process replica set: ``handles(n)`` returns one
    :class:`Collective` per simulated host, synchronized with barriers.
    Each replica runs the SAME loop code a real host would, on its own
    thread — the threaded analog of the repo's simulated 8-device CPU
    mesh, and what tests/test_guard.py and the fleet drills drive.
    """

    def __init__(self, n_replicas: int, timeout: float = 60.0):
        self.n_replicas = int(n_replicas)
        self.timeout = float(timeout)
        self._barrier = threading.Barrier(self.n_replicas)
        self._lock = threading.Lock()
        self._slots: Dict[int, Any] = {}

    def handles(self) -> List["_LocalHandle"]:
        return [_LocalHandle(self, r) for r in range(self.n_replicas)]

    def _exchange(self, replica_id: int, value):
        """All replicas deposit, then all read the full slot map."""
        with self._lock:
            self._slots[replica_id] = value
        self._barrier.wait(self.timeout)
        out = dict(self._slots)
        # second barrier: nobody may start the NEXT exchange (and
        # overwrite slots) until everyone has read this one
        self._barrier.wait(self.timeout)
        return out


class _LocalHandle(Collective):
    def __init__(self, group: LocalCollective, replica_id: int):
        self.group = group
        self.n_replicas = group.n_replicas
        self.replica_id = int(replica_id)

    def impl_name(self) -> str:
        return "LocalCollective"        # the sim, not its handle class

    def all_gather(self, arr: np.ndarray) -> np.ndarray:
        slots = self.group._exchange(self.replica_id, np.asarray(arr))
        return np.stack([slots[r] for r in range(self.n_replicas)])

    def broadcast_from(self, src, arrays):
        mine = ([np.asarray(a) for a in arrays]
                if self.replica_id == src else None)
        slots = self.group._exchange(self.replica_id, mine)
        return [np.copy(a) for a in slots[src]]

    def barrier(self) -> None:
        self.group._barrier.wait(self.group.timeout)


# ---------------------------------------------------------------------------
# The guard
# ---------------------------------------------------------------------------


class ConsistencyGuard:
    """Wrap a compiled ``TrainStep`` with cross-replica divergence
    detection and majority repair (module docstring). Call-compatible
    with the wrapped step — same donation contract; rebind state and
    scaler_state to the returned values.

    Build the inner step with
    ``step.with_options(fingerprint_every=N)`` so the checksums ride
    the jitted program's aux (in-jit, donation-safe, computed only at
    boundaries); the guard then never re-reads the state on the hot
    path. A step without the option still works — the guard falls back
    to the cold-path :func:`state_fingerprint` at each boundary.

    At a boundary (``state.count`` hits a multiple of
    ``fingerprint_every``, checked once per new count value):

    1. all-gather fingerprints over ``collective``;
    2. identical everywhere -> done (the overwhelmingly common case);
    3. divergent with a quorum -> structured ``resilience`` record
       (event ``replica_divergence``, sites localized to buffer +
       parameter leaf + replica), then the majority replica's full
       state is broadcast and the minority adopts it — every replica
       leaves the boundary bit-identical;
    4. divergent with NO quorum -> record, then every replica restores
       ``manager.latest_valid()`` (the PR-2 rollback ladder), or
       :class:`DivergenceError` with the report when no manager.
    """

    def __init__(self, step, *, collective: Optional[Collective] = None,
                 fingerprint_every: Optional[int] = None, manager=None,
                 record_kind: str = "resilience", on_event=None,
                 flight_recorder=None):
        self.step = step
        self.collective = collective or NullCollective()
        # the black box this guard's triggers dump to; None -> the
        # process-global recorder (telemetry.flight). A per-guard
        # recorder matters in the LocalCollective sim, where every
        # simulated host needs its own ring + dump (one shared global
        # recorder would serialize its dump lock across the very
        # threads whose collectives must run concurrently)
        self.flight_recorder = flight_recorder
        every = (fingerprint_every if fingerprint_every is not None
                 else step.options.get("fingerprint_every"))
        if not every or int(every) <= 0:
            raise ValueError(
                "fingerprint_every must be a positive int (pass it here "
                "or build the step with_options(fingerprint_every=N))")
        self.fingerprint_every = int(every)
        self._aux_carries_fp = (
            step.options.get("fingerprint_every") == self.fingerprint_every)
        self.manager = manager
        self.record_kind = record_kind
        self.on_event = on_event
        self.last_report: Optional[DivergenceReport] = None
        self.last_event: Optional[Dict[str, Any]] = None
        self.repairs = 0
        self.rollbacks = 0
        self._last_checked_count = -1

    def __call__(self, state, flat_grads, scaler_state=None, *, lr=None):
        outs = self.step(state, flat_grads, scaler_state, lr=lr)
        if self.step.scaler is not None:
            new_state, new_sstate, aux = outs
        else:
            new_state, aux = outs
            new_sstate = None
        count = int(new_state.count)
        if (count % self.fingerprint_every != 0
                or count == self._last_checked_count):
            return outs
        self._last_checked_count = count
        new_state = self._check(new_state, aux)
        if self.step.scaler is not None:
            return new_state, new_sstate, aux
        return new_state, aux

    # -- post-restore baseline ---------------------------------------------

    def verify_restore(self, state, baseline=None) -> np.ndarray:
        """Accept a restored state into the guarded run.

        Recomputes the bitwise fingerprint of ``state``, checks it
        against ``baseline`` (the fingerprint an elastic restore
        verified on reassembly — ``ElasticRestoredState.fingerprint``
        — or any saved layout manifest's), and, on a multi-replica
        collective, all-gathers the fingerprints so the WHOLE world
        proves it restored identical bits before any training step
        runs. A collective call: every replica must reach it.

        Returns the verified sums (seeded into the flight recorder's
        digest ring, and the boundary at this count is marked checked).
        Raises :class:`DivergenceError` on any mismatch — a bad
        restore must be rebuilt, never trained on — after dumping a
        flight bundle (trigger ``elastic_restore_error``).
        """
        from apex_tpu import records
        from apex_tpu.telemetry import flight as _flight
        from apex_tpu.telemetry import metrics as _metrics

        col = self.collective
        sums = np.asarray(state_fingerprint(state).sums, np.uint32)
        count = int(state.count)

        def _fail(msg: str, extra: Dict[str, Any]):
            event = {"event": "restore_baseline_mismatch",
                     "count": count, "replica_id": col.replica_id,
                     "n_replicas": col.n_replicas, **extra}
            records.write_record(self.record_kind, event)
            reg = _metrics.registry()
            reg.counter("resilience_restore_baseline_mismatches",
                        "post-restore fingerprint baseline "
                        "failures").inc()
            reg.event("restore_baseline_mismatch", **extra)
            err = DivergenceError(msg)
            _flight.notify("elastic_restore_error",
                           recorder=self.flight_recorder, error=err,
                           fleet=False, extra=event)
            raise err

        if baseline is not None:
            want = np.asarray(baseline, np.uint32)
            if sums.shape != want.shape or not np.array_equal(sums, want):
                _fail(
                    f"restored state's fingerprint does not match the "
                    f"checkpoint baseline on replica {col.replica_id} "
                    "— the restore produced different bits than were "
                    "saved", {"reason": "baseline"})
        if col.n_replicas > 1:
            payload = np.concatenate(
                [np.asarray([count], np.uint32), sums.reshape(-1)])
            gathered = col.all_gather(payload)
            counts = gathered[:, 0].astype(np.int64).tolist()
            report = compare_fingerprints(
                gathered[:, 1:].reshape((col.n_replicas,) + sums.shape))
            if len(set(counts)) != 1 or report.divergent:
                _fail(
                    f"replicas restored different state (counts "
                    f"{counts}, minority {list(report.minority_replicas)})"
                    " — the world must re-run the restore, not train",
                    {"reason": "cross_replica", "counts": counts,
                     "minority": list(report.minority_replicas)})
        _flight.record_digest(count, sums, recorder=self.flight_recorder)
        self._last_checked_count = count
        _metrics.registry().event("restore_baseline_verified",
                                  count=count, n_replicas=col.n_replicas)
        return sums

    # -- boundary ----------------------------------------------------------

    def _local_sums(self, state, aux) -> np.ndarray:
        if self._aux_carries_fp and aux.state_fingerprint is not None:
            return np.asarray(aux.state_fingerprint)
        return state_fingerprint(state).sums

    def _check(self, state, aux):
        from apex_tpu.telemetry import flight as _flight

        col = self.collective
        if col.n_replicas <= 1:
            return state
        sums = self._local_sums(state, aux)
        # the flight recorder's state-digest ring rides the checksum
        # the boundary already computed — a postmortem bundle then
        # shows WHEN the state last verified, at zero extra reductions
        _flight.record_digest(int(state.count), sums,
                              recorder=self.flight_recorder)
        # one payload: [count | flattened sums] so step agreement and
        # state agreement ride a single gather
        payload = np.concatenate(
            [np.asarray([int(state.count)], np.uint32), sums.reshape(-1)])
        gathered = col.all_gather(payload)
        counts = gathered[:, 0].astype(np.int64)
        if len(set(counts.tolist())) != 1:
            err = DivergenceError(
                f"replicas are at different step counts {counts.tolist()} "
                "— the fleet lost lockstep (check data sharding and "
                "skipped-step divergence) and fingerprints cannot be "
                "compared")
            # every replica computes this from the identical gather, so
            # the fleet-level dump is collective-safe even here
            _flight.notify("divergence_error", recorder=self.flight_recorder,
                           error=err, collective=col,
                           extra={"counts": counts.tolist()})
            raise err
        report = compare_fingerprints(
            gathered[:, 1:].reshape((col.n_replicas,) + sums.shape))
        self.last_report = report
        if not report.divergent:
            return state
        return self._repair(state, report)

    def _repair(self, state, report: DivergenceReport):
        from apex_tpu import records
        from apex_tpu.resilience.watchdog import leaf_names

        col = self.collective
        names = leaf_names(state.space)
        buffers = fingerprint_buffer_names(state)
        sites = [{"replica": r, "buffer": buffers[b], "leaf": leaf,
                  "name": names[leaf]}
                 for r, b, leaf in report.sites]
        action = ("majority_repair" if report.has_quorum
                  else ("rollback" if self.manager is not None
                        else "unrecoverable"))
        event = {
            "event": "replica_divergence",
            "n_replicas": col.n_replicas,
            "replica_id": col.replica_id,
            "count": int(state.count),
            "has_quorum": report.has_quorum,
            "majority_replica": report.majority_replica,
            "minority_replicas": list(report.minority_replicas),
            "sites": sites,
            "action": action,
        }
        self.last_event = event
        records.write_record(self.record_kind, event)
        from apex_tpu.telemetry import metrics as _metrics

        reg = _metrics.registry()
        reg.counter("resilience_divergence_events",
                    "cross-replica state divergences detected").inc(
            action=action)
        reg.event("replica_divergence", action=action,
                  has_quorum=report.has_quorum,
                  n_sites=len(sites), count=int(state.count))
        # the black box: every replica reaches this boundary with the
        # identical report, so the dump may gather the FLEET snapshot
        # over the same collective — the bundle shows every host's
        # counters/timeline next to the divergence it explains
        from apex_tpu.telemetry import flight as _flight

        _flight.notify("replica_divergence", recorder=self.flight_recorder,
                       collective=col, extra=event)
        if self.on_event is not None:
            self.on_event(event)

        if report.has_quorum:
            self.repairs += 1
            reg.counter("resilience_divergence_repairs",
                        "divergences repaired by majority broadcast").inc()
            return self._adopt_majority(state, report.majority_replica)
        if self.manager is not None:
            self.rollbacks += 1
            reg.counter("resilience_divergence_rollbacks",
                        "no-quorum divergences resolved by rollback").inc()
            col.barrier()          # nobody restores while a peer still saves
            restored = self.manager.restore(template=state)
            return restored.opt_state
        err = DivergenceError(
            f"replica state diverged with no agreeing majority "
            f"({col.n_replicas} replicas, sites: "
            f"{[s['name'] for s in sites] or 'unlocalized'}) and no "
            "checkpoint manager to roll back with", report=report)
        _flight.notify("divergence_error", recorder=self.flight_recorder,
                       error=err, collective=col, extra=event)
        raise err

    def _adopt_majority(self, state, src: int):
        """Broadcast the majority replica's buffers; every replica
        rebuilds its state from the received copy (bit-identical for
        agreeing members, the repair for the minority)."""
        import jax.numpy as jnp

        keys = sorted(state.slots)
        local = ([np.asarray(state.master)]
                 + [np.asarray(state.slots[k]) for k in keys]
                 + [np.asarray(state.count), np.asarray(state.found_inf)])
        got = self.collective.broadcast_from(src, local)
        master, slot_vals = got[0], got[1:1 + len(keys)]
        count, found_inf = got[-2], got[-1]
        return state._replace(
            master=jnp.asarray(master),
            slots={k: jnp.asarray(v) for k, v in zip(keys, slot_vals)},
            count=jnp.asarray(count, jnp.int32),
            found_inf=jnp.asarray(found_inf, jnp.float32))


# ---------------------------------------------------------------------------
# Preemption-safe shutdown
# ---------------------------------------------------------------------------


class PreemptionHandler:
    """Flag-drain preemption protocol.

    The signal handler body is async-signal-safe: it assigns two
    attributes and nothing else (no allocation beyond an int, no
    locks, no I/O — everything heavy happens later, on the step loop's
    thread, when it polls :meth:`should_stop`).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self.requested = False
        self.signum: Optional[int] = None
        self._previous: Dict[int, Any] = {}
        self._installed = False

    # the signal-context entry point — keep it trivial
    def _handle(self, signum, frame):  # noqa: ARG002
        self.requested = True
        self.signum = signum

    def install(self) -> "PreemptionHandler":
        """Register on the configured signals (main thread only, per
        the ``signal`` module's contract); previous handlers are saved
        and restored by :meth:`uninstall`."""
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        self._installed = False

    def should_stop(self, collective: Optional[Collective] = None) -> bool:
        """Drain point for the step loop. With a collective, runs the
        cross-host agreement reduction: ANY flagged host stops the
        whole fleet (the scheduler rarely signals every host in the
        same instant; a fleet that half-stops deadlocks its next
        collective). Without one, just the local flag."""
        if collective is None or collective.n_replicas <= 1:
            return self.requested
        return collective.agree_any(self.requested)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def install_preemption_handler(
        signals=(signal.SIGTERM, signal.SIGINT)) -> PreemptionHandler:
    """Install and return a :class:`PreemptionHandler` (see
    :class:`PreemptionHandler` and docs/resilience.md "Preemption")."""
    return PreemptionHandler(signals).install()


def graceful_shutdown(manager, step: int, state, *, scaler_state=None,
                      rng_state=None, extra=None,
                      collective: Optional[Collective] = None,
                      handler: Optional[PreemptionHandler] = None,
                      record_kind: str = "resilience",
                      flight_recorder=None) -> str:
    """The drain action: cross-host barrier, priority final checkpoint,
    structured record. Returns the checkpoint path; the caller exits
    its loop afterwards and a fresh process auto-resumes from
    ``manager.latest_valid()`` (tests/test_guard.py pins the round
    trip).

    The barrier runs FIRST so no host checkpoints while a peer is
    still mid-step (a multi-host quorum save needs every host's shard;
    see checkpoint.py's quorum mode). Any in-flight async save is
    drained, then the final save runs SYNCHRONOUSLY — on SIGTERM there
    is no later step to overlap with, only a kill deadline.
    """
    from apex_tpu import records

    t_drain0 = time.perf_counter()
    col = collective or NullCollective()
    col.barrier()
    manager.wait()
    was_async = manager.async_save
    manager.async_save = False
    # goodput ledger, part one — BEFORE the save: the final checkpoint
    # packs the ledger into its extra, so the barrier/drain wall spent
    # so far must be credited now or it dies with this process
    from apex_tpu.telemetry import goodput as _goodput

    _goodput.note_drain(time.perf_counter() - t_drain0)
    try:
        t_save0 = time.perf_counter()
        path = manager.save(step, state, scaler_state=scaler_state,
                            rng_state=rng_state, extra=extra)
        save_s = time.perf_counter() - t_save0
    finally:
        manager.async_save = was_async
    event = {
        "event": "preemption_checkpoint",
        "step": int(step),
        "signum": handler.signum if handler is not None else None,
        "path": path,
        "n_replicas": col.n_replicas,
        "replica_id": col.replica_id,
    }
    records.write_record(record_kind, event)
    # flight bundle AFTER the final checkpoint is durable: the black
    # box names the checkpoint a fresh process will resume from. Every
    # host runs graceful_shutdown (should_stop is an agreement
    # reduction), so the fleet gather is collective-safe here
    from apex_tpu.telemetry import flight as _flight

    _flight.notify("preemption_shutdown", recorder=flight_recorder,
                   collective=col, extra=event)
    # goodput ledger, part two — the post-save tail (record + flight
    # bundle), net of the save itself (the save's own span landed in
    # checkpoint_save). This portion is live-view only: it postdates
    # the pack the final checkpoint carried.
    _goodput.note_drain(time.perf_counter() - t_save0,
                        save_seconds=save_s)
    return path


__all__ = [
    "Collective",
    "ConsistencyGuard",
    "DivergenceError",
    "DivergenceReport",
    "Fingerprint",
    "KVStoreCollective",
    "LocalCollective",
    "NullCollective",
    "PreemptionHandler",
    "ProcessCollective",
    "compare_fingerprints",
    "fingerprint_buffer_names",
    "graceful_shutdown",
    "install_preemption_handler",
    "state_fingerprint",
    "state_fingerprint_array",
]

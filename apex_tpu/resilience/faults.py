"""Deterministic fault injection for the resilience subsystem.

Three rounds of hardware evidence were lost to a flaky tunneled-TPU
environment (apex_tpu/records.py:3-17) with nothing in the codebase
able to *reproduce* that flakiness on demand. This module is the
reproduction harness: every failure mode the resilience layer defends
against — NaN gradients, transient/permanent I/O errors, truncated
checkpoint files, a process dying mid-run — can be injected at exact,
deterministic points (no randomness, no wall-clock), either from test
code via the :func:`inject` context manager or from the environment
via the ``APEX_TPU_FAULTS`` knob.

Injection is *site + counter* based: components call
``faults.check("site")`` at their fault points, and the active
:class:`FaultInjector` raises at the call indices the plan names.
Sites wired into the package:

===================  ======================================================
site                 fault point
===================  ======================================================
``device_put``       ``PrefetchLoader``'s worker-thread host->device
                     transfer (apex_tpu/runtime)
``record_write``     ``records.write_record``'s disk write
``checkpoint_write`` ``resilience.checkpoint.CheckpointManager._write``
===================  ======================================================

Env knob grammar (semicolon-separated clauses)::

    APEX_TPU_FAULTS="nan_grads=3,4;nan_leaf=2;io:device_put=0,1;
                     io_permanent:record_write=5;truncate=12;crash=7"

- ``nan_grads=<steps>``          poison the flat gradient at these steps
- ``nan_leaf=<i>``               which leaf to poison (default: element 0)
- ``io:<site>=<indices>``        transient ``FaultError`` at these call
                                 indices of ``site`` (0-based)
- ``io_permanent:<site>=<k>``    every call of ``site`` from index ``k``
                                 on raises (a dead disk / dead transport)
- ``truncate=<steps>``           truncate the checkpoint payload written
                                 at these steps AFTER it is finalized
                                 (simulated on-disk corruption)
- ``crash=<steps>``              ``SimulatedCrash`` from
                                 :func:`maybe_crash` at these steps
- ``data_stall_ms=<ms>``         sleep ``ms`` inside the
                                 ``PrefetchLoader`` worker's
                                 host->device transfer — the consumer
                                 blocks in its ``data_wait`` span, so
                                 the goodput drill can assert the
                                 stalled seconds land in the ledger's
                                 ``data_wait`` bucket, not
                                 ``unattributed``
- ``ckpt_stall_ms=<ms>``         sleep ``ms`` inside the checkpoint
                                 payload write — inside the timed save,
                                 so the stall lands in
                                 ``checkpoint_save``

Distributed sites (the guard/quorum tier, docs/resilience.md):

- ``bit_flip=<steps>``           flip ONE bit of the flat master at
                                 these steps (silent data corruption)
- ``bit_flip_replica=<r>``       only on replica/process ``r``
                                 (default: every replica)
- ``bit_flip_leaf=<i>``          which parameter leaf takes the flip
                                 (default: element 0 of the buffer)
- ``crash_before_commit=<steps>`` ``SimulatedCrash`` inside a host's
                                 quorum-checkpoint save, after the step
                                 dir is claimed but before the host's
                                 shard lands — the coordinator must
                                 time out, refuse the commit, and the
                                 partial host-set must never be resumed
- ``sigterm=<steps>``            deliver a REAL ``SIGTERM`` to this
                                 process at these steps (exercises the
                                 async-signal preemption path)

Elastic-resharding sites (resilience/elastic.py, docs/resilience.md
"Elastic resume"):

- ``shard_truncate=<steps>``     truncate one host's ELASTIC shard
                                 payload AFTER the coordinator's
                                 commit lands — a committed-but-rotten
                                 range the restore path must refuse
- ``shard_truncate_host=<h>``    which host's shard the coordinator
                                 truncates (default: host 0)
- ``world_mismatch=<steps>``     the coordinator records an
                                 inconsistent layout manifest (claimed
                                 world != the committed ranges) — the
                                 restore planner must detect it
- ``range_fetch_timeout=<idx>``  the elastic restore's peer fetch at
                                 these 0-based fetch indices times out;
                                 the planner must fall back to disk

Comms-plane sites (telemetry/comms.py instrumented collectives,
docs/observability.md "Comms & sharding plane"):

- ``io:collective=<idx>``        transient ``FaultError`` raised out of
                                 the traced collective op at these
                                 0-based call indices (every traced op
                                 counts — barriers included)
- ``collective_slow=<ms>``       add a ``ms`` delay to traced
                                 collective ops — the deterministic
                                 slow-interconnect drill behind the
                                 ``collective_slow`` EWMA escalation
- ``collective_slow_at=<idx>``   restrict the injected delay to these
                                 0-based traced-op indices (default:
                                 every op once ``collective_slow`` is
                                 set — set late indices so the EWMA
                                 warms up on healthy ops first)
- ``collective_payload_corrupt=<idx>`` flip ONE byte of the result of
                                 the payload-carrying traced op
                                 (all_gather / broadcast_from) at
                                 these 0-based payload-op indices —
                                 silent wire corruption the consumer
                                 (guard fingerprints, elastic verify)
                                 must catch

Serving sites (apex_tpu/serving/scheduler.py, docs/serving.md):

- ``serving_pool_exhausted=<steps>`` admission control at these engine
                                 steps behaves as if the KV pool were
                                 empty — the scheduler must shed load
                                 to the queue, keep in-flight decodes
                                 running, and dump a flight bundle
- ``decode_step_exception=<steps>`` the decode dispatch at these
                                 engine steps raises ``FaultError`` —
                                 the scheduler's binary-split isolation
                                 retries the batch; a step-level fault
                                 fails every sub-dispatch too, so the
                                 whole batch quarantines (blocks freed,
                                 ``serving_quarantine`` bundle, queue
                                 keeps serving). ``io:decode_step``
                                 injects by CALL index instead — a
                                 single transient index is absorbed by
                                 the retry with ZERO quarantines
- ``decode_nonfinite=<steps>``   poison ONE batch lane's cached K/V
                                 with NaN before the decode dispatch at
                                 these engine steps — the lane's logits
                                 come out nonfinite through the REAL
                                 attention path and the engine must
                                 quarantine only that sequence
- ``decode_nonfinite_lane=<i>``  which in-flight lane takes the NaN
                                 (default: lane 0)
- ``prefill_chunk_exception=<idx>`` the chunk-prefill dispatch number
                                 ``idx`` (0-based, per engine; the
                                 binary-split retries re-check the
                                 SAME index) raises ``FaultError`` —
                                 the whole chunk batch quarantines
                                 and the engine keeps serving.
                                 ``io:prefill_chunk`` injects by CALL
                                 index instead: one transient index
                                 is absorbed by the split retry with
                                 zero quarantines
- ``serving_snapshot_corrupt=<idx>`` truncate the serving drain
                                 snapshot payload AFTER it is finalized
                                 at these 0-based save indices — the
                                 committed-but-rotten snapshot
                                 ``latest_snapshot`` must refuse
- ``weight_swap_mismatch=<idx>`` force ``swap_weights`` validation to
                                 report a signature mismatch at these
                                 0-based swap indices — drills the
                                 structured-rejection path end to end

Fleet-router sites (apex_tpu/serving/fleet.py, docs/serving.md
"Fleet"):

- ``engine_crash=<steps>``       :class:`EngineCrash` out of the
                                 router's per-engine step dispatch at
                                 these ROUTER steps — a router-visible
                                 hard engine death the router must
                                 fence (never retry) and recover from
- ``engine_crash_engine=<i>``    which engine (0-based join order)
                                 ``engine_crash`` kills (default: 0)
- ``engine_stall_ms=<ms>``       sleep ``ms`` inside the target
                                 engine's step dispatch — its
                                 heartbeat goes stale while the engine
                                 stays ALIVE; the router must hedge
                                 its queued work, not fence it
- ``engine_stall_engine=<i>``    which engine stalls (default: 0)
- ``engine_stall_at=<steps>``    restrict the stall to these router
                                 steps (default: every step)
- ``router_snapshot_missing=<idx>`` the router's recovery number
                                 ``idx`` (0-based, per router) finds
                                 NO usable drain snapshot — forcing
                                 the replay-from-prompt+generated
                                 recovery path
- ``io:fleet_router``            transient ``FaultError`` at the
                                 router's per-engine step site (call
                                 indexed) — absorbed by the router's
                                 ``resilience.retry`` backoff

KV-handoff sites (apex_tpu/serving/fleet.py disaggregated
prefill/decode, docs/serving.md "Disaggregated prefill/decode"):

- ``kv_transfer_corrupt=<idx>``  flip ONE byte of the received KV
                                 payload at these 0-based transfer
                                 attempts (each attempt advances the
                                 counter) — the per-block sha256
                                 verify must refuse the install and
                                 the retry re-sends the SAME manifest
- ``kv_transfer_timeout=<idx>``  the transfer attempt raises a
                                 transient ``FaultError`` before any
                                 bytes move (a hung wire) — absorbed
                                 by the handoff's ``resilience.retry``
                                 backoff
- ``kv_transfer_partial=<idx>``  zero the received payload's tail
                                 block at these transfer attempts — a
                                 torn transfer the block-by-block
                                 verify must catch BEFORE install
- ``handoff_orphan=<idx>``       abandon handoff number ``idx``
                                 after export (as if the decode
                                 target died holding the payload) —
                                 the source's exported blocks must be
                                 freed and scrubbed under the
                                 dirty-block rule and the request
                                 re-prefilled on a survivor
- ``io:kv_handoff=<idx>``        transient ``FaultError`` at the
                                 handoff transfer site (call indexed)
                                 — the generic transient-wire drill,
                                 absorbed by the same retry policy

MoE workload-plane sites (apex_tpu/mesh/mesh.py MeshTrainStep,
docs/moe.md):

- ``moe_router_collapse=<steps>`` zero every MoE gate kernel in the
                                 flat master BEFORE the train-step
                                 dispatch at these steps — all router
                                 logits tie, top-k's deterministic
                                 tie-break routes EVERY token to
                                 experts 0..k-1. The Switch aux loss
                                 stays at its balanced value (uniform
                                 probs), so the drill proves the
                                 ``moe_expert_load`` histogram + the
                                 ``moe_imbalance`` EWMA latch are the
                                 detector, not the loss
- ``moe_expert_dead=<idx>``      zero expert ``idx``'s down-projection
                                 (``w2``) in the flat master before
                                 every dispatch while the plan is
                                 active — the expert still receives
                                 its tokens and contributes nothing
                                 (a dead shard host); loss degrades
                                 while routing stays balanced
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Any, Dict, FrozenSet, Optional

ENV_KNOB = "APEX_TPU_FAULTS"


class FaultError(OSError):
    """An injected I/O failure (an ``OSError`` so the same retry
    policies that absorb real transient I/O absorb injected ones)."""


class SimulatedCrash(RuntimeError):
    """An injected process death (kill-and-resume tests raise and catch
    this where a real run would be SIGKILLed / preempted)."""


class EngineCrash(RuntimeError):
    """An injected router-visible hard engine death (the
    ``engine_crash`` clause). Deliberately NOT an ``OSError``: the
    fleet router's transient-retry policy must never retry it — a dead
    engine is fenced and its work recovered, immediately."""


def _int_set(val: str) -> FrozenSet[int]:
    return frozenset(int(v) for v in val.split(",") if v.strip() != "")


@dataclasses.dataclass
class FaultInjector:
    """A deterministic fault plan. All counters are call-order based;
    two identical runs inject at identical points."""

    nan_grad_steps: FrozenSet[int] = frozenset()
    nan_leaf: Optional[int] = None          # None -> poison element 0
    # site -> 0-based call indices that raise a transient FaultError
    io_errors: Dict[str, FrozenSet[int]] = dataclasses.field(
        default_factory=dict)
    # site -> first call index from which EVERY call raises
    io_permanent_from: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    truncate_steps: FrozenSet[int] = frozenset()
    crash_steps: FrozenSet[int] = frozenset()
    # distributed sites
    bit_flip_steps: FrozenSet[int] = frozenset()
    bit_flip_replica: Optional[int] = None   # None -> every replica
    bit_flip_leaf: Optional[int] = None      # None -> buffer element 0
    crash_before_commit_steps: FrozenSet[int] = frozenset()
    sigterm_steps: FrozenSet[int] = frozenset()
    # elastic-resharding sites (resilience/elastic.py)
    shard_truncate_steps: FrozenSet[int] = frozenset()
    shard_truncate_host: int = 0
    world_mismatch_steps: FrozenSet[int] = frozenset()
    range_fetch_timeout: FrozenSet[int] = frozenset()
    # comms-plane sites (telemetry/comms.py instrumented collectives)
    collective_slow_ms: float = 0.0
    collective_slow_at: FrozenSet[int] = frozenset()
    collective_corrupt_indices: FrozenSet[int] = frozenset()
    # serving sites (apex_tpu/serving/scheduler.py, serving/resilience.py)
    pool_exhausted_steps: FrozenSet[int] = frozenset()
    decode_exception_steps: FrozenSet[int] = frozenset()
    prefill_chunk_exception_indices: FrozenSet[int] = frozenset()
    decode_nonfinite_steps: FrozenSet[int] = frozenset()
    decode_nonfinite_lane: int = 0
    snapshot_corrupt_indices: FrozenSet[int] = frozenset()
    weight_swap_mismatch_indices: FrozenSet[int] = frozenset()
    # fleet-router sites (apex_tpu/serving/fleet.py)
    engine_crash_steps: FrozenSet[int] = frozenset()
    engine_crash_engine: int = 0
    engine_stall_ms: float = 0.0
    engine_stall_engine: int = 0
    engine_stall_at: FrozenSet[int] = frozenset()
    router_snapshot_missing: FrozenSet[int] = frozenset()
    # kv-handoff sites (apex_tpu/serving/fleet.py disaggregation)
    kv_transfer_corrupt: FrozenSet[int] = frozenset()
    kv_transfer_timeout: FrozenSet[int] = frozenset()
    kv_transfer_partial: FrozenSet[int] = frozenset()
    handoff_orphan: FrozenSet[int] = frozenset()
    # MoE workload-plane sites (mesh/mesh.py MeshTrainStep)
    moe_router_collapse_steps: FrozenSet[int] = frozenset()
    moe_expert_dead: Optional[int] = None
    # goodput-drill stall sites (telemetry/goodput.py run ledger)
    data_stall_ms: float = 0.0
    ckpt_stall_ms: float = 0.0

    def __post_init__(self):
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- site/counter I/O faults ------------------------------------------

    def count(self, site: str) -> int:
        """Calls of ``site`` seen so far."""
        with self._lock:
            return self._counts.get(site, 0)

    def check(self, site: str) -> None:
        """Record one call of ``site``; raise if the plan says so."""
        with self._lock:
            idx = self._counts.get(site, 0)
            self._counts[site] = idx + 1
        perm = self.io_permanent_from.get(site)
        if perm is not None and idx >= perm:
            raise FaultError(
                f"injected permanent I/O failure at {site}[{idx}]")
        if idx in self.io_errors.get(site, frozenset()):
            raise FaultError(
                f"injected transient I/O failure at {site}[{idx}]")

    # -- NaN gradients -----------------------------------------------------

    def should_poison(self, step: int) -> bool:
        return int(step) in self.nan_grad_steps

    def poison_grads(self, flat_grads, step: int, space=None):
        """Return ``flat_grads`` with NaN written into the configured
        leaf's slice (element 0 when no leaf/space is given) when
        ``step`` is in the plan; unchanged otherwise."""
        if not self.should_poison(step):
            return flat_grads
        import jax.numpy as jnp

        if self.nan_leaf is not None and space is not None:
            off = space.offsets[self.nan_leaf]
            size = max(1, min(space.sizes[self.nan_leaf], 8))
            return flat_grads.at[off:off + size].set(jnp.nan)
        return flat_grads.at[0].set(jnp.nan)

    # -- checkpoint corruption / crash ------------------------------------

    def should_truncate(self, step: int) -> bool:
        return int(step) in self.truncate_steps

    def maybe_crash(self, step: int) -> None:
        if int(step) in self.crash_steps:
            raise SimulatedCrash(f"injected crash at step {int(step)}")

    # -- distributed sites -------------------------------------------------

    def should_bit_flip(self, step: int, replica: int = 0) -> bool:
        return (int(step) in self.bit_flip_steps
                and (self.bit_flip_replica is None
                     or int(replica) == self.bit_flip_replica))

    def flip_bits(self, buf, step: int, replica: int = 0, space=None):
        """Return ``buf`` with ONE mantissa bit of one element flipped
        (element 0 of the configured leaf's slice, or of the buffer)
        when the plan targets (step, replica); unchanged otherwise.
        The silent-data-corruption model: a value that is still finite
        and plausible, detectable only bitwise."""
        if not self.should_bit_flip(step, replica):
            return buf
        import jax
        import jax.numpy as jnp

        idx = 0
        if self.bit_flip_leaf is not None and space is not None:
            idx = space.offsets[self.bit_flip_leaf]
        word = jax.lax.bitcast_convert_type(buf[idx], jnp.uint32)
        flipped = jax.lax.bitcast_convert_type(
            word ^ jnp.uint32(1 << 12), buf.dtype)
        return buf.at[idx].set(flipped)

    def maybe_crash_before_commit(self, step: int) -> None:
        if int(step) in self.crash_before_commit_steps:
            raise SimulatedCrash(
                f"injected host crash before quorum commit at step "
                f"{int(step)}")

    # -- elastic-resharding sites ------------------------------------------

    def shard_truncate_target(self, step: int) -> Optional[int]:
        """Host whose committed elastic shard the coordinator truncates
        at this step, or None — the deterministic committed-but-rotten
        range the elastic restore path must refuse."""
        if int(step) in self.shard_truncate_steps:
            return int(self.shard_truncate_host)
        return None

    def should_world_mismatch(self, step: int) -> bool:
        return int(step) in self.world_mismatch_steps

    def should_range_timeout(self, index: int) -> bool:
        """True when the elastic restore's peer fetch number ``index``
        (0-based, per restore) is planned to time out."""
        return int(index) in self.range_fetch_timeout

    # -- comms-plane sites -------------------------------------------------

    def collective_delay_s(self) -> float:
        """Seconds of injected delay for THIS traced collective op
        (each call advances the 0-based traced-op index;
        ``collective_slow_at`` empty means every op once
        ``collective_slow_ms`` is set). 0.0 off-plan."""
        with self._lock:
            idx = self._counts.get("collective_slow", 0)
            self._counts["collective_slow"] = idx + 1
        if self.collective_slow_ms <= 0.0:
            return 0.0
        if self.collective_slow_at and idx not in self.collective_slow_at:
            return 0.0
        return self.collective_slow_ms / 1e3

    def should_corrupt_collective(self) -> bool:
        """True when THIS payload-carrying traced op (all_gather /
        broadcast_from; each call advances the 0-based payload-op
        index) must have one result byte flipped."""
        with self._lock:
            idx = self._counts.get("collective_corrupt", 0)
            self._counts["collective_corrupt"] = idx + 1
        return idx in self.collective_corrupt_indices

    # -- serving sites -----------------------------------------------------

    def should_pool_exhaust(self, step: int) -> bool:
        """True when the serving scheduler's admission control at
        engine step ``step`` must behave as if the KV pool were empty
        (the deterministic shed-load drill)."""
        return int(step) in self.pool_exhausted_steps

    def maybe_decode_exception(self, step: int) -> None:
        """Raise a :class:`FaultError` out of the serving decode
        dispatch at planned engine steps — the deterministic stand-in
        for a dead device / crashed compile mid-serve."""
        if int(step) in self.decode_exception_steps:
            raise FaultError(
                f"injected decode-step exception at engine step "
                f"{int(step)}")

    def maybe_prefill_chunk_exception(self, index: int) -> None:
        """Raise a :class:`FaultError` out of the serving chunk-prefill
        dispatch number ``index`` (0-based, per engine). The scheduler
        passes the TOP-LEVEL dispatch index down through its
        binary-split retries, so a planned index fails every
        sub-dispatch — the whole chunk batch quarantines, mirroring
        ``decode_step_exception``."""
        if int(index) in self.prefill_chunk_exception_indices:
            raise FaultError(
                f"injected prefill-chunk exception at dispatch "
                f"{int(index)}")

    def nonfinite_lane_at(self, step: int) -> Optional[int]:
        """In-flight lane whose cached K/V the serving engine poisons
        with NaN before the decode dispatch at ``step`` (the lane's
        logits then come out nonfinite through the real attention
        path), or None off-plan."""
        if int(step) in self.decode_nonfinite_steps:
            return int(self.decode_nonfinite_lane)
        return None

    def should_snapshot_corrupt(self, index: int) -> bool:
        """True when the serving drain snapshot save number ``index``
        (0-based, per engine) must be truncated AFTER finalize — the
        committed-but-rotten snapshot the loader must refuse."""
        return int(index) in self.snapshot_corrupt_indices

    def should_weight_swap_mismatch(self, index: int) -> bool:
        """True when ``swap_weights`` call number ``index`` (0-based,
        per engine) must report a forced signature mismatch."""
        return int(index) in self.weight_swap_mismatch_indices

    # -- fleet-router sites ------------------------------------------------

    def maybe_engine_crash(self, step: int, engine: int) -> None:
        """Raise :class:`EngineCrash` out of the fleet router's step
        dispatch for engine ``engine`` (0-based join order) at planned
        ROUTER steps — the deterministic hard-death drill behind the
        router's fence-and-recover path."""
        if (int(step) in self.engine_crash_steps
                and int(engine) == self.engine_crash_engine):
            raise EngineCrash(
                f"injected engine crash: engine {int(engine)} at "
                f"router step {int(step)}")

    def engine_stall_s(self, step: int, engine: int) -> float:
        """Seconds of injected stall for engine ``engine``'s step
        dispatch at router step ``step`` (``engine_stall_at`` empty
        means every step once ``engine_stall_ms`` is set). The engine
        stays alive — its heartbeat just goes stale, so the router
        must hedge, not fence. 0.0 off-plan."""
        if (self.engine_stall_ms <= 0.0
                or int(engine) != self.engine_stall_engine):
            return 0.0
        if self.engine_stall_at and int(step) not in self.engine_stall_at:
            return 0.0
        return self.engine_stall_ms / 1e3

    def should_skip_router_snapshot(self, index: int) -> bool:
        """True when the fleet router's recovery number ``index``
        (0-based, per router) must behave as if NO drain snapshot were
        usable — forcing the replay-from-prompt+generated path."""
        return int(index) in self.router_snapshot_missing

    # -- kv-handoff sites --------------------------------------------------

    def kv_transfer_fault(self) -> Optional[str]:
        """Fault planned for THIS KV handoff transfer attempt (each
        call advances the 0-based transfer-attempt index): one of
        ``"corrupt"`` (flip one received byte — verify must refuse),
        ``"timeout"`` (raise before any bytes move), ``"partial"``
        (zero the received tail block — verify must refuse), or None
        off-plan. Retries advance the counter too, so a single planned
        index is absorbed by one idempotent re-send."""
        with self._lock:
            idx = self._counts.get("kv_transfer", 0)
            self._counts["kv_transfer"] = idx + 1
        if idx in self.kv_transfer_corrupt:
            return "corrupt"
        if idx in self.kv_transfer_timeout:
            return "timeout"
        if idx in self.kv_transfer_partial:
            return "partial"
        return None

    def should_orphan_handoff(self) -> bool:
        """True when THIS handoff (each call advances the 0-based
        handoff index) must be abandoned after export — as if the
        decode target died holding the payload. The router must free
        and scrub the exported source blocks under the dirty-block
        rule and re-prefill the request on a survivor."""
        with self._lock:
            idx = self._counts.get("handoff_orphan", 0)
            self._counts["handoff_orphan"] = idx + 1
        return idx in self.handoff_orphan

    # -- MoE workload-plane sites ------------------------------------------

    def should_collapse_router(self, step: int) -> bool:
        """True when the MoE train step at ``step`` must zero every
        gate kernel before dispatch — the deterministic router-collapse
        drill behind the ``moe_imbalance`` latch."""
        return int(step) in self.moe_router_collapse_steps

    def dead_expert(self) -> Optional[int]:
        """Expert index whose ``w2`` down-projection the MoE train
        step zeroes before each dispatch, or None."""
        return self.moe_expert_dead

    # -- goodput-drill stall sites -----------------------------------------

    def data_stall_s(self) -> float:
        """Seconds the ``PrefetchLoader`` worker sleeps per transfer —
        stalled input pipeline the ledger must attribute to
        ``data_wait``. 0.0 off-plan."""
        return max(0.0, self.data_stall_ms) / 1e3

    def ckpt_stall_s(self) -> float:
        """Seconds the checkpoint payload write sleeps — slow
        checkpoint storage the ledger must attribute to
        ``checkpoint_save``. 0.0 off-plan."""
        return max(0.0, self.ckpt_stall_ms) / 1e3

    def maybe_sigterm(self, step: int) -> None:
        """Deliver a REAL SIGTERM to this process at planned steps —
        the deterministic stand-in for the scheduler's preemption
        notice, exercising the actual async-signal path
        (resilience/guard.py PreemptionHandler)."""
        if int(step) in self.sigterm_steps:
            import os as _os
            import signal as _signal

            _os.kill(_os.getpid(), _signal.SIGTERM)

    # -- env knob ----------------------------------------------------------

    @classmethod
    def from_env(cls, spec: str) -> "FaultInjector":
        """Parse the ``APEX_TPU_FAULTS`` grammar (module docstring)."""
        kw: Dict[str, Any] = {"io_errors": {}, "io_permanent_from": {}}
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            key, _, val = clause.partition("=")
            key = key.strip()
            if key == "nan_grads":
                kw["nan_grad_steps"] = _int_set(val)
            elif key == "nan_leaf":
                kw["nan_leaf"] = int(val)
            elif key == "truncate":
                kw["truncate_steps"] = _int_set(val)
            elif key == "crash":
                kw["crash_steps"] = _int_set(val)
            elif key == "bit_flip":
                kw["bit_flip_steps"] = _int_set(val)
            elif key == "bit_flip_replica":
                kw["bit_flip_replica"] = int(val)
            elif key == "bit_flip_leaf":
                kw["bit_flip_leaf"] = int(val)
            elif key == "crash_before_commit":
                kw["crash_before_commit_steps"] = _int_set(val)
            elif key == "sigterm":
                kw["sigterm_steps"] = _int_set(val)
            elif key == "shard_truncate":
                kw["shard_truncate_steps"] = _int_set(val)
            elif key == "shard_truncate_host":
                kw["shard_truncate_host"] = int(val)
            elif key == "world_mismatch":
                kw["world_mismatch_steps"] = _int_set(val)
            elif key == "range_fetch_timeout":
                kw["range_fetch_timeout"] = _int_set(val)
            elif key == "collective_slow":
                kw["collective_slow_ms"] = float(val)
            elif key == "collective_slow_at":
                kw["collective_slow_at"] = _int_set(val)
            elif key == "collective_payload_corrupt":
                kw["collective_corrupt_indices"] = _int_set(val)
            elif key == "serving_pool_exhausted":
                kw["pool_exhausted_steps"] = _int_set(val)
            elif key == "decode_step_exception":
                kw["decode_exception_steps"] = _int_set(val)
            elif key == "prefill_chunk_exception":
                kw["prefill_chunk_exception_indices"] = _int_set(val)
            elif key == "decode_nonfinite":
                kw["decode_nonfinite_steps"] = _int_set(val)
            elif key == "decode_nonfinite_lane":
                kw["decode_nonfinite_lane"] = int(val)
            elif key == "serving_snapshot_corrupt":
                kw["snapshot_corrupt_indices"] = _int_set(val)
            elif key == "weight_swap_mismatch":
                kw["weight_swap_mismatch_indices"] = _int_set(val)
            elif key == "engine_crash":
                kw["engine_crash_steps"] = _int_set(val)
            elif key == "engine_crash_engine":
                kw["engine_crash_engine"] = int(val)
            elif key == "engine_stall_ms":
                kw["engine_stall_ms"] = float(val)
            elif key == "engine_stall_engine":
                kw["engine_stall_engine"] = int(val)
            elif key == "engine_stall_at":
                kw["engine_stall_at"] = _int_set(val)
            elif key == "router_snapshot_missing":
                kw["router_snapshot_missing"] = _int_set(val)
            elif key == "kv_transfer_corrupt":
                kw["kv_transfer_corrupt"] = _int_set(val)
            elif key == "kv_transfer_timeout":
                kw["kv_transfer_timeout"] = _int_set(val)
            elif key == "kv_transfer_partial":
                kw["kv_transfer_partial"] = _int_set(val)
            elif key == "handoff_orphan":
                kw["handoff_orphan"] = _int_set(val)
            elif key == "moe_router_collapse":
                kw["moe_router_collapse_steps"] = _int_set(val)
            elif key == "moe_expert_dead":
                kw["moe_expert_dead"] = int(val)
            elif key == "data_stall_ms":
                kw["data_stall_ms"] = float(val)
            elif key == "ckpt_stall_ms":
                kw["ckpt_stall_ms"] = float(val)
            elif key.startswith("io:"):
                kw["io_errors"][key[len("io:"):]] = _int_set(val)
            elif key.startswith("io_permanent:"):
                kw["io_permanent_from"][key[len("io_permanent:"):]] = int(val)
            else:
                raise ValueError(
                    f"unknown {ENV_KNOB} clause {clause!r} (see "
                    "apex_tpu/resilience/faults.py for the grammar)")
        return cls(**kw)


# -- module-level active plan ----------------------------------------------

_ACTIVE: Optional[FaultInjector] = None
_ENV_CACHE: tuple = (None, None)          # (spec string, parsed injector)


def install(injector: Optional[FaultInjector]) -> None:
    """Install (or clear, with None) the process-wide fault plan."""
    global _ACTIVE
    _ACTIVE = injector


def active() -> Optional[FaultInjector]:
    """The installed injector, else one parsed from ``APEX_TPU_FAULTS``
    (cached per spec string), else None — the no-faults fast path."""
    if _ACTIVE is not None:
        return _ACTIVE
    spec = os.environ.get(ENV_KNOB)
    if not spec:
        return None
    global _ENV_CACHE
    if _ENV_CACHE[0] != spec:
        _ENV_CACHE = (spec, FaultInjector.from_env(spec))
    return _ENV_CACHE[1]


@contextlib.contextmanager
def inject(**kwargs):
    """``with faults.inject(nan_grad_steps={3}, ...):`` — install a plan
    for the block, restoring whatever was active before."""
    prev = _ACTIVE
    install(FaultInjector(**kwargs))
    try:
        yield _ACTIVE
    finally:
        install(prev)


def check(site: str) -> None:
    inj = active()
    if inj is not None:
        inj.check(site)


def poison_grads(flat_grads, step: int, space=None):
    inj = active()
    if inj is None:
        return flat_grads
    return inj.poison_grads(flat_grads, step, space=space)


def should_truncate(step: int) -> bool:
    inj = active()
    return inj is not None and inj.should_truncate(step)


def maybe_crash(step: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_crash(step)


def flip_bits(buf, step: int, replica: int = 0, space=None):
    inj = active()
    if inj is None:
        return buf
    return inj.flip_bits(buf, step, replica=replica, space=space)


def maybe_crash_before_commit(step: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_crash_before_commit(step)


def maybe_sigterm(step: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_sigterm(step)


def shard_truncate_target(step: int) -> Optional[int]:
    inj = active()
    return None if inj is None else inj.shard_truncate_target(step)


def should_world_mismatch(step: int) -> bool:
    inj = active()
    return inj is not None and inj.should_world_mismatch(step)


def should_range_timeout(index: int) -> bool:
    inj = active()
    return inj is not None and inj.should_range_timeout(index)


def collective_delay_s() -> float:
    inj = active()
    return 0.0 if inj is None else inj.collective_delay_s()


def should_corrupt_collective() -> bool:
    inj = active()
    return inj is not None and inj.should_corrupt_collective()


def should_pool_exhaust(step: int) -> bool:
    inj = active()
    return inj is not None and inj.should_pool_exhaust(step)


def maybe_decode_exception(step: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_decode_exception(step)


def maybe_prefill_chunk_exception(index: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_prefill_chunk_exception(index)


def nonfinite_lane_at(step: int) -> Optional[int]:
    inj = active()
    return None if inj is None else inj.nonfinite_lane_at(step)


def should_snapshot_corrupt(index: int) -> bool:
    inj = active()
    return inj is not None and inj.should_snapshot_corrupt(index)


def should_weight_swap_mismatch(index: int) -> bool:
    inj = active()
    return inj is not None and inj.should_weight_swap_mismatch(index)


def maybe_engine_crash(step: int, engine: int) -> None:
    inj = active()
    if inj is not None:
        inj.maybe_engine_crash(step, engine)


def engine_stall_s(step: int, engine: int) -> float:
    inj = active()
    return 0.0 if inj is None else inj.engine_stall_s(step, engine)


def should_skip_router_snapshot(index: int) -> bool:
    inj = active()
    return inj is not None and inj.should_skip_router_snapshot(index)


def kv_transfer_fault() -> Optional[str]:
    inj = active()
    return None if inj is None else inj.kv_transfer_fault()


def should_orphan_handoff() -> bool:
    inj = active()
    return inj is not None and inj.should_orphan_handoff()


def should_collapse_router(step: int) -> bool:
    inj = active()
    return inj is not None and inj.should_collapse_router(step)


def dead_expert() -> Optional[int]:
    inj = active()
    return None if inj is None else inj.dead_expert()


def data_stall_s() -> float:
    inj = active()
    return 0.0 if inj is None else inj.data_stall_s()


def ckpt_stall_s() -> float:
    inj = active()
    return 0.0 if inj is None else inj.ckpt_stall_s()


__all__ = [
    "ENV_KNOB", "EngineCrash", "FaultError", "FaultInjector",
    "SimulatedCrash",
    "active", "check", "ckpt_stall_s", "collective_delay_s",
    "data_stall_s", "dead_expert",
    "engine_stall_s",
    "flip_bits", "inject",
    "install", "kv_transfer_fault", "maybe_crash",
    "should_corrupt_collective", "should_orphan_handoff",
    "maybe_crash_before_commit", "maybe_decode_exception",
    "maybe_engine_crash", "maybe_prefill_chunk_exception",
    "maybe_sigterm", "nonfinite_lane_at", "poison_grads",
    "shard_truncate_target", "should_collapse_router",
    "should_pool_exhaust",
    "should_range_timeout", "should_skip_router_snapshot",
    "should_snapshot_corrupt",
    "should_truncate", "should_weight_swap_mismatch",
    "should_world_mismatch",
]

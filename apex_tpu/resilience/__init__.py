"""Fault-tolerance layer over the fused train step.

Production JAX training lives or dies on crash/preemption/NaN
recovery (TorchTitan makes recoverable distributed checkpointing a
first-class pillar; this repo's own records module exists because
three rounds of hardware evidence died to a flaky tunnel). This
package makes recovery a native subsystem:

- :mod:`~apex_tpu.resilience.checkpoint` — atomic, self-validating,
  keep-last-k checkpoints of the full train state over the flat host
  buffers; ``latest_valid()`` auto-resume that skips corruption.
- :mod:`~apex_tpu.resilience.watchdog` — ``NonfiniteWatchdog``:
  consecutive-skip counting, per-parameter NaN localization, and
  rollback with a re-initialized loss scale.
- :mod:`~apex_tpu.resilience.retry` — deadline-aware exponential
  backoff with jitter, applied to the prefetch pipeline's device
  transfers and ``records`` disk writes.
- :mod:`~apex_tpu.resilience.faults` — deterministic fault injection
  (context manager + ``APEX_TPU_FAULTS`` env knob) driving the
  kill-and-resume and fault-matrix tests.
- :mod:`~apex_tpu.resilience.guard` — the DISTRIBUTED tier:
  ``ConsistencyGuard`` detects cross-replica state divergence via
  bitwise per-leaf fingerprints all-gathered over the replica set,
  localizes it to (parameter leaf, replica), and repairs it by
  broadcasting the agreeing majority's state; ``PreemptionHandler`` +
  ``graceful_shutdown`` turn SIGTERM into a cross-host-agreed priority
  final checkpoint. ``checkpoint.py``'s quorum mode gives the fleet
  multi-host checkpoints a partial host-set can never corrupt.
- :mod:`~apex_tpu.resilience.elastic` — ELASTIC resharding:
  ``ElasticCheckpointManager`` writes quorum checkpoints as
  logically-indexed range shards and restores them on ANY host count —
  ``ElasticRestorePlanner`` re-partitions the committed ranges onto
  the live world, missing ranges travel over the guard's
  ``Collective``, and the reassembled state is verified bitwise
  against the layout manifest's per-leaf fingerprint.

See docs/resilience.md for the recovery story end to end.
"""

from apex_tpu.resilience import faults
from apex_tpu.resilience.checkpoint import (
    CheckpointError,
    CheckpointManager,
    RestoredState,
)
from apex_tpu.resilience.elastic import (
    ElasticCheckpointManager,
    ElasticLayoutError,
    ElasticRestoredState,
    ElasticRestoreError,
    ElasticRestorePlanner,
    partition_ranges,
)
from apex_tpu.resilience.faults import FaultError, FaultInjector, SimulatedCrash
from apex_tpu.resilience.guard import (
    Collective,
    ConsistencyGuard,
    DivergenceError,
    DivergenceReport,
    KVStoreCollective,
    LocalCollective,
    NullCollective,
    PreemptionHandler,
    ProcessCollective,
    compare_fingerprints,
    graceful_shutdown,
    install_preemption_handler,
    state_fingerprint,
)
from apex_tpu.resilience.retry import (
    NON_RETRYABLE,
    backoff_delays,
    retry,
    retry_call,
)
from apex_tpu.resilience.watchdog import (
    NonfiniteWatchdog,
    RollbackLimitExceeded,
    RollbackUnavailable,
    leaf_names,
    localize_nonfinite,
)

__all__ = [
    "CheckpointError",
    "CheckpointManager",
    "Collective",
    "ConsistencyGuard",
    "DivergenceError",
    "DivergenceReport",
    "ElasticCheckpointManager",
    "ElasticLayoutError",
    "ElasticRestoreError",
    "ElasticRestoredState",
    "ElasticRestorePlanner",
    "FaultError",
    "FaultInjector",
    "KVStoreCollective",
    "LocalCollective",
    "NON_RETRYABLE",
    "NonfiniteWatchdog",
    "NullCollective",
    "PreemptionHandler",
    "ProcessCollective",
    "RestoredState",
    "RollbackLimitExceeded",
    "RollbackUnavailable",
    "SimulatedCrash",
    "backoff_delays",
    "compare_fingerprints",
    "faults",
    "graceful_shutdown",
    "install_preemption_handler",
    "leaf_names",
    "localize_nonfinite",
    "partition_ranges",
    "retry",
    "retry_call",
    "state_fingerprint",
]

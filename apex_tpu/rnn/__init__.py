"""RNN family — LSTM / GRU / ReLU / Tanh / mLSTM
(ref: apex/RNN/RNNBackend.py:25-365, models.py:19-51, cells.py:12-84).

The reference stacks per-timestep cell modules under Python loops with
stateful hidden buffers. The TPU design is one ``lax.scan`` per
(layer, direction): the cell is a pure function on carried state, XLA
fuses the gate pointwise math (the reference needs rnnFusedPointwise
CUDA kernels for this), and the scan keeps the whole sequence on
device. Stacking and bidirectionality are Python-level composition
exactly as in the reference's stackedRNN/bidirectionalRNN, with
inter-layer dropout.

Layout: (seq, batch, features); ``batch_first=True`` transposes at the
boundary (ref RNNBackend.py:222-238).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# cell math (pure functions: (params, x_t, state) -> (state, out))
# --------------------------------------------------------------------------


def _linear(x, w, b=None):
    y = x @ w
    return y if b is None else y + b


def _lstm_gates(p, x, hidden_in, c):
    """Shared LSTM gate/state math (i, f, g, o over a 4x gate stack)."""
    gates = _linear(x, p["w_ih"], p.get("b_ih")) + _linear(
        hidden_in, p["w_hh"], p.get("b_hh"))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c = f * c + i * jnp.tanh(g)
    h = o * jnp.tanh(c)
    return (h, c), h


def lstm_cell(p, x, state):
    """ref cells.py mLSTMCell's standard-LSTM core / torch LSTMCell."""
    h, c = state
    return _lstm_gates(p, x, h, c)


def mlstm_cell(p, x, state):
    """Multiplicative LSTM (ref cells.py:55-84): the hidden input to the
    gates is m = (x W_mih) * (h W_mhh)."""
    h, c = state
    m = _linear(x, p["w_mih"]) * _linear(h, p["w_mhh"])
    return _lstm_gates(p, x, m, c)


def gru_cell(p, x, state):
    """torch-convention GRU (ref models.py:26 wraps nn.GRUCell)."""
    (h,) = state
    xg = _linear(x, p["w_ih"], p.get("b_ih"))
    hg = _linear(h, p["w_hh"], p.get("b_hh"))
    xr, xz, xn = jnp.split(xg, 3, axis=-1)
    hr, hz, hn = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h = (1 - z) * n + z * h
    return (h,), h


def _simple_cell(act):
    def cell(p, x, state):
        (h,) = state
        h = act(_linear(x, p["w_ih"], p.get("b_ih"))
                + _linear(h, p["w_hh"], p.get("b_hh")))
        return (h,), h
    return cell


relu_cell = _simple_cell(jax.nn.relu)
tanh_cell = _simple_cell(jnp.tanh)

_CELLS = {
    "lstm": (lstm_cell, 4, 2, False),
    "mlstm": (mlstm_cell, 4, 2, True),
    "gru": (gru_cell, 3, 1, False),
    "relu": (relu_cell, 1, 1, False),
    "tanh": (tanh_cell, 1, 1, False),
}


class RNN(nn.Module):
    """Stacked (optionally bidirectional) recurrent network
    (ref RNNBackend.py bidirectionalRNN/stackedRNN/RNNCell).

    Input (seq, batch, input_size) — or (batch, seq, ...) with
    ``batch_first``. Returns (output, final_states) where output is the
    top layer's hidden sequence (directions concatenated) and
    final_states is a list of per-layer tuples.
    """

    cell_type: str
    input_size: int
    hidden_size: int
    # recurrent output projection (ref RNNBackend.py:258-262,361-363):
    # h is projected hidden_size -> output_size after every step; the
    # projected h is both the carried recurrent input (w_hh consumes
    # output_size) and the emitted output. Cell-interior state (LSTM c)
    # stays hidden_size. None = no projection.
    output_size: Any = None
    num_layers: int = 1
    bias: bool = True
    batch_first: bool = False
    dropout: float = 0.0
    bidirectional: bool = False
    param_dtype: Any = jnp.float32

    @property
    def _out_size(self):
        if self.output_size is None:
            return self.hidden_size
        if self.output_size <= 0:
            raise ValueError(f"output_size must be positive, got {self.output_size}")
        return self.output_size

    def _cell_params(self, name, in_size):
        cell, gate_mult, _, has_m = _CELLS[self.cell_type]
        g = gate_mult * self.hidden_size
        out = self._out_size
        mk = lambda n, shape: self.param(  # noqa: E731
            f"{name}_{n}", nn.initializers.lecun_normal(), shape,
            self.param_dtype)
        p = {"w_ih": mk("w_ih", (in_size, g)),
             "w_hh": mk("w_hh", (out, g))}
        if out != self.hidden_size:
            if self.cell_type == "gru":
                # the GRU recurrence's (1-z)*n + z*h term mixes the
                # hidden-width gates with the carried h, which is
                # output_size-wide under projection — undefined (the
                # reference crashes on this path too: torch GRUCell's
                # z*(hidden-newgate) has the same width mismatch)
                raise NotImplementedError(
                    "GRU does not support output_size != hidden_size")
            p["w_ho"] = mk("w_ho", (self.hidden_size, out))
        if has_m:
            # ref cells.py mLSTMRNNCell: the multiplicative path is
            # output_size-wide — w_mih (out, in), w_mhh (out, out), and
            # w_hh consumes m (out) — so m matches w_hh's (out, g)
            p["w_mih"] = mk("w_mih", (in_size, out))
            p["w_mhh"] = mk("w_mhh", (out, out))
        if self.bias:
            z = lambda n, shape: self.param(  # noqa: E731
                f"{name}_{n}", nn.initializers.zeros, shape,
                self.param_dtype)
            p["b_ih"] = z("b_ih", (g,))
            p["b_hh"] = z("b_hh", (g,))
        return p

    @nn.compact
    def __call__(self, x, initial_states=None, *, deterministic=True):
        cell, _, n_state, _ = _CELLS[self.cell_type]
        if self.batch_first:
            x = x.transpose(1, 0, 2)
        b = x.shape[1]
        dirs = 2 if self.bidirectional else 1

        def run_scan(p, xs, reverse, init):
            if init is None:
                # carry dtype = promoted (input, param) dtype so fp16
                # inputs against fp32 params scan cleanly; state[0] (the
                # carried h) is output_size-wide under projection, the
                # rest stay hidden_size (ref init_hidden, RNNBackend.py:325)
                cdt = jnp.result_type(xs.dtype, p["w_hh"].dtype)
                init = tuple(
                    jnp.zeros(
                        (b, self._out_size if i == 0 else self.hidden_size),
                        cdt)
                    for i in range(n_state))

            def step(state, x_t):
                state, out = cell(p, x_t, state)
                if "w_ho" in p:
                    out = out @ p["w_ho"]
                    state = (out,) + tuple(state[1:])
                return state, out

            # scan's reverse=True: last-to-first processing with outs in
            # original order — no materialized sequence reversals
            return lax.scan(step, init, xs, reverse=reverse)

        finals = []
        for layer in range(self.num_layers):
            in_size = (self.input_size if layer == 0
                       else self._out_size * dirs)
            outs_dirs, finals_layer = [], []
            for d in range(dirs):
                p = self._cell_params(f"l{layer}d{d}", in_size)
                init = (initial_states[layer][d]
                        if initial_states is not None else None)
                final, outs = run_scan(p, x, reverse=(d == 1), init=init)
                outs_dirs.append(outs)
                finals_layer.append(final)
            x = (jnp.concatenate(outs_dirs, axis=-1)
                 if dirs == 2 else outs_dirs[0])
            finals.append(tuple(finals_layer))
            if (self.dropout > 0.0 and not deterministic
                    and layer < self.num_layers - 1):
                x = nn.Dropout(rate=self.dropout)(x, deterministic=False)

        if self.batch_first:
            x = x.transpose(1, 0, 2)
        return x, finals


def _ctor(cell_type):
    def make(input_size, hidden_size, num_layers, bias=True,
             batch_first=False, dropout=0.0, bidirectional=False,
             output_size=None, **kw):
        """ref models.py constructors; output_size enables the
        reference's w_ho recurrent projection (RNNBackend.py:258-262)."""
        return RNN(cell_type=cell_type, input_size=input_size,
                   hidden_size=hidden_size, num_layers=num_layers,
                   bias=bias, batch_first=batch_first, dropout=dropout,
                   bidirectional=bidirectional, output_size=output_size,
                   **kw)
    make.__name__ = cell_type.upper()
    return make


LSTM = _ctor("lstm")
GRU = _ctor("gru")
ReLU = _ctor("relu")
Tanh = _ctor("tanh")
mLSTM = _ctor("mlstm")

__all__ = ["GRU", "LSTM", "RNN", "ReLU", "Tanh", "mLSTM",
           "gru_cell", "lstm_cell", "mlstm_cell", "relu_cell", "tanh_cell"]

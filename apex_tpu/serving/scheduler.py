"""Continuous-batching scheduler: admission control, per-step join of
prefills and decodes, eviction, and end-to-end telemetry.

The serving tier's control plane (ROADMAP item 1; TorchTitan's
production framing — the scheduler is a first-class, observable
subsystem, not a demo loop). Every engine ``step()``:

1. **Admits** queued requests against the KV pool: a request enters
   only if :class:`~apex_tpu.serving.kv_cache.KVCache` can reserve its
   FULL span (prompt + max_new_tokens), so an admitted request can
   never die of pool exhaustion mid-decode. A request larger than the
   whole pool is rejected (``serving_request_error``); a transiently
   full pool defers admission (the request waits, nothing breaks).
2. **Prefills** the newly admitted as one bucketed batch (batch and
   seq padded to powers of two — the compile-count bound), emitting
   each request's FIRST token from the same program that writes the
   cache (TTFT is one dispatch after admission).
3. **Decodes** every in-flight sequence as one bucketed batch joined
   with the step's new arrivals — continuous batching: a finishing
   sequence's slot (and blocks) are reused by the next admission on
   the very next step, no static-batch barrier.
4. **Evicts/finishes**: sequences hitting ``max_new_tokens`` or their
   EOS free their blocks immediately and land in :meth:`drain`.

Telemetry (the PR-4/5 spine, docs/serving.md metric table):
``serving_queue_depth`` / ``serving_batch_size`` /
``serving_kv_blocks_in_use`` gauges per step, per-request TTFT/TPOT
latency histograms, ``prefill`` / ``decode`` timeline spans (category
``serving``), ``serving_requests{outcome=}`` / ``serving_tokens``
counters, and ``serving_request_error`` / ``serving_pool_exhausted``
structured events that double as flight-recorder triggers — a crash
mid-serve leaves a postmortem bundle naming the request.

Degradation paths are deterministically drillable via
``APEX_TPU_FAULTS`` (resilience/faults.py):

- ``serving_pool_exhausted=<steps>``: admission at those engine steps
  behaves as if the pool were empty — load sheds to the queue,
  in-flight decodes keep running, one event + bundle fire.
- ``decode_step_exception=<steps>``: the decode dispatch raises —
  in-flight requests finish with an error (blocks freed, bundle
  dumped) and the engine keeps serving the queue.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.serving.decode import DecodeStep, make_decode_step
from apex_tpu.serving.kv_cache import KVCache, PoolExhausted, bucket


@dataclasses.dataclass
class Request:
    """One generation request."""

    id: Any
    prompt: Sequence[int]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).ravel()
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.id!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.id!r}: max_new_tokens must be >= 1")


@dataclasses.dataclass
class RequestResult:
    """A finished request: generated tokens + the latency the serving
    bench reports (TTFT = submit -> first token; TPOT = mean
    inter-token interval after the first)."""

    id: Any
    tokens: List[int]
    ttft_s: Optional[float]
    tpot_s: Optional[float]
    finish_reason: str                  # "length" | "eos" | "error"
    error: Optional[str] = None


@dataclasses.dataclass
class _InFlight:
    req: Request
    seq_id: Any
    generated: List[int]
    t_submit: float
    t_first: Optional[float] = None
    t_last: Optional[float] = None

    @property
    def position(self) -> int:
        """0-based position of the NEXT cache append: the last
        generated token's slot (prompt is already cached)."""
        return len(self.req.prompt) + len(self.generated) - 1


class ContinuousBatcher:
    """The continuous-batching engine (module docstring).

    ``max_batch`` bounds in-flight sequences; ``max_prefill_batch``
    bounds how many admissions one step prefills together (prefill
    cost scales with batch x seq — decode keeps running next step
    either way). ``min_width_bucket`` / ``min_seq_bucket`` floor the
    shape buckets so short bursts don't mint tiny one-off programs.
    Decode batches always pad to ``max_batch``: ONE decode program per
    table-width bucket, the compile-count bound check_serving.sh pins.
    """

    def __init__(self, model, params, cache: KVCache, *,
                 max_batch: int = 8, max_prefill_batch: int = 4,
                 min_width_bucket: int = 4, min_seq_bucket: int = 16,
                 registry=None, timeline=None,
                 clock: Callable[[], float] = time.perf_counter,
                 step_fn: Optional[DecodeStep] = None):
        from apex_tpu import telemetry

        self.params = params
        self.cache = cache
        self.step_fn = (step_fn if step_fn is not None
                        else make_decode_step(model, cache))
        self.max_batch = int(max_batch)
        self.max_prefill_batch = int(max_prefill_batch)
        self.min_width_bucket = int(min_width_bucket)
        self.min_seq_bucket = int(min_seq_bucket)
        self.clock = clock
        self._registry = (registry if registry is not None
                          else telemetry.registry())
        self._timeline = timeline
        self.queue: "deque[Tuple[Request, float]]" = deque()
        self.running: List[_InFlight] = []
        self.finished: List[RequestResult] = []
        self.step_idx = 0
        self._seq_counter = 0
        self._pool_exhausted_dumped = False

    # -- telemetry helpers ---------------------------------------------------

    def _tl(self):
        if self._timeline is not None:
            return self._timeline
        from apex_tpu.telemetry import timeline as _timeline

        return _timeline.get_timeline()

    def _publish_gauges(self) -> None:
        r = self._registry
        r.gauge("serving_queue_depth",
                "requests waiting for admission").set(len(self.queue))
        r.gauge("serving_batch_size",
                "in-flight sequences this engine step").set(
            len(self.running))
        r.gauge("serving_kv_blocks_in_use",
                "KV pool blocks held by in-flight sequences").set(
            self.cache.blocks_in_use)

    def _finish(self, fl: _InFlight, reason: str,
                error: Optional[str] = None) -> None:
        self.cache.free(fl.seq_id)
        n = len(fl.generated)
        ttft = (fl.t_first - fl.t_submit) if fl.t_first is not None else None
        tpot = None
        if n > 1 and fl.t_first is not None and fl.t_last is not None:
            tpot = (fl.t_last - fl.t_first) / (n - 1)
        r = self._registry
        r.counter("serving_requests",
                  "finished requests by outcome").inc(outcome=reason)
        r.counter("serving_tokens", "generated tokens").inc(n)
        if ttft is not None:
            r.histogram("serving_ttft_seconds",
                        "submit -> first generated token").observe(ttft)
        if tpot is not None:
            r.histogram("serving_tpot_seconds",
                        "mean inter-token interval after the first"
                        ).observe(tpot)
        self.finished.append(RequestResult(
            id=fl.req.id, tokens=list(fl.generated), ttft_s=ttft,
            tpot_s=tpot, finish_reason=reason, error=error))

    def _reject(self, req: Request, msg: str) -> None:
        ev = self._registry.event("serving_request_error",
                                  request=str(req.id), error=msg)
        from apex_tpu.telemetry import flight as _flight

        _flight.notify("serving_request_error",
                       error=RuntimeError(msg), fleet=False,
                       extra={"request": str(req.id), "event": ev})
        self.finished.append(RequestResult(
            id=req.id, tokens=[], ttft_s=None, tpot_s=None,
            finish_reason="error", error=msg))

    # -- API -----------------------------------------------------------------

    def warmup(self, state, seq_buckets: Optional[Sequence[int]] = None,
               width_buckets: Optional[Sequence[int]] = None):
        """Compile the engine's programs off the hot path: the decode
        program per table-width bucket and the prefill programs for
        every admission batch bucket x seq bucket (admissions trickle,
        so batches of 1, 2, ... each mint a program). Every write
        lands in the trash block; returns the threaded cache state.
        Serving latency after warmup never includes an XLA compile —
        and the compile tracker sees zero ``recompile`` events from
        the hot loop (tools/check_serving.sh)."""
        import jax

        seqs = sorted(set(seq_buckets or [self.min_seq_bucket]))
        widths = sorted(set(width_buckets or [self.min_width_bucket]))
        batches = []
        b = 1
        while b < self.max_prefill_batch:
            batches.append(b)
            b *= 2
        batches.append(bucket(self.max_prefill_batch))
        out = None
        for w in widths:
            out = self.step_fn.decode(
                self.params, state, np.zeros(self.max_batch, np.int32),
                np.zeros(self.max_batch, np.int32),
                np.zeros((self.max_batch, w), np.int32))
            state = out.cache
            for nb in batches:
                for s in seqs:
                    out = self.step_fn.prefill(
                        self.params, state, np.zeros((nb, s), np.int32),
                        np.zeros((nb,), np.int32),
                        np.zeros((nb, w), np.int32))
                    state = out.cache
        if out is not None:
            jax.block_until_ready(out.next_token)
        return state

    def submit(self, request: Request) -> None:
        self.queue.append((request, self.clock()))

    def idle(self) -> bool:
        return not self.queue and not self.running

    def drain(self) -> List[RequestResult]:
        out, self.finished = self.finished, []
        return out

    # -- one engine step -----------------------------------------------------

    def _admit(self, exhausted: bool) -> List[_InFlight]:
        admitted: List[_InFlight] = []
        while (self.queue
               and len(self.running) + len(admitted) < self.max_batch
               and len(admitted) < self.max_prefill_batch):
            req, t_submit = self.queue[0]
            total = len(req.prompt) + req.max_new_tokens
            need = self.cache.blocks_for(total)
            if need > self.cache.num_blocks:
                self.queue.popleft()
                self._reject(req, (
                    f"request needs {need} KV blocks, pool capacity is "
                    f"{self.cache.num_blocks} — can never be admitted"))
                continue
            if exhausted:
                break                        # shed load: stay queued
            try:
                self._seq_counter += 1
                seq_id = ("s", self._seq_counter, req.id)
                self.cache.allocate(seq_id, total)
            except PoolExhausted:
                self._registry.counter(
                    "serving_admission_deferred",
                    "admissions deferred by a transiently full pool"
                ).inc()
                break                        # wait for blocks to free
            self.queue.popleft()
            admitted.append(_InFlight(req=req, seq_id=seq_id,
                                      generated=[], t_submit=t_submit))
        return admitted

    def _tables_for(self, flights: List[_InFlight], batch: int):
        widths = [len(self.cache.table(f.seq_id)) for f in flights]
        w = bucket(max(widths), self.min_width_bucket)
        return self.cache.table_array([f.seq_id for f in flights], w,
                                      batch=batch)

    def _prefill(self, admitted: List[_InFlight], state):
        import jax

        b = bucket(len(admitted))
        s = bucket(max(len(f.req.prompt) for f in admitted),
                   self.min_seq_bucket)
        tokens = np.zeros((b, s), np.int32)
        lengths = np.zeros((b,), np.int32)
        for i, f in enumerate(admitted):
            tokens[i, :len(f.req.prompt)] = f.req.prompt
            lengths[i] = len(f.req.prompt)
        tables = self._tables_for(admitted, b)
        with self._tl().phase("prefill", category="serving"):
            out = self.step_fn.prefill(self.params, state, tokens,
                                       lengths, tables)
            jax.block_until_ready(out.next_token)
        now = self.clock()
        ids = np.asarray(out.next_token)
        for i, f in enumerate(admitted):
            f.generated.append(int(ids[i]))
            f.t_first = f.t_last = now
        return out.cache

    def _decode(self, state, idx: int):
        import jax

        from apex_tpu.resilience import faults

        b = self.max_batch          # fixed: one program per width bucket
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        for i, f in enumerate(self.running):
            tokens[i] = f.generated[-1]
            positions[i] = f.position
        tables = self._tables_for(self.running, b)
        with self._tl().phase("decode", category="serving"):
            # deterministic drill sites: the named engine-step clause
            # (decode_step_exception=<steps>) plus the generic
            # call-indexed io:decode_step grammar
            faults.maybe_decode_exception(idx)
            faults.check("decode_step")
            out = self.step_fn.decode(self.params, state, tokens,
                                      positions, tables)
            jax.block_until_ready(out.next_token)
        now = self.clock()
        ids = np.asarray(out.next_token)
        for i, f in enumerate(self.running):
            f.generated.append(int(ids[i]))
            f.t_last = now
        return out.cache, out

    def _reap(self) -> List[Any]:
        done, keep = [], []
        for f in self.running:
            if (f.req.eos_id is not None
                    and f.generated[-1] == f.req.eos_id):
                self._finish(f, "eos")
                done.append(f.req.id)
            elif len(f.generated) >= f.req.max_new_tokens:
                self._finish(f, "length")
                done.append(f.req.id)
            else:
                keep.append(f)
        self.running = keep
        return done

    def step(self, state) -> Tuple[Any, Dict[str, Any]]:
        """One engine iteration over the donated cache ``state``;
        returns ``(new_state, report)`` — the report (admitted /
        decoded / finished ids, blocks in use) is the golden-sequence
        surface tests assert against."""
        from apex_tpu.resilience import faults
        from apex_tpu.telemetry import flight as _flight

        idx = self.step_idx
        self.step_idx += 1
        exhausted = faults.should_pool_exhaust(idx)
        if exhausted:
            self._registry.event("serving_pool_exhausted", step=idx,
                                 injected=True,
                                 queued=len(self.queue),
                                 in_flight=len(self.running))
            if not self._pool_exhausted_dumped:
                self._pool_exhausted_dumped = True
                _flight.notify(
                    "serving_pool_exhausted", fleet=False,
                    extra={"step": idx, "queued": len(self.queue),
                           "blocks_in_use": self.cache.blocks_in_use})
        admitted = self._admit(exhausted)
        report: Dict[str, Any] = {
            "step": idx,
            "admitted": [f.req.id for f in admitted],
            "decoded": [],
            "finished": [],
            "queued": len(self.queue),
        }
        if admitted:
            state = self._prefill(admitted, state)
            self.running.extend(admitted)
        # reap BEFORE decoding: a request whose prefill token already
        # hit max_new/EOS must not buy a decode slot
        report["finished"].extend(self._reap())
        if self.running:
            try:
                state, _ = self._decode(state, idx)
                report["decoded"] = [f.req.id for f in self.running]
            except Exception as e:  # noqa: BLE001 — degrade, keep serving
                msg = f"{type(e).__name__}: {str(e)[:200]}"
                self._registry.event("serving_request_error",
                                     step=idx, error=msg,
                                     in_flight=len(self.running))
                _flight.notify("serving_request_error", error=e,
                               fleet=False,
                               extra={"step": idx,
                                      "requests": [str(f.req.id)
                                                   for f in self.running]})
                for f in self.running:
                    self._finish(f, "error", error=msg)
                    report["finished"].append(f.req.id)
                self.running = []
        report["finished"].extend(self._reap())
        report["blocks_in_use"] = self.cache.blocks_in_use
        self._publish_gauges()
        return state, report


def serve_loop(batcher: ContinuousBatcher, state, requests:
               Sequence[Request], *,
               arrivals: Optional[Sequence[float]] = None,
               clock: Callable[[], float] = time.perf_counter,
               sleep: Callable[[float], None] = time.sleep):
    """Drive ``batcher`` over an arrival schedule until every request
    finishes; returns ``(final_cache_state, results)``.

    ``arrivals`` are seconds offsets from loop start (default: all at
    t=0). Submissions happen when the wall clock passes each offset —
    the serving bench's Poisson schedule goes through here.
    """
    order = sorted(range(len(requests)),
                   key=lambda i: arrivals[i] if arrivals else 0.0)
    t0 = clock()
    results: List[RequestResult] = []
    i = 0
    while i < len(order) or not batcher.idle():
        now = clock() - t0
        while i < len(order) and (
                not arrivals or arrivals[order[i]] <= now):
            batcher.submit(requests[order[i]])
            i += 1
        if batcher.idle():
            if i < len(order):
                sleep(max(0.0, min(arrivals[order[i]] - now, 0.001)))
            continue
        state, _ = batcher.step(state)
        results.extend(batcher.drain())
    results.extend(batcher.drain())
    return state, results


def static_batch_generate(model, params, cache: KVCache, state,
                          requests: Sequence[Request], *,
                          batch_size: int = 8,
                          arrivals: Optional[Sequence[float]] = None,
                          clock: Callable[[], float] = time.perf_counter,
                          sleep: Callable[[float], None] = time.sleep,
                          step_fn: Optional[DecodeStep] = None,
                          min_seq_bucket: int = 16,
                          min_width_bucket: int = 4):
    """The naive baseline the serving bench compares against: fixed
    batches in arrival order, each run to the SLOWEST member's last
    token before the next batch starts — late arrivals wait behind the
    barrier, early finishers idle inside it. Same jitted steps, same
    cache machinery; only the scheduling differs. Returns
    ``(final_cache_state, results)``.
    """
    import jax

    step = step_fn if step_fn is not None else make_decode_step(model,
                                                                cache)
    t0 = clock()
    results: List[RequestResult] = []
    pending = list(requests)
    submit_at = list(arrivals) if arrivals else [0.0] * len(pending)
    pos = 0
    while pos < len(pending):
        batch = pending[pos:pos + batch_size]
        t_sub = submit_at[pos:pos + batch_size]
        pos += len(batch)
        # the static server cannot start until every member has arrived
        wait = max(t_sub) - (clock() - t0)
        if wait > 0:
            sleep(wait)
        seqs = []
        for j, req in enumerate(batch):
            sid = ("static", pos, j)
            cache.allocate(sid, len(req.prompt) + req.max_new_tokens)
            seqs.append(sid)
        b = bucket(len(batch))
        s = bucket(max(len(r.prompt) for r in batch), min_seq_bucket)
        w = bucket(max(len(cache.table(sid)) for sid in seqs),
                   min_width_bucket)
        tokens = np.zeros((b, s), np.int32)
        lengths = np.zeros((b,), np.int32)
        for j, req in enumerate(batch):
            tokens[j, :len(req.prompt)] = req.prompt
            lengths[j] = len(req.prompt)
        tables = cache.table_array(seqs, w, batch=b)
        out = step.prefill(params, state, tokens, lengths, tables)
        jax.block_until_ready(out.next_token)
        now = clock()
        state = out.cache
        gen = [[int(t)] for t in np.asarray(out.next_token)[:len(batch)]]
        t_first = [now] * len(batch)
        t_last = [now] * len(batch)
        # decode until the SLOWEST member is done (no early slot reuse)
        rounds = max(r.max_new_tokens for r in batch) - 1
        for _ in range(rounds):
            toks = np.zeros((b,), np.int32)
            poss = np.zeros((b,), np.int32)
            for j, req in enumerate(batch):
                toks[j] = gen[j][-1]
                poss[j] = len(req.prompt) + len(gen[j]) - 1
            out = step.decode(params, state, toks, poss, tables)
            jax.block_until_ready(out.next_token)
            now = clock()
            state = out.cache
            ids = np.asarray(out.next_token)
            for j, req in enumerate(batch):
                if len(gen[j]) < req.max_new_tokens:
                    gen[j].append(int(ids[j]))
                    t_last[j] = now
        for j, req in enumerate(batch):
            n = len(gen[j])
            ttft = t_first[j] - (t0 + t_sub[j])
            tpot = ((t_last[j] - t_first[j]) / (n - 1)) if n > 1 else None
            results.append(RequestResult(
                id=req.id, tokens=gen[j], ttft_s=ttft, tpot_s=tpot,
                finish_reason="length"))
            cache.free(seqs[j])
    return state, results


__all__ = [
    "ContinuousBatcher",
    "Request",
    "RequestResult",
    "serve_loop",
    "static_batch_generate",
]

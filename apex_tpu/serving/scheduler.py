"""Continuous-batching scheduler: admission control, per-step join of
prefills and decodes, eviction, and end-to-end telemetry.

The serving tier's control plane (ROADMAP item 1; TorchTitan's
production framing — the scheduler is a first-class, observable
subsystem, not a demo loop). Every engine ``step()``:

1. **Admits** queued requests against the KV pool, reusing published
   prompt-prefix blocks by reference (the prefix cache,
   serving/kv_cache.py): matched tokens skip prefill entirely; the
   private remainder is reserved — the FULL span (prompt +
   max_new_tokens) for short prompts, or STAGED per-chunk for long
   ones (chunked prefill), with the decode span reserved together
   with the last chunk so a request that reaches DECODING still can
   never die of pool exhaustion mid-decode. A request larger than the
   whole pool is rejected (``serving_request_error``); a transiently
   full pool defers admission (the request waits, nothing breaks).
2. **Prefills**: fresh short prompts as one bucketed monolithic batch
   (batch and seq padded to powers of two — the compile-count bound),
   emitting each request's FIRST token from the same program that
   writes the cache (TTFT is one dispatch after admission). Long or
   prefix-resumed prompts live in the ``PREFILLING`` state and
   advance ONE bucketed chunk per step under the per-step
   ``prefill_token_budget`` — a 4k-token prompt never stalls the
   step's decode dispatch behind one monolithic prefill
   (Sarathi-style chunked prefill, docs/serving.md).
3. **Decodes** every in-flight sequence as one bucketed batch joined
   with the step's new arrivals — continuous batching: a finishing
   sequence's slot (and blocks) are reused by the next admission on
   the very next step, no static-batch barrier. Token selection
   (greedy or fused temperature/top-k/top-p sampling) happens inside
   the decode program (serving/decode.py).
4. **Evicts/finishes**: sequences hitting ``max_new_tokens`` or their
   EOS free their block references immediately (shared prefix blocks
   stay resident in the prefix cache) and land in :meth:`drain`.

Telemetry (the PR-4/5 spine, docs/serving.md metric table):
``serving_queue_depth`` / ``serving_batch_size`` /
``serving_kv_blocks_in_use`` gauges per step, per-request TTFT/TPOT
latency histograms, ``prefill`` / ``decode`` timeline spans (category
``serving``), ``serving_requests{outcome=}`` / ``serving_tokens``
counters, and ``serving_request_error`` / ``serving_pool_exhausted``
structured events that double as flight-recorder triggers — a crash
mid-serve leaves a postmortem bundle naming the request.

Resilience (serving/resilience.py, docs/serving.md "Failure modes &
recovery") — the engine degrades per-REQUEST, never per-process:

- **deadlines**: ``Request.deadline_ms`` is a TTL from submission;
  expired requests (queued or in-flight) reap at the top of every
  step, BEFORE admission and decode, with outcome
  ``deadline_exceeded`` (counter + event of the same name).
- **quarantine**: a decode dispatch that raises is retried by binary
  split — halves that succeed keep their tokens, offenders bottom out
  as singletons and finish with outcome ``error``. Nonfinite logits
  localize directly via the decode program's in-jit per-lane finite
  flag. Either way the ``serving_quarantine`` trigger fires and the
  engine keeps serving; quarantined sequences' pool blocks are
  scrubbed before reuse (a NaN row must not haunt the next tenant).
- **preemption drain**: with a ``preemption`` handler attached,
  ``should_stop()`` flips the engine to drain mode — no new
  admissions; with a ``snapshot_dir``, every queued + in-flight
  request persists to an atomic serving snapshot a fresh engine
  resumes from (``resilience.resume_requests``); without one,
  in-flight work finishes and the queue errors out loudly.
- **weight hot-swap**: ``resilience.swap_weights`` stages validated
  params; the engine installs them here, at a step boundary between
  decode dispatches, so no request is dropped
  (``serving_weight_swap`` event with old/new digests).

Degradation paths are deterministically drillable via
``APEX_TPU_FAULTS`` (resilience/faults.py):

- ``serving_pool_exhausted=<steps>``: admission at those engine steps
  behaves as if the pool were empty — load sheds to the queue,
  in-flight decodes keep running, one event + bundle fire.
- ``decode_step_exception=<steps>``: the decode dispatch raises at
  those engine steps — a step-level fault fails every binary-split
  retry too, so the whole batch quarantines (blocks freed, bundle
  dumped) and the engine keeps serving the queue. ``io:decode_step``
  injects by CALL index instead: one transient index is absorbed by
  the retry with zero quarantines.
- ``decode_nonfinite=<steps>`` (+ ``decode_nonfinite_lane``): one
  lane's cached K/V is poisoned with NaN — only that sequence
  quarantines; the rest of the batch keeps its tokens.
- ``prefill_chunk_exception=<idx>``: the chunk-prefill dispatch
  number ``idx`` raises — the binary-split retries re-check the SAME
  dispatch index, so the whole chunk batch quarantines (private
  blocks scrubbed+freed, shared prefix references released) and the
  engine keeps serving. ``io:prefill_chunk`` injects by CALL index
  instead: one transient index is absorbed by the retry with zero
  quarantines.

Request plane (serving/tracing.py + telemetry/slo.py,
docs/observability.md "Request plane"): pass ``tracer=RequestTracer()``
and every request gets a :class:`~apex_tpu.serving.tracing.RequestTrace`
— trace id minted at :meth:`ContinuousBatcher.submit`, spans/marks at
every state transition (queued, admitted, prefill, each
``prefill_chunk[i]``, a coalesced decode window, retry/quarantine/
drain/finish), perfetto export one track per request, and the trace id
persisted in drain snapshots so a resumed engine continues the SAME
trace. Pass ``slo=SLOMonitor(...)`` and the engine feeds it per-request
TTFT/TPOT/goodput and per-step queue depth, publishes burn-rate gauges
via ``slo.tick()``, and consults ``slo.should_shed()`` at admission —
a latched burn-rate alert sheds load to the queue
(``serving_slo_shed``) exactly like a transiently exhausted pool.
:meth:`ContinuousBatcher.introspect` is the live view over all of it.
Both default to None: the unarmed engine pays one attribute check per
hook site (the ``disabled is step`` discipline).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.serving.decode import DecodeStep, make_decode_step
from apex_tpu.serving.kv_cache import KVCache, PoolExhausted, bucket
from apex_tpu.telemetry.metrics import TOKEN_COUNT_BUCKETS


@dataclasses.dataclass
class Request:
    """One generation request. ``deadline_ms`` is a TTL measured from
    submission: a request still queued, prefilling, or decoding when
    it elapses is reaped with outcome ``deadline_exceeded`` (its
    generated-so-far tokens are returned; its private blocks free
    immediately, shared prefix references are released). ``None``
    means no deadline.

    Sampling knobs (fused in-program, serving/decode.py):
    ``temperature == 0`` is greedy argmax — bitwise the pre-sampling
    behavior; ``temperature > 0`` draws from the softmax at that
    temperature, restricted to the top ``top_k`` logits (0 = off) and
    the top-``top_p`` nucleus (1.0 = off). ``seed`` keys the
    counter-based per-request PRNG — the stream is a pure function of
    ``(seed, token index)``, so a drain/resume replay regenerates it
    token for token.

    ``trace_id`` is the request plane's identity: normally None (the
    engine's tracer mints one at ``submit()``); a resumed drain
    snapshot carries the ORIGINAL id back (with ``resumed_from``
    naming the snapshot) so the continued trace is the same trace."""

    id: Any
    prompt: Sequence[int]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    deadline_ms: Optional[float] = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    trace_id: Optional[str] = None
    resumed_from: Optional[str] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).ravel()
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.id!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.id!r}: max_new_tokens must be >= 1")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"request {self.id!r}: deadline_ms must be > 0 or None")
        if self.temperature < 0:
            raise ValueError(
                f"request {self.id!r}: temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError(f"request {self.id!r}: top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"request {self.id!r}: top_p must be in (0, 1]")


@dataclasses.dataclass
class RequestResult:
    """A finished request: generated tokens + the latency the serving
    bench reports (TTFT = submit -> first token; TPOT = mean
    inter-token interval after the first).

    ``reason`` is the machine-readable code for every NON-normal
    terminal outcome — routers (and the fleet's handoff path,
    serving/fleet.py) must branch on this field, never string-match
    ``error``:

    - ``"draining"`` — submit on a draining engine, or preempted with
      no snapshot (refusal, no work done)
    - ``"shedding"`` — fleet-wide SLO shed (serving/fleet.py)
    - ``"oversized"`` — the request can never fit the pool
    - ``"handoff_degraded"`` — refused while the fleet's
      colocated-fallback latch is closed (serving/fleet.py)
    - ``"deadline_queued"`` / ``"deadline_prefilling"`` /
      ``"deadline_in_flight"`` — TTL reaps, by the state the request
      died in (``finish_reason == "deadline_exceeded"``)
    - ``"quarantined"`` — per-request fault isolation
      (``finish_reason == "error"``)

    None for the normal outcomes (``length`` / ``eos``)."""

    id: Any
    tokens: List[int]
    ttft_s: Optional[float]
    tpot_s: Optional[float]
    # "length" | "eos" | "error" | "deadline_exceeded"
    finish_reason: str
    error: Optional[str] = None
    # structured terminal-outcome code (docstring); None when normal
    reason: Optional[str] = None


@dataclasses.dataclass
class _InFlight:
    req: Request
    seq_id: Any
    generated: List[int]
    t_submit: float
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    # chunked-prefill progress: prompt tokens already resident in the
    # cache (prefix-cache matches count — prefill resumes after them);
    # a request is PREFILLING while prefilled < len(prompt)
    prefilled: int = 0
    stalls: int = 0

    @property
    def position(self) -> int:
        """0-based position of the NEXT cache append: the last
        generated token's slot (prompt is already cached)."""
        return len(self.req.prompt) + len(self.generated) - 1


class ContinuousBatcher:
    """The continuous-batching engine (module docstring).

    ``max_batch`` bounds in-flight sequences; ``max_prefill_batch``
    bounds how many admissions one step prefills together (prefill
    cost scales with batch x seq — decode keeps running next step
    either way). ``min_width_bucket`` / ``min_seq_bucket`` floor the
    shape buckets so short bursts don't mint tiny one-off programs.
    Decode batches always pad to ``max_batch``: ONE decode program per
    table-width bucket, the compile-count bound check_serving.sh pins.
    """

    def __init__(self, model, params, cache: KVCache, *,
                 max_batch: int = 8, max_prefill_batch: int = 4,
                 min_width_bucket: int = 4, min_seq_bucket: int = 16,
                 prefill_chunk: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None,
                 prefill_interval: int = 1,
                 registry=None, timeline=None,
                 clock: Callable[[], float] = time.perf_counter,
                 step_fn: Optional[DecodeStep] = None,
                 preemption=None, snapshot_dir: Optional[str] = None,
                 tracer=None, slo=None):
        from apex_tpu import telemetry

        self.params = params
        self.cache = cache
        self.step_fn = (step_fn if step_fn is not None
                        else make_decode_step(model, cache))
        self.max_batch = int(max_batch)
        self.max_prefill_batch = int(max_prefill_batch)
        self.min_width_bucket = int(min_width_bucket)
        self.min_seq_bucket = int(min_seq_bucket)
        # chunked prefill (docs/serving.md): prompts longer than
        # `prefill_chunk` advance one bucketed chunk per engine step,
        # co-scheduled with the decode dispatch, instead of one
        # monolithic prefill; `prefill_token_budget` caps the prefill
        # tokens one step may spend (default: a full chunk batch).
        # None = monolithic prefill (the pre-chunking behavior);
        # prefix-cache resumes ride the chunk program either way.
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1 or None")
        self.prefill_chunk = (int(prefill_chunk)
                              if prefill_chunk is not None else None)
        self.prefill_token_budget = (
            int(prefill_token_budget) if prefill_token_budget is not None
            else (self.prefill_chunk * self.max_prefill_batch
                  if self.prefill_chunk else None))
        # the prefill/decode interleave ratio (the Sarathi TTFT/TPOT
        # dial): with k > 1, chunk dispatches run only every k-th step
        # WHILE decodes are in flight — each skipped step is a pure
        # decode step, bounding the chunking tax on TPOT at the price
        # of slower long-prompt TTFT. With no decodes running, chunks
        # advance every step regardless (throttling an idle engine
        # buys nothing).
        if prefill_interval < 1:
            raise ValueError("prefill_interval must be >= 1")
        self.prefill_interval = int(prefill_interval)
        self.clock = clock
        self._registry = (registry if registry is not None
                          else telemetry.registry())
        self._timeline = timeline
        # guards queue mutation + pool reservation (submit() may run on
        # a client thread while the engine thread admits), the finished
        # list, and the staged weight swap — the engine-owned state
        # (running, cache pools) stays single-threaded
        self._lock = threading.Lock()
        self.queue: "deque[Tuple[Request, float]]" = deque()
        self.running: List[_InFlight] = []
        # PREFILLING: admitted, cache partially written, no first
        # token yet — advanced chunk-by-chunk in _prefill_chunks
        self.prefilling: List[_InFlight] = []
        self.finished: List[RequestResult] = []
        self.step_idx = 0
        self._seq_counter = 0
        self._chunk_dispatches = 0        # prefill_chunk_exception idx
        self._pending_copies: Dict[Any, List[Tuple[int, int, int]]] = {}
        self._pool_exhausted_dumped = False
        # resilience plane (serving/resilience.py)
        self.preemption = preemption          # guard.PreemptionHandler
        self.snapshot_dir = snapshot_dir
        self.draining = False
        self.drained_snapshot: Optional[str] = None
        self._pending_swap = None             # (params, info) staged
        self._snapshot_count = 0
        self._swap_count = 0
        # request plane (serving/tracing.py, telemetry/slo.py): both
        # optional — an unarmed engine pays one attribute check per
        # hook site (the `disabled is step` discipline)
        self.tracer = tracer                  # tracing.RequestTracer
        self.slo = slo                        # slo.SLOMonitor
        self._shed_active = False
        if slo is not None:
            slo.attach(
                trace_provider=(tracer.trace_dicts
                                if tracer is not None else None),
                introspect_provider=self.introspect)

    # -- telemetry helpers ---------------------------------------------------

    def _tl(self):
        if self._timeline is not None:
            return self._timeline
        from apex_tpu.telemetry import timeline as _timeline

        return _timeline.get_timeline()

    def _publish_gauges(self) -> None:
        r = self._registry
        r.gauge("serving_queue_depth",
                "requests waiting for admission").set(len(self.queue))
        r.gauge("serving_batch_size",
                "in-flight sequences this engine step").set(
            len(self.running))
        r.gauge("serving_kv_blocks_in_use",
                "KV pool blocks held by in-flight sequences").set(
            self.cache.blocks_in_use)
        stats = self.cache.prefix_stats()
        r.gauge("serving_prefix_blocks_shared",
                "KV blocks referenced by >= 2 sequences").set(
            stats["shared_blocks"])
        r.gauge("serving_prefix_cached_blocks",
                "zero-ref prefix-cache blocks resident (reclaimable)"
                ).set(stats["cached_blocks"])
        r.gauge("serving_prefilling",
                "admitted sequences still prefilling (chunked)").set(
            len(self.prefilling))

    def _push_result(self, res: RequestResult) -> None:
        # the single completion chokepoint: every outcome — length/
        # eos, quarantine, deadline, rejection — lands here, so the
        # request plane closes traces and feeds the SLO window here
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.finish(res.id, res.finish_reason, t=self.clock(),
                      error=res.error)
        if self.slo is not None:
            self.slo.observe_request(
                res.id, ttft_s=res.ttft_s, tpot_s=res.tpot_s,
                ok=res.finish_reason in ("length", "eos"),
                t=self.clock())
        with self._lock:
            self.finished.append(res)

    def _finish(self, fl: _InFlight, reason: str,
                error: Optional[str] = None, *, dirty: bool = False,
                clean_blocks: Sequence[int] = (),
                reason_code: Optional[str] = None) -> None:
        self._pending_copies.pop(fl.seq_id, None)
        self.cache.free(fl.seq_id, dirty=dirty, clean_blocks=clean_blocks)
        n = len(fl.generated)
        ttft = (fl.t_first - fl.t_submit) if fl.t_first is not None else None
        tpot = None
        if n > 1 and fl.t_first is not None and fl.t_last is not None:
            tpot = (fl.t_last - fl.t_first) / (n - 1)
        r = self._registry
        r.counter("serving_requests",
                  "finished requests by outcome").inc(outcome=reason)
        r.counter("serving_tokens", "generated tokens").inc(n)
        if ttft is not None:
            r.histogram("serving_ttft_seconds",
                        "submit -> first generated token").observe(ttft)
        if tpot is not None:
            r.histogram("serving_tpot_seconds",
                        "mean inter-token interval after the first"
                        ).observe(tpot)
        self._push_result(RequestResult(
            id=fl.req.id, tokens=list(fl.generated), ttft_s=ttft,
            tpot_s=tpot, finish_reason=reason, error=error,
            reason=reason_code))

    def _reject(self, req: Request, msg: str, *,
                reason: str = "oversized") -> None:
        ev = self._registry.event("serving_request_error",
                                  request=str(req.id), error=msg,
                                  reason=reason)
        from apex_tpu.telemetry import flight as _flight

        _flight.notify("serving_request_error",
                       error=RuntimeError(msg), fleet=False,
                       extra={"request": str(req.id), "reason": reason,
                              "event": ev})
        self._push_result(RequestResult(
            id=req.id, tokens=[], ttft_s=None, tpot_s=None,
            finish_reason="error", error=msg, reason=reason))

    # -- API -----------------------------------------------------------------

    def _chunk_buckets(self) -> List[int]:
        """The chunk-program seq buckets warmup mints: powers of two
        from the bucket floor up to the full chunk (the final partial
        chunk of a prompt buckets below ``prefill_chunk``)."""
        top = bucket(self.prefill_chunk or self.min_seq_bucket)
        lo = min(self.min_seq_bucket, top)
        out = []
        s = lo
        while s <= top:
            out.append(s)
            s *= 2
        return out

    def warmup(self, state, seq_buckets: Optional[Sequence[int]] = None,
               width_buckets: Optional[Sequence[int]] = None,
               chunk_buckets: Optional[Sequence[int]] = None):
        """Compile the engine's programs off the hot path: the decode
        program per table-width bucket, the prefill programs for
        every admission batch bucket x seq bucket (admissions trickle,
        so batches of 1, 2, ... each mint a program), and the
        chunk-resume programs per batch bucket x chunk bucket (chunked
        prefill + prefix-cache resumes both ride them — pass
        ``chunk_buckets`` covering the resume remainders you expect
        when chunking is off but prefix sharing is on). Every write
        lands in the trash block; returns the threaded cache state.
        Serving latency after warmup never includes an XLA compile —
        and the compile tracker sees zero ``recompile`` events from
        the hot loop (tools/check_serving.sh): chunking adds one
        program per (batch bucket, chunk bucket, width), not a
        storm."""
        import jax

        seqs = sorted(set(seq_buckets or [self.min_seq_bucket]))
        widths = sorted(set(width_buckets or [self.min_width_bucket]))
        chunks = sorted(set(chunk_buckets
                            if chunk_buckets is not None
                            else (self._chunk_buckets()
                                  if self.prefill_chunk else seqs)))
        batches = []
        b = 1
        while b < self.max_prefill_batch:
            batches.append(b)
            b *= 2
        batches.append(bucket(self.max_prefill_batch))
        out = None
        for w in widths:
            out = self.step_fn.decode(
                self.params, state, np.zeros(self.max_batch, np.int32),
                np.zeros(self.max_batch, np.int32),
                np.zeros((self.max_batch, w), np.int32))
            state = out.cache
            for nb in batches:
                for s in seqs:
                    out = self.step_fn.prefill(
                        self.params, state, np.zeros((nb, s), np.int32),
                        np.zeros((nb,), np.int32),
                        np.zeros((nb, w), np.int32))
                    state = out.cache
                for s in chunks:
                    out = self.step_fn.prefill_chunk(
                        self.params, state, np.zeros((nb, s), np.int32),
                        np.zeros((nb,), np.int32),
                        np.zeros((nb,), np.int32),
                        np.zeros((nb, w), np.int32))
                    state = out.cache
        if out is not None:
            jax.block_until_ready(out.next_token)
        return state

    def submit(self, request: Request) -> None:
        """Enqueue one request (thread-safe: clients may submit while
        the engine thread is admitting). A draining engine refuses
        loudly — its snapshot is already committed, so a late request
        must go to the resumed engine, never silently vanish.

        The request plane starts here: with a tracer attached, the
        trace id is minted now (or CONTINUED, when a resumed snapshot
        already set ``request.trace_id`` — the trace then carries a
        ``resumed_from`` mark naming the snapshot)."""
        now = self.clock()
        tr = self.tracer
        if tr is not None and tr.enabled:
            request.trace_id = tr.begin(
                request.id, t_submit=now, trace_id=request.trace_id,
                resumed_from=request.resumed_from)
        if self.draining:
            self._push_result(RequestResult(
                id=request.id, tokens=[], ttft_s=None, tpot_s=None,
                finish_reason="error",
                error="engine draining (preemption): resubmit to the "
                      "resumed engine",
                reason="draining"))
            return
        with self._lock:
            self.queue.append((request, now))

    def take_queued(self, max_n: Optional[int] = None
                    ) -> List[Tuple[Request, float]]:
        """Withdraw up to ``max_n`` queued (NOT yet admitted) requests
        from the tail of the queue — newest first, so the oldest
        arrivals keep their admission order — and return them as
        ``[(request, t_submit)]``. The engine forgets them entirely
        (no result, no trace transition: the caller owns both now).
        The fleet router's bounded-hedge hook: work a stalled engine
        hasn't started can move to a healthy peer; in-flight work
        stays put (serving/fleet.py)."""
        out: List[Tuple[Request, float]] = []
        with self._lock:
            while self.queue and (max_n is None or len(out) < max_n):
                out.append(self.queue.pop())
        return out

    # -- disaggregated handoff hooks (serving/fleet.py) ----------------------

    def take_prefilled(self, max_n: Optional[int] = None
                       ) -> List[_InFlight]:
        """Surrender up to ``max_n`` prefill-COMPLETE in-flight
        sequences (prompt fully cached, first token sampled, decode
        not started here) — the prefill side of a disaggregated
        handoff (serving/fleet.py). The engine forgets each request
        (no result, no trace transition: the caller owns both now)
        but its KV reservation STAYS allocated: the caller must export
        the blocks and then ``cache.free`` the sequence — on success
        AND on failure — or the pool leaks. Engine-thread only, like
        ``step``."""
        out: List[_InFlight] = []
        keep: List[_InFlight] = []
        for f in self.running:
            if ((max_n is None or len(out) < max_n)
                    and f.prefilled >= len(f.req.prompt)
                    and f.generated):
                out.append(f)
            else:
                keep.append(f)
        self.running = keep
        for f in out:
            self._pending_copies.pop(f.seq_id, None)
        return out

    def install_prefilled(self, state, req: Request,
                          generated: Sequence[int], k, v, *,
                          t_submit: float,
                          t_first: Optional[float] = None,
                          t_last: Optional[float] = None):
        """Adopt a handed-off, prefill-complete request: reserve its
        FULL decode span (prompt + max_new — the can-never-die-
        mid-decode invariant holds from the first local step), install
        the already-VERIFIED KV payload into the fresh blocks
        (``KVCache.import_blocks``; verification is the caller's job,
        before this is called), publish the prompt blocks into the
        local prefix index, and join ``running`` directly — no queue,
        no prefill. ``t_submit``/``t_first``/``t_last`` carry the
        SOURCE engine's timestamps so TTFT/TPOT stay end-to-end
        truthful. Raises :class:`PoolExhausted` (reserving nothing)
        when the local pool cannot hold the span; returns the new
        device state. Engine-thread only, like ``step``."""
        total = len(req.prompt) + req.max_new_tokens
        with self._lock:
            self._seq_counter += 1
            seq_id = ("h", self._seq_counter, req.id)
        self.cache.allocate(seq_id, total)
        try:
            state = self.cache.import_blocks(state, seq_id, k, v)
        except Exception:
            self.cache.free(seq_id)
            raise
        fl = _InFlight(req=req, seq_id=seq_id,
                       generated=[int(t) for t in generated],
                       t_submit=t_submit, t_first=t_first,
                       t_last=(t_last if t_last is not None else t_first),
                       prefilled=len(req.prompt))
        self.running.append(fl)
        self.cache.publish_prefix(seq_id, req.prompt)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.decoding(req.id)
        return state

    def idle(self) -> bool:
        with self._lock:
            return (not self.queue and not self.running
                    and not self.prefilling)

    def drain(self) -> List[RequestResult]:
        with self._lock:
            out, self.finished = self.finished, []
        return out

    def introspect(self) -> Dict[str, Any]:
        """One JSON-able snapshot of the live engine — what
        ``tools/serving_top.py`` renders and ``slo_violation`` bundles
        embed: every queued / prefilling / decoding request (state,
        age, deadline headroom, block-table size, chunk progress,
        generated count, trace id), pool + prefix-cache occupancy,
        and the SLO window summary. Host-side reads only — safe to
        call from any thread between (or during) engine steps."""
        now = self.clock()
        with self._lock:
            queued = list(self.queue)
        prefilling = list(self.prefilling)
        running = list(self.running)

        def entry(req: Request, state: str, t_submit: float,
                  fl: Optional[_InFlight] = None) -> Dict[str, Any]:
            age = now - t_submit
            left = (req.deadline_ms - age * 1e3
                    if req.deadline_ms is not None else None)
            out = {"id": str(req.id), "trace_id": req.trace_id,
                   "state": state, "age_s": round(age, 6),
                   "deadline_ms": req.deadline_ms,
                   "deadline_left_ms": (round(left, 3)
                                        if left is not None else None),
                   "prompt_tokens": int(len(req.prompt)),
                   "max_new_tokens": int(req.max_new_tokens),
                   "prefilled": 0, "generated": 0, "blocks": 0}
            if fl is not None:
                out["prefilled"] = int(fl.prefilled)
                out["generated"] = len(fl.generated)
                try:
                    out["blocks"] = len(self.cache.table(fl.seq_id))
                except KeyError:
                    pass
            return out

        requests = ([entry(req, "queued", t) for req, t in queued]
                    + [entry(f.req, "prefilling", f.t_submit, f)
                       for f in prefilling]
                    + [entry(f.req, "decoding", f.t_submit, f)
                       for f in running])
        return {
            "step": self.step_idx,
            "draining": self.draining,
            "queue_depth": len(queued),
            "in_flight": len(running),
            "prefilling": len(prefilling),
            "requests": requests,
            "pool": {
                "num_blocks": self.cache.num_blocks,
                "block_size": self.cache.block_size,
                "blocks_in_use": self.cache.blocks_in_use,
                "free_blocks": self.cache.free_blocks,
                "prefix": self.cache.prefix_stats(),
            },
            "slo": (self.slo.summary()
                    if self.slo is not None else None),
            "traces": (self.tracer.summary()
                       if self.tracer is not None else None),
        }

    # -- resilience plane (serving/resilience.py) ----------------------------

    def _snapshot_entries(self) -> List[Dict[str, Any]]:
        """Every queued + prefilling + in-flight request as JSON-ready
        entries (the drain snapshot payload): prompt, generated-so-far
        tokens, the admission-relevant knobs, and the per-request RNG
        state (sampling knobs + seed — the sampled stream is a pure
        function of ``(seed, token index)``, so the resumed engine
        replays it token for token). Queue order, then prefilling,
        then running — the resumed engine re-admits in the same
        order."""
        def entry(req: Request, generated: List[int],
                  state: str) -> Dict[str, Any]:
            return {"id": req.id,
                    "prompt": [int(t) for t in req.prompt],
                    "max_new_tokens": int(req.max_new_tokens),
                    "eos_id": req.eos_id,
                    "deadline_ms": req.deadline_ms,
                    "temperature": float(req.temperature),
                    "top_k": int(req.top_k),
                    "top_p": float(req.top_p),
                    "seed": int(req.seed),
                    "trace_id": req.trace_id,
                    "generated": generated, "state": state}

        out: List[Dict[str, Any]] = []
        with self._lock:
            queued = list(self.queue)
        for req, _ in queued:
            out.append(entry(req, [], "queued"))
        for f in self.prefilling:
            # no first token yet: the resumed engine re-prefills the
            # whole prompt (chunk progress is cache state, not tokens)
            out.append(entry(f.req, [], "prefilling"))
        for f in self.running:
            out.append(entry(f.req, [int(t) for t in f.generated],
                             "in_flight"))
        return out

    def _stage_params(self, params, info: Dict[str, Any]) -> None:
        """Stage a validated weight swap (``resilience.swap_weights``);
        the engine installs it at the top of its next step — between
        decode dispatches, so no request is dropped."""
        with self._lock:
            self._pending_swap = (params, info)

    def _install_pending_params(self, idx: int) -> None:
        with self._lock:
            pend, self._pending_swap = self._pending_swap, None
        if pend is None:
            return
        params, info = pend
        self.params = params
        r = self._registry
        r.counter("serving_weight_swaps",
                  "live weight hot-swaps installed").inc()
        r.event("serving_weight_swap", step=idx,
                old_digest=info["old_digest"],
                new_digest=info["new_digest"])

    def _reap_deadlines(self, idx: int, now: float) -> List[Any]:
        """Reap every queued + prefilling + in-flight request whose
        TTL elapsed — BEFORE admission, chunking, and decode, so an
        expired request never buys a prefill chunk or a decode slot.
        A mid-``PREFILLING`` reap releases only the request's private
        blocks (shared prefix references are just decremented —
        refcounted free). Returns the reaped ids."""
        def expired(req: Request, t_submit: float) -> bool:
            return (req.deadline_ms is not None
                    and (now - t_submit) * 1000.0 >= req.deadline_ms)

        expired_q: List[Tuple[Request, float]] = []
        with self._lock:
            if any(expired(req, t) for req, t in self.queue):
                keep: "deque[Tuple[Request, float]]" = deque()
                for req, t in self.queue:
                    (expired_q if expired(req, t) else keep).append(
                        (req, t))
                self.queue = keep
        expired_pre = [f for f in self.prefilling
                       if expired(f.req, f.t_submit)]
        expired_run = [f for f in self.running
                       if expired(f.req, f.t_submit)]
        if not expired_q and not expired_run and not expired_pre:
            return []
        r = self._registry
        ids: List[Any] = []
        for req, _ in expired_q:
            r.counter("serving_deadline_exceeded",
                      "requests reaped past their TTL").inc(where="queued")
            self._push_result(RequestResult(
                id=req.id, tokens=[], ttft_s=None, tpot_s=None,
                finish_reason="deadline_exceeded",
                error=f"deadline {req.deadline_ms:g}ms elapsed before "
                      "admission",
                reason="deadline_queued"))
            ids.append(req.id)
        if expired_pre:
            gone = {id(f) for f in expired_pre}
            self.prefilling = [f for f in self.prefilling
                               if id(f) not in gone]
            for f in expired_pre:
                r.counter("serving_deadline_exceeded",
                          "requests reaped past their TTL").inc(
                    where="prefilling")
                self._finish(f, "deadline_exceeded",
                             error=f"deadline {f.req.deadline_ms:g}ms "
                                   "elapsed mid-prefill",
                             reason_code="deadline_prefilling")
                ids.append(f.req.id)
        if expired_run:
            gone = {id(f) for f in expired_run}
            self.running = [f for f in self.running
                            if id(f) not in gone]
            for f in expired_run:
                r.counter("serving_deadline_exceeded",
                          "requests reaped past their TTL").inc(
                    where="in_flight")
                self._finish(f, "deadline_exceeded",
                             error=f"deadline {f.req.deadline_ms:g}ms "
                                   "elapsed mid-decode",
                             reason_code="deadline_in_flight")
                ids.append(f.req.id)
        # flight-safe: the event rides the recorder's ring via the
        # registry sink — no bundle per expiry (deadlines are routine)
        r.event("serving_deadline_exceeded", step=idx,
                requests=[str(i) for i in ids])
        return ids

    def _scrub_pending(self, state):
        """Zero the pool rows of dirty blocks whose refcount reached
        zero since the last step (quarantined tenants of SHARED
        blocks — refcount zero -> scrub -> free list), then hand them
        back to the allocator. Runs at the top of every step, before
        admission can reuse them."""
        from apex_tpu.serving import kv_cache as _kv

        blocks = self.cache.take_pending_scrub()
        if not blocks:
            return state
        state = _kv.scrub_blocks(state, blocks)
        self.cache.scrub_done(blocks)
        self._registry.counter(
            "serving_blocks_scrubbed",
            "dirty blocks zeroed before reuse").inc(len(blocks))
        return state

    def _quarantine(self, state, quarantined, idx: int,
                    report: Dict[str, Any]):
        """Finish the named (flight, reason) pairs with outcome
        ``error`` — blocks scrubbed then freed, counters/events/bundle
        emitted — while the rest of the engine keeps serving. The
        ``serving_quarantine`` trigger replaces the old engine-fatal
        decode-exception path.

        A nonfinite lane APPENDED NaN K/V into its own blocks during
        the dispatch that exposed it; masked attention zeroes masked
        *scores*, not masked V rows (0 x NaN = NaN), so a freed block
        must never hand NaN to its next tenant. Blocks ONLY this
        sequence references (and nobody can match from the prefix
        index) are zeroed right here; its shared/published blocks are
        marked dirty instead — unpublished at once, and scrubbed when
        their refcount reaches zero (``_scrub_pending``)."""
        from apex_tpu.serving import kv_cache as _kv
        from apex_tpu.telemetry import flight as _flight

        excl = sorted({b for f, _ in quarantined
                       for b in self.cache.exclusive_blocks(f.seq_id)})
        state = _kv.scrub_blocks(state, excl)
        r = self._registry
        ids = [str(f.req.id) for f, _ in quarantined]
        reasons = [msg for _, msg in quarantined]
        gone = {id(f) for f, _ in quarantined}
        self.running = [f for f in self.running if id(f) not in gone]
        self.prefilling = [f for f in self.prefilling
                           if id(f) not in gone]
        traced = self.tracer is not None and self.tracer.enabled
        for f, msg in quarantined:
            kind = ("nonfinite" if "nonfinite" in msg else "exception")
            r.counter("serving_quarantined",
                      "sequences quarantined by per-request fault "
                      "isolation").inc(reason=kind)
            if traced:
                self.tracer.mark(f.req.id, "quarantine", self.clock(),
                                 reason=msg, step=idx)
            self._finish(f, "error", error=f"quarantined: {msg}",
                         dirty=True, clean_blocks=excl,
                         reason_code="quarantined")
            report["finished"].append(f.req.id)
        report.setdefault("quarantined", []).extend(
            f.req.id for f, _ in quarantined)
        ev = r.event("serving_quarantine", step=idx, requests=ids,
                     reasons=reasons, in_flight=len(self.running))
        _flight.notify("serving_quarantine", fleet=False,
                       extra={"step": idx, "requests": ids,
                              "reasons": reasons, "event": ev})
        return state

    def _enter_drain(self, idx: int, report: Dict[str, Any]) -> None:
        """Flip to drain mode on a preemption flag: no new admissions
        ever again on this engine. With a ``snapshot_dir``, queued +
        in-flight work persists to an atomic serving snapshot
        (in-flight blocks free; a fresh engine resumes the snapshot);
        without one (or on a failed save), in-flight work keeps
        decoding to completion and the queue errors out loudly —
        either way nothing is silently dropped."""
        from apex_tpu.telemetry import flight as _flight

        self.draining = True
        signum = getattr(self.preemption, "signum", None)
        n_queued = len(self.queue)
        n_running = len(self.running) + len(self.prefilling)
        path = None
        save_error: Optional[str] = None
        if self.snapshot_dir is not None:
            from apex_tpu.serving import resilience as _sresil

            try:
                path = _sresil.save_snapshot(
                    self, self.snapshot_dir, step=idx,
                    reason=f"preemption (signal {signum})")
            except Exception as e:  # noqa: BLE001 — degrade, don't drop
                save_error = f"{type(e).__name__}: {str(e)[:200]}"
        if path is not None:
            self.drained_snapshot = path
            tr = self.tracer
            if tr is not None and tr.enabled:
                # close every snapshotted trace here with outcome
                # `drained`; the resumed engine CONTINUES the same
                # trace id (the snapshot carries it) on its side
                t = self.clock()
                with self._lock:
                    queued_reqs = [req for req, _ in self.queue]
                for req in queued_reqs:
                    tr.drained(req.id, t, snapshot=path)
                for f in self.prefilling + self.running:
                    tr.drained(f.req.id, t, snapshot=path)
            for f in self.running:
                self.cache.free(f.seq_id)
            for f in self.prefilling:
                self.cache.free(f.seq_id)
            self.running = []
            self.prefilling = []
            self._pending_copies.clear()
            with self._lock:
                self.queue.clear()
        else:
            # finish mode: keep decoding the in-flight work; the queue
            # cannot be admitted any more, so fail it loudly
            with self._lock:
                dropped, self.queue = list(self.queue), deque()
            for req, _ in dropped:
                self._reject(req, (
                    "preempted before admission and no drain snapshot "
                    + (f"(save failed: {save_error})" if save_error
                       else "(no snapshot_dir configured)")),
                    reason="draining")
        report["drained"] = True
        report["snapshot"] = path
        r = self._registry
        r.counter("serving_drains", "preemption drains entered").inc(
            mode="snapshot" if path is not None else "finish")
        ev = r.event("serving_drain", step=idx, signum=signum,
                     snapshot=path, save_error=save_error,
                     queued=n_queued, in_flight=n_running)
        _flight.notify("serving_drain", fleet=False,
                       extra={"step": idx, "snapshot": path,
                              "save_error": save_error,
                              "queued": n_queued,
                              "in_flight": n_running, "event": ev})

    # -- one engine step -----------------------------------------------------

    def _admit(self, exhausted: bool) -> Tuple[List[_InFlight],
                                               List[_InFlight]]:
        """Pop queued requests into the engine; returns ``(direct,
        chunked)`` — ``direct`` prefills monolithically this step (a
        fresh short prompt: the pre-chunking program, bitwise
        unchanged), ``chunked`` enters ``PREFILLING`` (a long prompt
        under chunked prefill, or any prefix-cache resume).

        Reservation is prefix-aware and staged: matched prefix blocks
        are taken by REFERENCE (``serving_prefix_cache_hits``), and a
        chunked admission reserves only its first chunk's private
        blocks — ``_prefill_chunks`` extends the reservation chunk by
        chunk, taking the decode span with the final chunk."""
        if self.draining:
            return [], []                    # drain mode: queue frozen
        if self.slo is not None and self.slo.should_shed():
            # a latched burn-rate alert (telemetry/slo.py) sheds load
            # exactly like an exhausted pool: requests stay queued,
            # in-flight decodes keep running, admission resumes when
            # the short window recovers (only passes with work queued
            # count as shed)
            if self.queue:
                self._registry.counter(
                    "serving_slo_shed",
                    "admission passes shed by a latched SLO "
                    "burn-rate alert").inc()
                if not self._shed_active:
                    self._shed_active = True
                    self._registry.event("serving_slo_shed",
                                         slos=self.slo.alerting(),
                                         queued=len(self.queue))
            return [], []
        self._shed_active = False
        if any(f.stalls > 0 for f in self.prefilling):
            # a PREFILLING sequence is waiting on blocks: admitting new
            # work would steal the blocks it needs (and, after a
            # deadlock-breaking requeue, ping-pong the pool between the
            # two forever) — in-progress prompts drain first
            self._registry.counter(
                "serving_admission_deferred",
                "admissions deferred by a transiently full pool").inc()
            return [], []
        direct: List[_InFlight] = []
        chunked: List[_InFlight] = []
        rejects: List[Tuple[Request, str]] = []
        hits: List[int] = []
        deferred = False
        chunk = self.prefill_chunk
        # queue pop + pool reservation under ONE lock: a submit() on a
        # client thread can never interleave with the reservation
        with self._lock:
            while (self.queue
                   and (len(self.running) + len(self.prefilling)
                        + len(direct) + len(chunked) < self.max_batch)
                   and len(direct) + len(chunked) < self.max_prefill_batch):
                req, t_submit = self.queue[0]
                total = len(req.prompt) + req.max_new_tokens
                need = self.cache.blocks_for(total)
                if need > self.cache.num_blocks:
                    self.queue.popleft()
                    rejects.append((req, (
                        f"request needs {need} KV blocks, pool capacity "
                        f"is {self.cache.num_blocks} — can never be "
                        "admitted")))
                    continue
                if exhausted:
                    break                    # shed load: stay queued
                try:
                    self._seq_counter += 1
                    seq_id = ("s", self._seq_counter, req.id)
                    match = self.cache.allocate_prefix(
                        seq_id, req.prompt, total_len=total,
                        chunk=chunk)
                except PoolExhausted:
                    self._seq_counter -= 1
                    deferred = True
                    break                    # wait for blocks to free
                self.queue.popleft()
                fl = _InFlight(req=req, seq_id=seq_id, generated=[],
                               t_submit=t_submit,
                               prefilled=match.matched)
                hits.append(1 if match.matched > 0 else 0)
                if match.copies:
                    self._pending_copies[seq_id] = list(match.copies)
                if (match.matched == 0
                        and (chunk is None or len(req.prompt) <= chunk)):
                    direct.append(fl)
                else:
                    chunked.append(fl)
        if deferred:
            self._registry.counter(
                "serving_admission_deferred",
                "admissions deferred by a transiently full pool").inc()
        if hits:
            c = self._registry.counter(
                "serving_prefix_cache_hits",
                "admissions by prompt-prefix cache outcome")
            n_hit = sum(hits)
            if n_hit:
                c.inc(n_hit, outcome="hit")
            if len(hits) - n_hit:
                c.inc(len(hits) - n_hit, outcome="miss")
        for req, msg in rejects:
            self._reject(req, msg)
        tr = self.tracer
        if tr is not None and tr.enabled and (direct or chunked):
            now = self.clock()
            for fl in direct:
                tr.admitted(fl.req.id, now, mode="direct",
                            matched=fl.prefilled)
            for fl in chunked:
                tr.admitted(fl.req.id, now, mode="chunked",
                            matched=fl.prefilled)
        return direct, chunked

    def _tables_for(self, flights: List[_InFlight], batch: int):
        widths = [len(self.cache.table(f.seq_id)) for f in flights]
        w = bucket(max(widths), self.min_width_bucket)
        return self.cache.table_array([f.seq_id for f in flights], w,
                                      batch=batch)

    def _sampling_for(self, flights: List[_InFlight], batch: int):
        """Per-lane sampling arrays (temps, top_ks, top_ps, seeds) for
        a padded batch — dummy lanes are greedy (temperature 0), so an
        all-greedy workload takes the in-program fast path."""
        temps = np.zeros(batch, np.float32)
        ks = np.zeros(batch, np.int32)
        ps = np.ones(batch, np.float32)
        seeds = np.zeros(batch, np.uint32)
        for i, f in enumerate(flights):
            temps[i] = f.req.temperature
            ks[i] = f.req.top_k
            ps[i] = f.req.top_p
            seeds[i] = np.uint32(f.req.seed & 0xFFFFFFFF)
        return temps, ks, ps, seeds

    def _prefill(self, admitted: List[_InFlight], state):
        """Prefill the admissions as one bucketed batch; returns
        ``(cache_state, finite)`` where ``finite[i]`` is the in-jit
        all-finite flag of lane ``i``'s first-token logits. Only
        finite lanes get their first token recorded — a nonfinite lane
        is quarantined by the caller before it joins ``running``."""
        import jax

        b = bucket(len(admitted))
        s = bucket(max(len(f.req.prompt) for f in admitted),
                   self.min_seq_bucket)
        tokens = np.zeros((b, s), np.int32)
        lengths = np.zeros((b,), np.int32)
        for i, f in enumerate(admitted):
            tokens[i, :len(f.req.prompt)] = f.req.prompt
            lengths[i] = len(f.req.prompt)
        tables = self._tables_for(admitted, b)
        t0 = self.clock()
        with self._tl().phase("prefill", category="serving"):
            out = self.step_fn.prefill(
                self.params, state, tokens, lengths, tables,
                sampling=self._sampling_for(admitted, b))
            jax.block_until_ready(out.next_token)
        now = self.clock()
        ids = np.asarray(out.next_token)
        finite = (np.asarray(out.finite)[:len(admitted)]
                  if out.finite is not None
                  else np.ones(len(admitted), bool))
        tr = self.tracer
        traced = tr is not None and tr.enabled
        for i, f in enumerate(admitted):
            if traced:
                tr.span(f.req.id, "prefill", t0, now - t0,
                        tokens=len(f.req.prompt))
            if finite[i]:
                f.generated.append(int(ids[i]))
                f.prefilled = len(f.req.prompt)
                f.t_first = f.t_last = now
                self.cache.publish_prefix(f.seq_id, f.req.prompt)
                if traced:
                    tr.mark(f.req.id, "first_token", now)
        return out.cache, finite

    # -- chunked prefill (the PREFILLING state) ------------------------------

    def _chunk_batch(self, state, batchees, cidx: int, b: int, s: int,
                     width: int):
        """ONE chunk-prefill dispatch over ``batchees`` = [(flight,
        chunk_len)], padded to the top-level (b, s, width) so
        binary-split retries reuse the same compiled program; returns
        ``(cache_state, token_ids, finite, now)``. The fault sites
        live here: ``prefill_chunk_exception=<idx>`` checks the
        TOP-LEVEL dispatch index ``cidx`` (retries re-check the same
        index, so the clause fails every sub-dispatch — the whole
        batch quarantines), ``io:prefill_chunk`` counts calls (one
        transient index is absorbed by the retry)."""
        import jax

        from apex_tpu.resilience import faults

        tokens = np.zeros((b, s), np.int32)
        starts = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        for i, (f, cs) in enumerate(batchees):
            tokens[i, :cs] = f.req.prompt[f.prefilled:f.prefilled + cs]
            starts[i] = f.prefilled
            lengths[i] = cs
        tables = self.cache.table_array(
            [f.seq_id for f, _ in batchees], width, batch=b)
        with self._tl().phase("prefill_chunk", category="serving"):
            faults.maybe_prefill_chunk_exception(cidx)
            faults.check("prefill_chunk")
            out = self.step_fn.prefill_chunk(
                self.params, state, tokens, starts, lengths, tables,
                sampling=self._sampling_for([f for f, _ in batchees], b))
            jax.block_until_ready(out.next_token)
        now = self.clock()
        ids = np.asarray(out.next_token)
        finite = (np.asarray(out.finite)[:len(batchees)]
                  if out.finite is not None
                  else np.ones(len(batchees), bool))
        return out.cache, ids, finite, now

    def _isolate_chunks(self, state, batchees, cidx: int, b: int,
                        s: int, width: int):
        """Chunk-prefill ``batchees`` with per-request fault isolation
        (the decode ``_isolate`` idiom on the chunk dispatch); returns
        ``(state, done, quarantined)`` — ``done`` is ``[(flight,
        chunk_len, token, t)]``, ``quarantined`` ``[(flight, msg)]``."""
        try:
            state, ids, finite, now = self._chunk_batch(
                state, batchees, cidx, b, s, width)
        except Exception as e:  # noqa: BLE001 — isolate, keep serving
            if len(batchees) == 1:
                msg = f"{type(e).__name__}: {str(e)[:200]}"
                return state, [], [(batchees[0][0], msg)]
            if self.tracer is not None and self.tracer.enabled:
                t = self.clock()
                for f, _ in batchees:
                    self.tracer.mark(f.req.id, "retry_split", t,
                                     batch=len(batchees),
                                     site="prefill_chunk")
            mid = len(batchees) // 2
            state, d_lo, q_lo = self._isolate_chunks(
                state, batchees[:mid], cidx, b, s, width)
            state, d_hi, q_hi = self._isolate_chunks(
                state, batchees[mid:], cidx, b, s, width)
            return state, d_lo + d_hi, q_lo + q_hi
        done, quarantined = [], []
        for i, (f, cs) in enumerate(batchees):
            if finite[i]:
                done.append((f, cs, int(ids[i]), now))
            else:
                quarantined.append((f, "nonfinite logits (prefill chunk)"))
        return state, done, quarantined

    def _prefill_chunks(self, state, idx: int, report: Dict[str, Any]):
        """Advance the PREFILLING sequences by one bucketed chunk each
        under the per-step token budget, co-scheduled with the step's
        decode dispatch (chunked prefill — the reason a 4k-token
        prompt cannot stall in-flight decodes). Reservation is staged:
        each chunk extends the block table just-in-time, and the FINAL
        chunk reserves the decode span (prompt + max_new), restoring
        the can-never-die-mid-decode invariant at the PREFILLING ->
        DECODING transition. A sequence that cannot extend stalls in
        place (``serving_prefill_stalled``); if nothing else is
        running or prefilling — nothing will ever free blocks — the
        head stalled sequence is requeued
        (``serving_prefill_requeued``) so the engine cannot
        deadlock."""
        from apex_tpu.serving import kv_cache as _kv

        if (self.prefill_interval > 1 and self.running
                and idx % self.prefill_interval):
            return state          # this step is decode-only (knob doc)
        chunk = self.prefill_chunk
        budget = self.prefill_token_budget
        r = self._registry
        batchees: List[Tuple[_InFlight, int]] = []
        stalled: List[_InFlight] = []
        used = 0
        for f in self.prefilling:
            if len(batchees) >= self.max_prefill_batch:
                break
            rem = len(f.req.prompt) - f.prefilled
            cs = rem if chunk is None else min(rem, chunk)
            if budget is not None and batchees and used + cs > budget:
                break
            final = f.prefilled + cs >= len(f.req.prompt)
            target = (len(f.req.prompt) + f.req.max_new_tokens
                      if final else f.prefilled + cs)
            try:
                self.cache.extend(f.seq_id, target)
            except PoolExhausted:
                f.stalls += 1
                stalled.append(f)
                r.counter("serving_prefill_stalled",
                          "chunk reservations deferred by a full "
                          "pool").inc()
                if (f.stalls == 1 and self.tracer is not None
                        and self.tracer.enabled):
                    self.tracer.mark(f.req.id, "prefill_stalled",
                                     prefilled=f.prefilled)
                continue
            f.stalls = 0
            batchees.append((f, cs))
            used += cs
        if not batchees:
            if stalled and not self.running:
                # nothing decodes, nothing prefills: no block will
                # ever free — requeue the head stalled sequence
                f = stalled[0]
                self.prefilling.remove(f)
                self._pending_copies.pop(f.seq_id, None)
                self.cache.free(f.seq_id)
                with self._lock:
                    self.queue.appendleft((f.req, f.t_submit))
                r.counter("serving_prefill_requeued",
                          "prefilling sequences returned to the queue "
                          "to break a reservation deadlock").inc()
                r.event("serving_prefill_requeued", step=idx,
                        request=str(f.req.id), prefilled=f.prefilled)
                if self.tracer is not None and self.tracer.enabled:
                    self.tracer.requeued(f.req.id, self.clock())
            return state
        # execute pending COW fork copies before the chunk gathers
        copies: List[Tuple[int, int, int]] = []
        for f, _ in batchees:
            c = self._pending_copies.pop(f.seq_id, None)
            if c:
                copies.extend(c)
        if copies:
            state = _kv.apply_copies(state, copies)
            for f, _ in batchees:
                self.cache.fork_copied(f.seq_id)
        cidx = self._chunk_dispatches
        self._chunk_dispatches += 1
        b = bucket(len(batchees))
        floor = min(self.min_seq_bucket,
                    bucket(chunk) if chunk else self.min_seq_bucket)
        s = bucket(max(cs for _, cs in batchees), floor)
        widths = [len(self.cache.table(f.seq_id)) for f, _ in batchees]
        width = bucket(max(widths), self.min_width_bucket)
        t0 = self.clock()
        state, done, quarantined = self._isolate_chunks(
            state, batchees, cidx, b, s, width)
        t1 = self.clock()
        tr = self.tracer
        traced = tr is not None and tr.enabled
        now_done: List[_InFlight] = []
        for f, cs, tok, now in done:
            f.prefilled += cs
            r.counter("serving_prefill_chunks",
                      "prefill chunks dispatched").inc()
            r.histogram("serving_prefill_chunk_tokens",
                        "prompt tokens per prefill chunk",
                        buckets=TOKEN_COUNT_BUCKETS).observe(cs)
            report.setdefault("prefilled", []).append(f.req.id)
            if traced:
                tr.chunk_span(f.req.id, t0, t1 - t0, tokens=cs)
            if f.prefilled >= len(f.req.prompt):
                f.generated.append(tok)
                f.t_first = f.t_last = now
                now_done.append(f)
                self.cache.publish_prefix(f.seq_id, f.req.prompt)
                if traced:
                    tr.mark(f.req.id, "first_token", now)
                    tr.decoding(f.req.id)
        if now_done:
            gone = {id(f) for f in now_done}
            self.prefilling = [f for f in self.prefilling
                               if id(f) not in gone]
            self.running.extend(now_done)
        if quarantined:
            state = self._quarantine(state, quarantined, idx, report)
        return state

    def _decode_batch(self, state, flights: List[_InFlight], idx: int,
                      width: int):
        """ONE decode dispatch over ``flights`` (padded to
        ``max_batch`` x the step's shared ``width`` bucket, so
        binary-split retries reuse the very same compiled program);
        returns ``(cache_state, token_ids, finite, now)``. The fault
        sites live here, so the split retries re-traverse them —
        step-indexed clauses fail every sub-dispatch, call-indexed
        ``io:decode_step`` faults are absorbed by the retry."""
        import jax

        from apex_tpu.resilience import faults

        b = self.max_batch          # fixed: one program per width bucket
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        for i, f in enumerate(flights):
            tokens[i] = f.generated[-1]
            positions[i] = f.position
        tables = self.cache.table_array([f.seq_id for f in flights],
                                        width, batch=b)
        with self._tl().phase("decode", category="serving"):
            faults.maybe_decode_exception(idx)
            faults.check("decode_step")
            out = self.step_fn.decode(
                self.params, state, tokens, positions, tables,
                sampling=self._sampling_for(flights, b))
            jax.block_until_ready(out.next_token)
        now = self.clock()
        ids = np.asarray(out.next_token)
        finite = (np.asarray(out.finite)[:len(flights)]
                  if out.finite is not None
                  else np.ones(len(flights), bool))
        return out.cache, ids, finite, now

    def _isolate(self, state, flights: List[_InFlight], idx: int,
                 width: int):
        """Decode ``flights`` with per-request fault isolation; returns
        ``(state, accepted, quarantined)`` — ``accepted`` is
        ``[(flight, token, t)]``, ``quarantined`` ``[(flight, msg)]``.

        A dispatch exception triggers the binary split (the watchdog's
        localization idiom on the batch axis): each half retries as its
        own dispatch — the fault sites raise BEFORE the jitted call, so
        the donated cache state is still live — and offenders bottom
        out as singletons. Nonfinite logits need no split: the in-jit
        per-lane finite flag names them directly."""
        try:
            state, ids, finite, now = self._decode_batch(
                state, flights, idx, width)
        except Exception as e:  # noqa: BLE001 — isolate, keep serving
            if len(flights) == 1:
                msg = f"{type(e).__name__}: {str(e)[:200]}"
                return state, [], [(flights[0], msg)]
            if self.tracer is not None and self.tracer.enabled:
                t = self.clock()
                for f in flights:
                    self.tracer.mark(f.req.id, "retry_split", t,
                                     batch=len(flights), site="decode")
            mid = len(flights) // 2
            state, acc_lo, q_lo = self._isolate(state, flights[:mid],
                                                idx, width)
            state, acc_hi, q_hi = self._isolate(state, flights[mid:],
                                                idx, width)
            return state, acc_lo + acc_hi, q_lo + q_hi
        accepted, quarantined = [], []
        for i, f in enumerate(flights):
            if finite[i]:
                accepted.append((f, int(ids[i]), now))
            else:
                quarantined.append((f, "nonfinite logits"))
        return state, accepted, quarantined

    def _reap(self) -> List[Any]:
        done, keep = [], []
        for f in self.running:
            if (f.req.eos_id is not None
                    and f.generated[-1] == f.req.eos_id):
                self._finish(f, "eos")
                done.append(f.req.id)
            elif len(f.generated) >= f.req.max_new_tokens:
                self._finish(f, "length")
                done.append(f.req.id)
            else:
                keep.append(f)
        self.running = keep
        return done

    def step(self, state) -> Tuple[Any, Dict[str, Any]]:
        """One engine iteration over the donated cache ``state``;
        returns ``(new_state, report)`` — the report (admitted /
        decoded / finished ids, blocks in use, plus the resilience
        keys ``expired`` / ``quarantined`` / ``drained`` /
        ``snapshot``) is the golden-sequence surface tests assert
        against.

        Ordering is the resilience contract: staged weight swaps
        install FIRST (the step boundary between decode dispatches),
        deadline-expired requests reap BEFORE admission, chunking,
        and decode, the preemption flag is drained before any new
        work starts, pending block scrubs land before admission can
        reuse the blocks, and both the decode and the chunk-prefill
        dispatch run under per-request fault isolation."""
        from apex_tpu.resilience import faults
        from apex_tpu.telemetry import flight as _flight

        idx = self.step_idx
        self.step_idx += 1
        self._install_pending_params(idx)
        faults.maybe_sigterm(idx)       # the preemption drill site
        report: Dict[str, Any] = {
            "step": idx,
            "admitted": [],
            "prefilled": [],
            "decoded": [],
            "finished": [],
            "expired": self._reap_deadlines(idx, self.clock()),
        }
        if (not self.draining and self.preemption is not None
                and self.preemption.should_stop()):
            self._enter_drain(idx, report)
            if self.drained_snapshot is not None:
                # snapshot mode: queued + in-flight are persisted, the
                # engine is done — nothing left to prefill or decode
                report["queued"] = 0
                report["blocks_in_use"] = self.cache.blocks_in_use
                self._publish_gauges()
                return state, report
        state = self._scrub_pending(state)
        exhausted = faults.should_pool_exhaust(idx)
        if exhausted:
            self._registry.event("serving_pool_exhausted", step=idx,
                                 injected=True,
                                 queued=len(self.queue),
                                 in_flight=len(self.running))
            if not self._pool_exhausted_dumped:
                self._pool_exhausted_dumped = True
                _flight.notify(
                    "serving_pool_exhausted", fleet=False,
                    extra={"step": idx, "queued": len(self.queue),
                           "blocks_in_use": self.cache.blocks_in_use,
                           "prefix_cache": self.cache.prefix_stats()})
        direct, chunked = self._admit(exhausted)
        report["admitted"] = [f.req.id for f in direct + chunked]
        report["queued"] = len(self.queue)
        self.prefilling.extend(chunked)
        if direct:
            state, finite = self._prefill(direct, state)
            good = [f for i, f in enumerate(direct) if finite[i]]
            bad = [(f, "nonfinite logits (prefill)")
                   for i, f in enumerate(direct) if not finite[i]]
            self.running.extend(good)
            if bad:
                state = self._quarantine(state, bad, idx, report)
        if self.prefilling:
            # one bucketed chunk per sequence, budget-bounded — the
            # step's decode dispatch below runs either way (chunked
            # prefill's co-scheduling contract)
            state = self._prefill_chunks(state, idx, report)
        # reap BEFORE decoding: a request whose prefill token already
        # hit max_new/EOS must not buy a decode slot
        report["finished"].extend(self._reap())
        if self.running:
            widths = [len(self.cache.table(f.seq_id))
                      for f in self.running]
            width = bucket(max(widths), self.min_width_bucket)
            lane = faults.nonfinite_lane_at(idx)
            if lane is not None and lane < len(self.running):
                from apex_tpu.serving import resilience as _sresil

                f = self.running[lane]
                state = _sresil.poison_lane_kv(
                    state, self.cache, f.seq_id, f.position - 1)
            t0 = self.clock()
            state, accepted, quarantined = self._isolate(
                state, self.running, idx, width)
            tr = self.tracer
            traced = tr is not None and tr.enabled
            for f, tok, now in accepted:
                f.generated.append(tok)
                f.t_last = now
                if traced:
                    tr.decode_tick(f.req.id, t0, now)
            report["decoded"] = [f.req.id for f, _, _ in accepted]
            if quarantined:
                state = self._quarantine(state, quarantined, idx,
                                         report)
        report["finished"].extend(self._reap())
        report["blocks_in_use"] = self.cache.blocks_in_use
        self._publish_gauges()
        if self.slo is not None:
            now = self.clock()
            self.slo.observe("queue_depth", float(report["queued"]),
                             t=now)
            self.slo.tick(now=now, step=idx)
        return state, report


def serve_loop(batcher: ContinuousBatcher, state, requests:
               Sequence[Request], *,
               arrivals: Optional[Sequence[float]] = None,
               clock: Callable[[], float] = time.perf_counter,
               sleep: Callable[[float], None] = time.sleep):
    """Drive ``batcher`` over an arrival schedule until every request
    finishes; returns ``(final_cache_state, results)``.

    ``arrivals`` are seconds offsets from loop start (default: all at
    t=0). Submissions happen when the wall clock passes each offset —
    the serving bench's Poisson schedule goes through here.

    A draining engine ends the loop early: once the batcher flags
    ``draining`` (preemption), un-submitted arrivals stay with the
    caller and the loop returns as soon as the in-flight work is
    finished or snapshotted (``batcher.drained_snapshot`` names the
    snapshot a fresh engine resumes from).
    """
    order = sorted(range(len(requests)),
                   key=lambda i: arrivals[i] if arrivals else 0.0)
    t0 = clock()
    results: List[RequestResult] = []
    i = 0
    while i < len(order) or not batcher.idle():
        if (batcher.draining and not batcher.running
                and not batcher.prefilling):
            break
        now = clock() - t0
        while (i < len(order) and not batcher.draining
               and (not arrivals or arrivals[order[i]] <= now)):
            batcher.submit(requests[order[i]])
            i += 1
        if batcher.idle():
            if batcher.draining:
                break
            if i < len(order):
                sleep(max(0.0, min(arrivals[order[i]] - now, 0.001)))
            continue
        state, _ = batcher.step(state)
        results.extend(batcher.drain())
    results.extend(batcher.drain())
    return state, results


def static_batch_generate(model, params, cache: KVCache, state,
                          requests: Sequence[Request], *,
                          batch_size: int = 8,
                          arrivals: Optional[Sequence[float]] = None,
                          clock: Callable[[], float] = time.perf_counter,
                          sleep: Callable[[float], None] = time.sleep,
                          step_fn: Optional[DecodeStep] = None,
                          min_seq_bucket: int = 16,
                          min_width_bucket: int = 4):
    """The naive baseline the serving bench compares against: fixed
    batches in arrival order, each run to the SLOWEST member's last
    token before the next batch starts — late arrivals wait behind the
    barrier, early finishers idle inside it. Same jitted steps, same
    cache machinery; only the scheduling differs. Returns
    ``(final_cache_state, results)``.
    """
    import jax

    step = step_fn if step_fn is not None else make_decode_step(model,
                                                                cache)
    t0 = clock()
    results: List[RequestResult] = []
    pending = list(requests)
    submit_at = list(arrivals) if arrivals else [0.0] * len(pending)
    pos = 0
    while pos < len(pending):
        batch = pending[pos:pos + batch_size]
        t_sub = submit_at[pos:pos + batch_size]
        pos += len(batch)
        # the static server cannot start until every member has arrived
        wait = max(t_sub) - (clock() - t0)
        if wait > 0:
            sleep(wait)
        seqs = []
        for j, req in enumerate(batch):
            sid = ("static", pos, j)
            cache.allocate(sid, len(req.prompt) + req.max_new_tokens)
            seqs.append(sid)
        b = bucket(len(batch))
        s = bucket(max(len(r.prompt) for r in batch), min_seq_bucket)
        w = bucket(max(len(cache.table(sid)) for sid in seqs),
                   min_width_bucket)
        tokens = np.zeros((b, s), np.int32)
        lengths = np.zeros((b,), np.int32)
        for j, req in enumerate(batch):
            tokens[j, :len(req.prompt)] = req.prompt
            lengths[j] = len(req.prompt)
        tables = cache.table_array(seqs, w, batch=b)
        out = step.prefill(params, state, tokens, lengths, tables)
        jax.block_until_ready(out.next_token)
        now = clock()
        state = out.cache
        gen = [[int(t)] for t in np.asarray(out.next_token)[:len(batch)]]
        t_first = [now] * len(batch)
        t_last = [now] * len(batch)
        # decode until the SLOWEST member is done (no early slot reuse)
        rounds = max(r.max_new_tokens for r in batch) - 1
        for _ in range(rounds):
            toks = np.zeros((b,), np.int32)
            poss = np.zeros((b,), np.int32)
            for j, req in enumerate(batch):
                toks[j] = gen[j][-1]
                poss[j] = len(req.prompt) + len(gen[j]) - 1
            out = step.decode(params, state, toks, poss, tables)
            jax.block_until_ready(out.next_token)
            now = clock()
            state = out.cache
            ids = np.asarray(out.next_token)
            for j, req in enumerate(batch):
                if len(gen[j]) < req.max_new_tokens:
                    gen[j].append(int(ids[j]))
                    t_last[j] = now
        for j, req in enumerate(batch):
            n = len(gen[j])
            ttft = t_first[j] - (t0 + t_sub[j])
            tpot = ((t_last[j] - t_first[j]) / (n - 1)) if n > 1 else None
            results.append(RequestResult(
                id=req.id, tokens=gen[j], ttft_s=ttft, tpot_s=tpot,
                finish_reason="length"))
            cache.free(seqs[j])
    return state, results


__all__ = [
    "ContinuousBatcher",
    "Request",
    "RequestResult",
    "serve_loop",
    "static_batch_generate",
]

"""Paged KV cache: fixed-size blocks over one preallocated pool.

The serving tier's memory subsystem (ROADMAP item 1; the vLLM
PagedAttention layout re-expressed for this stack): instead of one
contiguous ``(batch, max_seq_len)`` KV buffer per sequence — whose
reallocation/copy on every growth step is exactly the churn the
donation-aware train step was built to kill — every layer's K and V
live in ONE preallocated pool of fixed-size blocks,

    pool: (num_layers, num_blocks, block_size, kv_heads, head_dim)

and each sequence owns an ordered *block table* (a list of pool block
indices). Appending a token writes one ``(kv_heads, head_dim)`` row at
``(table[pos // block_size], pos % block_size)``; reading gathers the
table back into a contiguous ``(kv_heads, padded_len, head_dim)`` view
for attention. Neither path ever reallocates the pool — the device
arrays are created once and donated through every decode step.

GQA pays GQA-sized blocks: the pool is sized from the model's
``kv_heads`` (``GPTConfig.kv_heads``), not ``num_heads``, so a 4x
grouped-query model holds 4x the sequences in the same HBM.

Block 0 is the **trash block**: writes from padded batch slots or
padded prompt tails land there (index clamping instead of predication
keeps the scatter shape static), and unallocated block-table entries
point at it so a short table gathers garbage that the attention mask
then drops. No real sequence is ever given block 0.

The allocator is host-side Python (the scheduler's admission control
runs on the host between steps); only :func:`gather_kv` /
:func:`append_kv` / :func:`append_kv_prefill` trace into jitted
programs. Allocation reserves the FULL block span a request can reach
(prompt + max_new_tokens) up front, so an admitted request can never
die of pool exhaustion mid-decode — admission control is the one gate
(docs/serving.md "admission control").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Admission refused: the pool cannot reserve the requested span.

    Carries ``needed`` / ``free`` block counts so the scheduler can
    tell a transient full pool (wait) from an impossible request
    (``needed > capacity``: reject)."""

    def __init__(self, msg: str, *, needed: int, free: int, capacity: int):
        super().__init__(msg)
        self.needed = int(needed)
        self.free = int(free)
        self.capacity = int(capacity)


class KVCacheState(NamedTuple):
    """The device-side pools — a pytree the decode step DONATES, so
    appends run in place and the cache never holds two copies."""

    k: Any    # (num_layers, num_blocks, block_size, kv_heads, head_dim)
    v: Any


class KVCache:
    """Block allocator + pool factory for one model's KV cache.

    ``num_blocks`` counts usable blocks *excluding* the trash block
    (the pool array holds ``num_blocks + 1``).

    Thread-safety contract: every ALLOCATOR method (allocate / free /
    table / table_array / can_admit and the counters) takes this
    cache's internal lock, so a client thread calling
    ``ContinuousBatcher.submit()`` and the engine thread admitting,
    finishing, or draining can interleave freely. The device POOLS
    (``init_state()``'s arrays) are not covered: they are owned by the
    engine thread and donated through each prefill/decode dispatch —
    nothing else may touch them mid-step.
    """

    def __init__(self, num_layers: int, kv_heads: int, head_dim: int, *,
                 num_blocks: int, block_size: int = 16,
                 dtype: Any = None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype if dtype is not None else jnp.float32
        self._lock = threading.Lock()
        # LIFO free list: a freed sequence's blocks are the next handed
        # out (reuse-after-free is the common case under steady load,
        # and LIFO keeps the hot blocks hot)
        self._free: List[int] = list(range(self.num_blocks, 0, -1))
        self._tables: Dict[Any, List[int]] = {}

    @classmethod
    def for_config(cls, cfg, *, num_blocks: int, block_size: int = 16,
                   dtype: Any = None) -> "KVCache":
        """Size the cache from a ``GPTConfig``-shaped model config:
        ``kv_heads`` (the GQA-narrowed count) x ``head_dim`` blocks —
        GQA pays GQA-sized blocks, never ``num_heads``-sized ones."""
        return cls(cfg.num_layers, cfg.kv_heads,
                   cfg.hidden_size // cfg.num_heads,
                   num_blocks=num_blocks, block_size=block_size,
                   dtype=dtype if dtype is not None else cfg.dtype)

    # -- pool ---------------------------------------------------------------

    def init_state(self) -> KVCacheState:
        """Allocate the pools (once; +1 block for the trash block)."""
        import jax.numpy as jnp

        shape = (self.num_layers, self.num_blocks + 1, self.block_size,
                 self.kv_heads, self.head_dim)
        return KVCacheState(k=jnp.zeros(shape, self.dtype),
                            v=jnp.zeros(shape, self.dtype))

    def pool_bytes(self) -> int:
        import jax.numpy as jnp

        n = (self.num_layers * (self.num_blocks + 1) * self.block_size
             * self.kv_heads * self.head_dim)
        return 2 * n * jnp.dtype(self.dtype).itemsize

    # -- allocator ----------------------------------------------------------

    def blocks_for(self, total_len: int) -> int:
        """Blocks a sequence of ``total_len`` tokens occupies."""
        return -(-max(int(total_len), 1) // self.block_size)

    def can_admit(self, total_len: int) -> bool:
        with self._lock:
            return self.blocks_for(total_len) <= len(self._free)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free)

    def allocate(self, seq_id, total_len: int) -> List[int]:
        """Reserve the full block span for a sequence reaching
        ``total_len`` tokens; raises :class:`PoolExhausted` when the
        free list can't cover it (the admission-control refusal)."""
        need = self.blocks_for(total_len)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            if need > len(self._free):
                raise PoolExhausted(
                    f"kv pool exhausted: sequence {seq_id!r} needs {need} "
                    f"blocks, {len(self._free)} free of {self.num_blocks}",
                    needed=need, free=len(self._free),
                    capacity=self.num_blocks)
            blocks = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = blocks
            return list(blocks)

    def free(self, seq_id) -> int:
        """Return a sequence's blocks to the pool; returns how many."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            if blocks is None:
                return 0
            self._free.extend(reversed(blocks))
            return len(blocks)

    def table(self, seq_id) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    @property
    def sequences(self) -> List[Any]:
        with self._lock:
            return list(self._tables)

    def table_array(self, seq_ids: Sequence[Any], width: int,
                    batch: Optional[int] = None) -> np.ndarray:
        """The batch's block tables as one right-padded ``(batch,
        width)`` int32 array — padding (and dummy batch rows past
        ``len(seq_ids)``) points at the trash block."""
        b = len(seq_ids) if batch is None else int(batch)
        out = np.full((b, int(width)), TRASH_BLOCK, np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                t = self._tables[sid]
                if len(t) > width:
                    raise ValueError(
                        f"table width {width} < {len(t)} blocks of "
                        f"sequence {sid!r}")
                out[i, :len(t)] = t
        return out


def bucket(n: int, minimum: int = 1) -> int:
    """Next power of two >= max(n, minimum) — the shape-bucketing that
    bounds the decode compile count (docs/serving.md)."""
    b = max(int(minimum), 1)
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Traced pool ops (what the jitted prefill/decode programs call)
# ---------------------------------------------------------------------------


def gather_kv(state: KVCacheState, tables):
    """Gather each sequence's blocks into contiguous per-batch views.

    ``tables`` (batch, width) int32 -> two ``(num_layers, batch,
    kv_heads, width * block_size, head_dim)`` arrays. Pure data
    movement — the bytes written by :func:`append_kv` come back
    bitwise (tests/test_serving.py pins it). Unallocated table entries
    gather the trash block; the caller's attention mask drops them.
    """
    def one(pool):
        g = pool[:, tables]            # (L, b, w, bs, kv, d)
        layers, b, w, bs, kv, d = g.shape
        return g.transpose(0, 1, 4, 2, 3, 5).reshape(layers, b, kv,
                                                     w * bs, d)
    return one(state.k), one(state.v)


def append_kv(state: KVCacheState, k_new, v_new, tables,
              positions) -> KVCacheState:
    """Write one token's K/V per sequence into the pool in place.

    ``k_new``/``v_new`` (num_layers, batch, kv_heads, head_dim);
    ``positions`` (batch,) the 0-based slot each token lands in. Rows
    whose table entry is the trash block (dummy batch slots) write
    harmlessly into it.
    """
    import jax.numpy as jnp

    bs = state.k.shape[2]
    w = tables.shape[1]
    blk = jnp.take_along_axis(
        tables, jnp.clip(positions[:, None] // bs, 0, w - 1), axis=1)[:, 0]
    slot = positions % bs
    return KVCacheState(k=state.k.at[:, blk, slot].set(k_new),
                        v=state.v.at[:, blk, slot].set(v_new))


def append_kv_prefill(state: KVCacheState, k_new, v_new, tables,
                      lengths) -> KVCacheState:
    """Write a whole prompt's K/V per sequence into the pool in place.

    ``k_new``/``v_new`` (num_layers, batch, kv_heads, seq, head_dim)
    right-padded; positions ``>= lengths`` clamp to the trash block
    (static scatter shape, no predication), so the pads' garbage K/V
    never lands in a real block.
    """
    import jax.numpy as jnp

    layers = state.k.shape[0]
    bs = state.k.shape[2]
    b, w = tables.shape
    s = k_new.shape[3]
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    valid = pos < lengths[:, None]
    blk = jnp.take_along_axis(tables, jnp.clip(pos // bs, 0, w - 1), axis=1)
    blk = jnp.where(valid, blk, TRASH_BLOCK)
    slot = pos % bs

    def one(pool, new):
        # (L, b, kv, s, d) -> (L, b, s, kv, d) to match pool[:, blk, slot]
        return pool.at[:, blk, slot].set(new.transpose(0, 1, 3, 2, 4))

    del layers
    return KVCacheState(k=one(state.k, k_new), v=one(state.v, v_new))


__all__ = [
    "KVCache",
    "KVCacheState",
    "PoolExhausted",
    "TRASH_BLOCK",
    "append_kv",
    "append_kv_prefill",
    "bucket",
    "gather_kv",
]

"""Paged KV cache: fixed-size blocks over one preallocated pool.

The serving tier's memory subsystem (ROADMAP item 1; the vLLM
PagedAttention layout re-expressed for this stack): instead of one
contiguous ``(batch, max_seq_len)`` KV buffer per sequence — whose
reallocation/copy on every growth step is exactly the churn the
donation-aware train step was built to kill — every layer's K and V
live in ONE preallocated pool of fixed-size blocks,

    pool: (num_layers, num_blocks, block_size, kv_heads, head_dim)

and each sequence owns an ordered *block table* (a list of pool block
indices). Appending a token writes one ``(kv_heads, head_dim)`` row at
``(table[pos // block_size], pos % block_size)``; reading gathers the
table back into a contiguous ``(kv_heads, padded_len, head_dim)`` view
for attention. Neither path ever reallocates the pool — the device
arrays are created once and donated through every decode step.

GQA pays GQA-sized blocks: the pool is sized from the model's
``kv_heads`` (``GPTConfig.kv_heads``), not ``num_heads``, so a 4x
grouped-query model holds 4x the sequences in the same HBM.

Block 0 is the **trash block**: writes from padded batch slots or
padded prompt tails land there (index clamping instead of predication
keeps the scatter shape static), and unallocated block-table entries
point at it so a short table gathers garbage that the attention mask
then drops. No real sequence is ever given block 0.

The allocator is host-side Python (the scheduler's admission control
runs on the host between steps); only :func:`gather_kv` /
:func:`append_kv` / :func:`append_kv_prefill` / :func:`append_kv_chunk`
trace into jitted programs.

Prefix sharing (docs/serving.md "Prefix cache"): every allocated block
carries a refcount, and blocks that hold a FULL block of prompt tokens
are published into a hash-chain index (``h_i = sha256(h_{i-1} ||
tokens[i*bs:(i+1)*bs])``) once their owner finishes prefill. A later
request whose prompt starts with the same token blocks takes shared
read-only references instead of re-paying prefill compute and KV
memory; at the divergence block a copy-on-write fork copies the common
row prefix into a private block, so the writer never mutates shared
state. Zero-ref published blocks stay resident as an LRU *prefix
cache* (reclaimed on demand — they count as free for admission);
blocks a quarantined tenant dirtied are scrubbed before any reuse
(the PR-9 NaN-scrub rule lifted to refcounted blocks: refcount zero →
scrub → free list).

Reservation is staged: :meth:`KVCache.allocate_prefix` reserves only
the span the caller names (a prefill chunk, or the full prompt +
max_new span), and :meth:`KVCache.extend` grows the reservation
chunk-by-chunk — the scheduler reserves the decode span (prompt +
max_new) together with the LAST chunk, so a request that reaches
DECODING still can never die of pool exhaustion mid-decode
(docs/serving.md "admission control").
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

TRASH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Admission refused: the pool cannot reserve the requested span.

    Carries ``needed`` / ``free`` block counts so the scheduler can
    tell a transient full pool (wait) from an impossible request
    (``needed > capacity``: reject)."""

    def __init__(self, msg: str, *, needed: int, free: int, capacity: int):
        super().__init__(msg)
        self.needed = int(needed)
        self.free = int(free)
        self.capacity = int(capacity)


class KVCacheState(NamedTuple):
    """The device-side pools — a pytree the decode step DONATES, so
    appends run in place and the cache never holds two copies."""

    k: Any    # (num_layers, num_blocks, block_size, kv_heads, head_dim)
    v: Any


class PrefixMatch(NamedTuple):
    """What :meth:`KVCache.allocate_prefix` matched for a prompt.

    ``matched`` tokens of the prompt are already resident (shared
    full blocks + ``fork_rows`` copied rows of the divergence block) —
    prefill resumes at position ``matched``. ``copies`` are the pending
    COW row copies ``(src_block, dst_block, rows)`` the engine must
    execute on the device state BEFORE the sequence's first chunk
    (``apply_copies``); until :meth:`KVCache.fork_copied` runs, the
    source blocks hold an extra reference so they cannot be evicted or
    scrubbed out from under the copy."""

    matched: int
    shared_blocks: int
    fork_rows: int
    copies: Tuple[Tuple[int, int, int], ...]


class KVCache:
    """Block allocator + pool factory for one model's KV cache.

    ``num_blocks`` counts usable blocks *excluding* the trash block
    (the pool array holds ``num_blocks + 1``).

    Thread-safety contract: every ALLOCATOR method (allocate / free /
    table / table_array / can_admit and the counters) takes this
    cache's internal lock, so a client thread calling
    ``ContinuousBatcher.submit()`` and the engine thread admitting,
    finishing, or draining can interleave freely. The device POOLS
    (``init_state()``'s arrays) are not covered: they are owned by the
    engine thread and donated through each prefill/decode dispatch —
    nothing else may touch them mid-step.
    """

    def __init__(self, num_layers: int, kv_heads: int, head_dim: int, *,
                 num_blocks: int, block_size: int = 16,
                 dtype: Any = None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype if dtype is not None else jnp.float32
        self._lock = threading.Lock()
        # LIFO free list: a freed sequence's blocks are the next handed
        # out (reuse-after-free is the common case under steady load,
        # and LIFO keeps the hot blocks hot)
        self._free: List[int] = list(range(self.num_blocks, 0, -1))
        self._tables: Dict[Any, List[int]] = {}
        # -- prefix-sharing plane (module docstring) -------------------
        self._refs: Dict[int, int] = {}          # block -> refcount
        # published block -> (chain hash, parent hash, block tokens)
        self._meta: Dict[int, Tuple[bytes, bytes, Tuple[int, ...]]] = {}
        self._index: Dict[bytes, int] = {}       # chain hash -> block
        self._children: Dict[bytes, List[int]] = {}
        # zero-ref published blocks, LRU order (prefix cache — these
        # count as reclaimable for admission)
        self._cached: "OrderedDict[int, bytes]" = OrderedDict()
        self._dirty: Set[int] = set()
        self._pending_scrub: List[int] = []      # zero-ref dirty blocks
        self._fork_refs: Dict[Any, List[int]] = {}
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0

    @classmethod
    def for_config(cls, cfg, *, num_blocks: int, block_size: int = 16,
                   dtype: Any = None) -> "KVCache":
        """Size the cache from a ``GPTConfig``-shaped model config:
        ``kv_heads`` (the GQA-narrowed count) x ``head_dim`` blocks —
        GQA pays GQA-sized blocks, never ``num_heads``-sized ones."""
        return cls(cfg.num_layers, cfg.kv_heads,
                   cfg.hidden_size // cfg.num_heads,
                   num_blocks=num_blocks, block_size=block_size,
                   dtype=dtype if dtype is not None else cfg.dtype)

    # -- pool ---------------------------------------------------------------

    def init_state(self) -> KVCacheState:
        """Allocate the pools (once; +1 block for the trash block)."""
        import jax.numpy as jnp

        shape = (self.num_layers, self.num_blocks + 1, self.block_size,
                 self.kv_heads, self.head_dim)
        return KVCacheState(k=jnp.zeros(shape, self.dtype),
                            v=jnp.zeros(shape, self.dtype))

    def pool_bytes(self) -> int:
        import jax.numpy as jnp

        n = (self.num_layers * (self.num_blocks + 1) * self.block_size
             * self.kv_heads * self.head_dim)
        return 2 * n * jnp.dtype(self.dtype).itemsize

    # -- allocator ----------------------------------------------------------

    def blocks_for(self, total_len: int) -> int:
        """Blocks a sequence of ``total_len`` tokens occupies."""
        return -(-max(int(total_len), 1) // self.block_size)

    def can_admit(self, total_len: int) -> bool:
        with self._lock:
            return self.blocks_for(total_len) <= self._reclaimable()

    def _reclaimable(self) -> int:
        # free list + the zero-ref prefix cache (evictable on demand)
        return len(self._free) + len(self._cached)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return self._reclaimable()

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by live sequences (cached prefix blocks
        and pending-scrub blocks are reclaimable, not in use)."""
        with self._lock:
            return len(self._refs)

    def _take_private(self, need: int, seq_id) -> List[int]:
        """Pop ``need`` fresh private blocks — free list first, then
        evict the LRU tail of the prefix cache. Caller holds the
        lock."""
        if need > self._reclaimable():
            raise PoolExhausted(
                f"kv pool exhausted: sequence {seq_id!r} needs {need} "
                f"blocks, {self._reclaimable()} free of "
                f"{self.num_blocks}",
                needed=need, free=self._reclaimable(),
                capacity=self.num_blocks)
        out: List[int] = []
        for _ in range(need):
            if self._free:
                out.append(self._free.pop())
            else:
                blk, _h = self._cached.popitem(last=False)   # LRU evict
                self._unpublish(blk)
                out.append(blk)
        for b in out:
            self._refs[b] = 1
        return out

    def allocate(self, seq_id, total_len: int) -> List[int]:
        """Reserve the full block span for a sequence reaching
        ``total_len`` tokens; raises :class:`PoolExhausted` when the
        free list can't cover it (the admission-control refusal).
        Private blocks only — the prefix-aware admit path is
        :meth:`allocate_prefix`."""
        need = self.blocks_for(total_len)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            blocks = self._take_private(need, seq_id)
            self._tables[seq_id] = blocks
            return list(blocks)

    def allocate_prefix(self, seq_id, prompt: Sequence[int],
                        total_len: int,
                        chunk: Optional[int] = None) -> PrefixMatch:
        """Reserve blocks for a sequence whose prompt is ``prompt``,
        reusing published prefix blocks by reference and COW-forking
        the divergence block.

        ``total_len`` is the full span (prompt + max_new). With
        ``chunk=None`` the whole span is reserved up front (the
        monolithic-admit contract); with a chunk size, reservation is
        STAGED — only ``matched + chunk`` tokens are covered now (the
        full span when that already reaches the end of the prompt),
        and the scheduler grows it via :meth:`extend` chunk by chunk.

        At most ``len(prompt) - 1`` tokens ever match (the last prompt
        token always prefills, so the first-token logits exist).
        Raises :class:`PoolExhausted` (leaking nothing) when the
        private remainder cannot be reserved.
        """
        prompt = tuple(int(t) for t in prompt)
        bs = self.block_size
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            hashes = self._chain_hashes(prompt)
            max_full = (len(prompt) - 1) // bs
            shared: List[int] = []
            parent = b""
            for i in range(min(len(hashes), max_full)):
                blk = self._index.get(hashes[i])
                if blk is None or blk in self._dirty:
                    break
                shared.append(blk)
                parent = hashes[i]
            m = len(shared)
            # COW fork: longest common row prefix with a published
            # child of the matched chain (leave >= 1 token to prefill)
            fork_src, fork_rows = None, 0
            budget = len(prompt) - 1 - m * bs
            if budget > 0:
                want = prompt[m * bs: (m + 1) * bs]
                for cand in self._children.get(parent, ()):
                    if cand in self._dirty or cand not in self._meta:
                        continue
                    toks = self._meta[cand][2]
                    f = 0
                    for a, c in zip(toks, want):
                        if a != c:
                            break
                        f += 1
                    f = min(f, budget)
                    if f > fork_rows:
                        fork_src, fork_rows = cand, f
            matched = m * bs + fork_rows
            if chunk is None or matched + chunk >= len(prompt):
                reserve_len = total_len
            else:
                reserve_len = matched + chunk      # staged: first chunk
            need = self.blocks_for(reserve_len) - m
            if need < 0:
                need = 0
            if fork_rows and need < 1:
                need = 1                     # the fork's private block
            # reference the matched blocks FIRST: _take_private evicts
            # the cached LRU, and a matched-but-unreferenced block
            # must not be evicted out from under this admission
            for blk in shared:
                self._ref_locked(blk)
            if fork_rows:
                self._ref_locked(fork_src)   # pin src until the copy
            try:
                priv = self._take_private(need, seq_id)
            except PoolExhausted:
                for blk in shared:           # leak nothing on refusal
                    self._unref_locked(blk, dirty=False)
                if fork_rows:
                    self._unref_locked(fork_src, dirty=False)
                raise
            copies: Tuple[Tuple[int, int, int], ...] = ()
            if fork_rows:
                self._fork_refs[seq_id] = [fork_src]
                copies = ((fork_src, priv[0], fork_rows),)
            self._tables[seq_id] = shared + priv
            if matched > 0:
                self.prefix_hits += 1
                self.prefix_tokens_saved += matched
            else:
                self.prefix_misses += 1
            return PrefixMatch(matched=matched, shared_blocks=m,
                               fork_rows=fork_rows, copies=copies)

    def extend(self, seq_id, total_len: int) -> int:
        """Grow a sequence's reservation to cover ``total_len`` tokens
        (staged per-chunk reservation); returns how many NEW private
        blocks were appended. Raises :class:`PoolExhausted` leaving
        the existing reservation intact."""
        with self._lock:
            table = self._tables[seq_id]
            need = self.blocks_for(total_len) - len(table)
            if need <= 0:
                return 0
            table.extend(self._take_private(need, seq_id))
            return need

    def fork_copied(self, seq_id) -> None:
        """Drop the pin on a COW fork's source blocks (the engine has
        executed the row copies on the device state)."""
        with self._lock:
            for blk in self._fork_refs.pop(seq_id, []):
                self._unref_locked(blk, dirty=False)

    def free(self, seq_id, *, dirty: bool = False,
             clean_blocks: Sequence[int] = ()) -> int:
        """Return a sequence's block references to the pool; returns
        how many blocks were released.

        ``dirty=True`` (the quarantine path) marks every released
        block — except ``clean_blocks``, which the caller already
        scrubbed device-side — as poisoned: it is unpublished at once
        (never matched again) and, when its refcount reaches zero,
        parked on the pending-scrub list instead of the free list
        until :meth:`scrub_done` confirms the device rows were zeroed
        (refcount zero -> scrub -> reuse)."""
        clean = set(int(b) for b in clean_blocks)
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            if blocks is None:
                return 0
            for blk in self._fork_refs.pop(seq_id, []):
                self._unref_locked(blk, dirty=False)
            for b in blocks:
                self._unref_locked(b, dirty=dirty and b not in clean)
            return len(blocks)

    def _ref_locked(self, blk: int) -> None:
        if blk in self._refs:
            self._refs[blk] += 1
            return
        # revive a zero-ref cached prefix block
        self._cached.pop(blk, None)
        self._refs[blk] = 1

    def _unref_locked(self, blk: int, *, dirty: bool) -> None:
        if dirty and blk not in self._dirty:
            self._dirty.add(blk)
            self._unpublish(blk)             # never matched again
        self._refs[blk] -= 1
        if self._refs[blk] > 0:
            return
        del self._refs[blk]
        if blk in self._dirty:
            self._pending_scrub.append(blk)
        elif blk in self._meta:
            self._cached[blk] = self._meta[blk][0]
            self._cached.move_to_end(blk)
        else:
            self._free.append(blk)

    def _unpublish(self, blk: int) -> None:
        meta = self._meta.pop(blk, None)
        if meta is None:
            return
        h, parent, _toks = meta
        if self._index.get(h) == blk:
            del self._index[h]
        kids = self._children.get(parent)
        if kids and blk in kids:
            kids.remove(blk)
            if not kids:
                del self._children[parent]
        self._cached.pop(blk, None)

    def _chain_hashes(self, prompt: Tuple[int, ...]) -> List[bytes]:
        bs = self.block_size
        out: List[bytes] = []
        h = b""
        for i in range(len(prompt) // bs):
            blk = np.asarray(prompt[i * bs:(i + 1) * bs],
                             np.int64).tobytes()
            h = hashlib.sha256(h + blk).digest()
            out.append(h)
        return out

    def publish_prefix(self, seq_id, prompt: Sequence[int]) -> int:
        """Publish a fully-prefilled sequence's full prompt blocks into
        the prefix index (later prompts with the same token blocks
        share them by reference); returns how many blocks were newly
        published. First publisher wins — blocks whose chain hash is
        already indexed are left alone."""
        prompt = tuple(int(t) for t in prompt)
        bs = self.block_size
        published = 0
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                return 0
            hashes = self._chain_hashes(prompt)
            parent = b""
            for i, h in enumerate(hashes):
                blk = table[i]
                if blk in self._dirty:
                    break
                if h in self._index:
                    parent = h
                    continue                 # first publisher wins
                if blk in self._meta:        # published under another
                    parent = h               # chain (shared-in block)
                    continue
                self._meta[blk] = (h, parent,
                                   prompt[i * bs:(i + 1) * bs])
                self._index[h] = blk
                self._children.setdefault(parent, []).append(blk)
                parent = h
                published += 1
            return published

    def take_pending_scrub(self) -> List[int]:
        """Pop the zero-ref dirty blocks awaiting a device scrub; the
        engine must zero their pool rows and call :meth:`scrub_done`
        before they can be reused."""
        with self._lock:
            out, self._pending_scrub = self._pending_scrub, []
            return out

    def scrub_done(self, blocks: Sequence[int]) -> None:
        """Return device-scrubbed blocks to the free list."""
        with self._lock:
            for b in blocks:
                self._dirty.discard(b)
                self._free.append(b)

    def reset_prefix_cache(self) -> int:
        """Drop every zero-ref cached prefix block back to the free
        list and clear the index (bench runs isolate workloads this
        way); returns how many blocks were reclaimed."""
        with self._lock:
            n = len(self._cached)
            for blk in list(self._cached):
                self._unpublish(blk)
                self._free.append(blk)
            self._cached.clear()
            self.prefix_hits = 0
            self.prefix_misses = 0
            self.prefix_tokens_saved = 0
            return n

    def prefix_match_len(self, prompt: Sequence[int]) -> int:
        """How many leading prompt tokens the prefix index could hand
        out by REFERENCE right now: full published, non-dirty blocks
        along the prompt's sha256 hash chain (capped at
        ``len(prompt) - 1`` like :meth:`allocate_prefix`; COW-fork
        partial rows are not counted — this is a cheap placement
        probe, not a reservation). Read-only: nothing is referenced,
        revived, or evicted. The fleet router's prefix-affinity score
        (serving/fleet.py): the engine whose pool already holds the
        longest prefix wins the request."""
        prompt = tuple(int(t) for t in prompt)
        with self._lock:
            hashes = self._chain_hashes(prompt)
            max_full = (len(prompt) - 1) // self.block_size
            m = 0
            for i in range(min(len(hashes), max_full)):
                blk = self._index.get(hashes[i])
                if blk is None or blk in self._dirty:
                    break
                m += 1
            return m * self.block_size

    def prefix_stats(self) -> Dict[str, int]:
        """Prefix-cache accounting for gauges/flight bundles."""
        with self._lock:
            shared = sum(1 for r in self._refs.values() if r > 1)
            return {
                "cached_blocks": len(self._cached),
                "shared_blocks": shared,
                "published_blocks": len(self._meta),
                "pending_scrub": len(self._pending_scrub),
                "hits": self.prefix_hits,
                "misses": self.prefix_misses,
                "tokens_saved": self.prefix_tokens_saved,
            }

    def block_ref(self, blk: int) -> int:
        with self._lock:
            return self._refs.get(int(blk), 0)

    def exclusive_blocks(self, seq_id) -> List[int]:
        """Blocks only this sequence references and nobody can match
        from the index — safe to scrub immediately on quarantine."""
        with self._lock:
            return [b for b in self._tables.get(seq_id, [])
                    if self._refs.get(b) == 1 and b not in self._meta]

    def table(self, seq_id) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    @property
    def sequences(self) -> List[Any]:
        with self._lock:
            return list(self._tables)

    def table_array(self, seq_ids: Sequence[Any], width: int,
                    batch: Optional[int] = None) -> np.ndarray:
        """The batch's block tables as one right-padded ``(batch,
        width)`` int32 array — padding (and dummy batch rows past
        ``len(seq_ids)``) points at the trash block."""
        b = len(seq_ids) if batch is None else int(batch)
        out = np.full((b, int(width)), TRASH_BLOCK, np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                t = self._tables[sid]
                if len(t) > width:
                    raise ValueError(
                        f"table width {width} < {len(t)} blocks of "
                        f"sequence {sid!r}")
                out[i, :len(t)] = t
        return out

    # -- disaggregated handoff (serving/fleet.py) --------------------------

    def export_blocks(self, state: KVCacheState, seq_id, *,
                      length: Optional[int] = None
                      ) -> Tuple[List[int], np.ndarray, np.ndarray]:
        """Extract a sequence's KV rows to the host for a cross-engine
        handoff: ``(blocks, k, v)`` where ``blocks`` is the sequence's
        block table (source indices, for the manifest) and ``k``/``v``
        are ``(num_layers, n, block_size, kv_heads, head_dim)`` host
        arrays. ``length`` bounds the export to the blocks that
        actually hold tokens (``blocks_for(length)``) so the wire never
        carries the unwritten decode-span tail; ``None`` exports the
        whole reservation. Read-only on both the table (a locked copy)
        and the pool — shared prefix blocks export fine."""
        table = self.table(seq_id)           # locked copy; raises unknown
        if length is not None:
            table = table[:self.blocks_for(length)]
        idx = np.asarray(table, np.int32)
        return (list(table), np.asarray(state.k[:, idx]),
                np.asarray(state.v[:, idx]))

    def import_blocks(self, state: KVCacheState, seq_id, k,
                      v) -> KVCacheState:
        """Install exported KV rows into THIS pool's blocks for an
        already-allocated ``seq_id`` (the receiving side of a handoff):
        row block ``i`` of the payload lands in the sequence's block
        ``table[i]``. The verify-before-install discipline is the
        CALLER's (serving/fleet.py hashes every block against the
        manifest first) — this method trusts its inputs. Returns the
        new device state; the table/refcounts are untouched."""
        import jax.numpy as jnp

        table = self.table(seq_id)
        k = np.asarray(k)
        v = np.asarray(v)
        n = k.shape[1]
        if n > len(table):
            raise ValueError(
                f"import_blocks: payload holds {n} blocks but sequence "
                f"{seq_id!r} reserves only {len(table)}")
        idx = jnp.asarray(table[:n], jnp.int32)
        return KVCacheState(
            k=state.k.at[:, idx].set(jnp.asarray(k, state.k.dtype)),
            v=state.v.at[:, idx].set(jnp.asarray(v, state.v.dtype)))


def bucket(n: int, minimum: int = 1) -> int:
    """Next power of two >= max(n, minimum) — the shape-bucketing that
    bounds the decode compile count (docs/serving.md)."""
    b = max(int(minimum), 1)
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Traced pool ops (what the jitted prefill/decode programs call)
# ---------------------------------------------------------------------------


def gather_kv(state: KVCacheState, tables):
    """Gather each sequence's blocks into contiguous per-batch views.

    ``tables`` (batch, width) int32 -> two ``(num_layers, batch,
    kv_heads, width * block_size, head_dim)`` arrays. Pure data
    movement — the bytes written by :func:`append_kv` come back
    bitwise (tests/test_serving.py pins it). Unallocated table entries
    gather the trash block; the caller's attention mask drops them.
    """
    def one(pool):
        g = pool[:, tables]            # (L, b, w, bs, kv, d)
        layers, b, w, bs, kv, d = g.shape
        return g.transpose(0, 1, 4, 2, 3, 5).reshape(layers, b, kv,
                                                     w * bs, d)
    return one(state.k), one(state.v)


def append_kv(state: KVCacheState, k_new, v_new, tables,
              positions) -> KVCacheState:
    """Write one token's K/V per sequence into the pool in place.

    ``k_new``/``v_new`` (num_layers, batch, kv_heads, head_dim);
    ``positions`` (batch,) the 0-based slot each token lands in. Rows
    whose table entry is the trash block (dummy batch slots) write
    harmlessly into it.
    """
    import jax.numpy as jnp

    bs = state.k.shape[2]
    w = tables.shape[1]
    blk = jnp.take_along_axis(
        tables, jnp.clip(positions[:, None] // bs, 0, w - 1), axis=1)[:, 0]
    slot = positions % bs
    return KVCacheState(k=state.k.at[:, blk, slot].set(k_new),
                        v=state.v.at[:, blk, slot].set(v_new))


def append_kv_prefill(state: KVCacheState, k_new, v_new, tables,
                      lengths) -> KVCacheState:
    """Write a whole prompt's K/V per sequence into the pool in place.

    ``k_new``/``v_new`` (num_layers, batch, kv_heads, seq, head_dim)
    right-padded; positions ``>= lengths`` clamp to the trash block
    (static scatter shape, no predication), so the pads' garbage K/V
    never lands in a real block.
    """
    return append_kv_chunk(state, k_new, v_new, tables, None, lengths)


def append_kv_chunk(state: KVCacheState, k_new, v_new, tables, starts,
                    lengths) -> KVCacheState:
    """Write one prefill CHUNK's K/V per sequence into the pool.

    The chunk-resumable generalization of :func:`append_kv_prefill`:
    chunk row ``i`` of sequence ``b`` lands at global position
    ``starts[b] + i`` (``starts=None`` means 0 — the monolithic
    prefill). Rows ``i >= lengths[b]`` (chunk padding) clamp to the
    trash block; the scatter shape stays static.
    """
    import jax.numpy as jnp

    bs = state.k.shape[2]
    b, w = tables.shape
    s = k_new.shape[3]
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    valid = pos < lengths[:, None]
    if starts is not None:
        pos = pos + starts[:, None]
    blk = jnp.take_along_axis(tables, jnp.clip(pos // bs, 0, w - 1), axis=1)
    blk = jnp.where(valid, blk, TRASH_BLOCK)
    slot = pos % bs

    def one(pool, new):
        # (L, b, kv, s, d) -> (L, b, s, kv, d) to match pool[:, blk, slot]
        return pool.at[:, blk, slot].set(new.transpose(0, 1, 3, 2, 4))

    return KVCacheState(k=one(state.k, k_new), v=one(state.v, v_new))


def apply_copies(state: KVCacheState,
                 copies: Sequence[Tuple[int, int, int]]) -> KVCacheState:
    """Execute COW fork row copies ``(src_block, dst_block, rows)`` on
    the device pools (host-issued between dispatches): the first
    ``rows`` rows of ``src`` — the common token prefix with the
    divergence block — are copied into the fresh private ``dst``; the
    shared source is never written."""
    k, v = state.k, state.v
    for src, dst, rows in copies:
        rows = int(rows)
        k = k.at[:, int(dst), :rows].set(k[:, int(src), :rows])
        v = v.at[:, int(dst), :rows].set(v[:, int(src), :rows])
    return KVCacheState(k=k, v=v)


def scrub_blocks(state: KVCacheState, blocks) -> KVCacheState:
    """Zero the named pool blocks (the quarantine / pending-scrub
    device op — a freed NaN row must never haunt the next tenant)."""
    import jax.numpy as jnp

    if len(blocks) == 0:
        return state
    b = jnp.asarray(sorted(int(x) for x in blocks), jnp.int32)
    return KVCacheState(k=state.k.at[:, b].set(0),
                        v=state.v.at[:, b].set(0))


__all__ = [
    "KVCache",
    "KVCacheState",
    "PoolExhausted",
    "PrefixMatch",
    "TRASH_BLOCK",
    "append_kv",
    "append_kv_chunk",
    "append_kv_prefill",
    "apply_copies",
    "bucket",
    "gather_kv",
    "scrub_blocks",
]

"""Fleet front door: a fault-tolerant router over N serving engines.

Everything below the router already exists — the latched
``should_shed()`` SLO hook, drain snapshots + ``resume_requests`` with
bitwise stream replay, ``resumed_from`` trace continuity, and the
sha256 hash-chain prefix index — but nothing consumed them ACROSS
engines, so one engine death was still a total outage. The
:class:`FleetRouter` is that consumer: one ``submit()`` / ``step()`` /
``merge_results()`` / ``introspect()`` surface fronting N
:class:`~apex_tpu.serving.scheduler.ContinuousBatcher` engines.

**Placement** (``submit``): prefix-affinity routing — each engine's
content-addressed prefix-cache index is probed with
:meth:`~apex_tpu.serving.kv_cache.KVCache.prefix_match_len`, and a
request sharing a cached prefix goes to the engine holding it
(``fleet_prefix_affinity_hits``), falling back to least queue depth.
Engines whose SLO monitor has LATCHED ``should_shed()`` are
deprioritized (not routed to while an alternative exists); when every
live engine is shedding, the fleet refuses admission with a structured
result (``reason="shedding"``, counter ``fleet_shed``) — never a
silent drop. ``placement`` selects ``"affinity"`` (default) /
``"least_queue"`` / ``"round_robin"`` so the affinity win is
measurable (tests, bench).

**Failover** (``step``): the router steps every live engine in turn,
deriving per-engine health from heartbeat staleness (a step that takes
longer than ``stall_after_s``) and consecutive step exceptions. A hard
death (:class:`~apex_tpu.resilience.faults.EngineCrash`, or
``max_step_failures`` consecutive exceptions — a wedged engine) FENCES
the engine: its in-flight + queued requests are recovered from its
last drain snapshot when one is usable (committed ``drained_snapshot``,
or a fresh ``save_snapshot`` under ``snapshot_dir/<engine>/``), and
REPLAYED from prompt + generated-so-far through the existing prefill
path when none is (``router_snapshot_missing=<idx>`` forces this
branch). Either way the work funnels through
:func:`~apex_tpu.serving.resilience.resume_requests` onto survivors
with ``resumed_from`` threading the SAME trace id, and the
counter-based per-request PRNG makes the recovered stream
token-identical to the uninterrupted run. Transient router-step
faults (``io:fleet_router``) ride ``resilience.retry`` backoff —
safe because every injection site fires BEFORE the engine dispatch —
with :class:`~apex_tpu.resilience.faults.EngineCrash` on the
non-retryable allowlist: a dead engine is fenced, immediately, never
retried. A slow-but-ALIVE engine gets a bounded hedge instead of a
kill: up to ``hedge_max`` of its not-yet-admitted requests move to a
healthy peer (``ContinuousBatcher.take_queued`` — in-flight work
stays put, so no stream is ever duplicated), the old trace segment
closing with outcome ``rerouted``.

**Elastic membership**: :meth:`FleetRouter.add_engine` compiles the
newcomer's programs off the hot path (``warm=True``) before it joins
the placement pool; :meth:`FleetRouter.remove_engine` applies the
drain discipline — snapshot, redistribute onto survivors — through the
same recovery path the failover uses (cause ``remove``: rerouted
counters tick, but no ``fleet_failovers`` and no flight bundle — a
planned exit is not a loss). A recovery with ZERO survivors parks the
work in an orphan list the next ``add_engine`` drains — still never a
silent drop.

**Disaggregated prefill/decode** (``add_engine(role=...)``): engines
seat with a role — ``prefill`` (admission + chunked prefill, then hand
the stream off), ``decode`` (receives streams only through the KV
handoff, never fresh admissions), or ``colocated`` (the default: both,
the pre-disaggregation behavior). After the engine loop of each
``step`` the router surrenders every prefill-complete flight from the
prefill seats (``ContinuousBatcher.take_prefilled`` — the KV
reservation stays until the handoff resolves), exports its filled
blocks from the paged pool
(:meth:`~apex_tpu.serving.kv_cache.KVCache.export_blocks`) and ships
them over a comms-instrumented loopback collective, so the payload is
priced by the wire-bytes model and visible in the comms ledger
whenever the comms plane is armed. Every transfer carries a per-block
sha256 manifest and is VERIFIED before install
(:meth:`~apex_tpu.serving.kv_cache.KVCache.import_blocks` writes only
manifest-clean payloads into the decode seat's pool); a failed verify
raises into ``resilience.retry`` and the SAME immutable export
re-sends — idempotent, keyed by the manifest root — with
:class:`~apex_tpu.resilience.faults.EngineCrash` on the give-up list.
The failure ladder, every rung zero-drop: a decode seat that dies
mid-handoff is fenced immediately and the stream re-prefills on a
survivor through the existing replay path (token-identical, same
trace id, ``resumed_from`` set); an orphaned export frees its source
blocks under the dirty-block scrub rule; a retry-exhausted transfer
keeps the stream on the source, which decodes it locally (colocated
degradation); and ``fallback_after`` consecutive transfer failures
LATCH colocated-fallback (``reason="handoff_degraded"``) — handoffs
stop, fresh admissions prefer colocated seats, and one healthy probe
transfer per fleet step through the same wire+verify path
auto-unlatches. A successful handoff lands one ``handoff`` span on
the request's single perfetto track (same trace id across engines).

Telemetry: ``fleet_engines{state=}``, ``fleet_failovers{cause=}``,
``fleet_requests_rerouted{cause=}``, ``fleet_prefix_affinity_hits``,
``fleet_shed``, per-engine ``fleet_engine_up`` /
``fleet_engine_step_seconds`` / ``fleet_engine_queue_depth`` gauges,
and a ``fleet_engine_lost`` flight trigger whose bundle embeds the
dead engine's last ``introspect()`` plus the structured recovery plan
(source, snapshot path, per-request target engine). The handoff plane
adds ``fleet_handoffs{outcome=}`` (ok / failed / orphan / dst_crash /
export_error), ``fleet_handoff_bytes``, ``fleet_handoff_retries``,
``fleet_handoff_probes{outcome=}``,
``fleet_colocated_fallback{transition=}`` (+ the
``fleet_colocated_fallback_latched`` gauge), and a
``kv_handoff_failed`` flight trigger whose bundle carries the sha256
manifest and the last attempt's per-block verify status. The router
shares
ONE :class:`~apex_tpu.serving.tracing.RequestTracer` across every
engine and marks each routing decision on the trace, so the perfetto
export shows a request crossing engines on a single track
(``export_trace`` groups tids by trace id).

Fault clauses (resilience/faults.py, docs/resilience.md grammar):
``engine_crash=<steps>`` (+ ``engine_crash_engine=<i>``) raises a hard
death out of engine *i*'s dispatch at those ROUTER steps;
``engine_stall_ms=<ms>`` (+ ``engine_stall_engine`` /
``engine_stall_at``) injects a heartbeat-stale-but-alive stall the
router must hedge, not fence; ``router_snapshot_missing=<idx>`` makes
recovery number ``idx`` behave as if no snapshot were usable;
``io:fleet_router`` injects transient step faults the retry absorbs.
The handoff grammar: ``kv_transfer_corrupt=<i>`` /
``kv_transfer_timeout=<i>`` / ``kv_transfer_partial=<i>`` fault the
*i*-th (0-based) transfer attempt — one flipped byte, a pre-byte
timeout, a zeroed tail block — ``handoff_orphan=<i>`` abandons
handoff number *i* after export, and ``io:kv_handoff`` injects
generic transients at the transfer site.
``tools/check_serving.sh`` drives two chaos drills: the router drill
(300 requests across 3 engines, one killed mid-load, one replacement
joining — goodput >= 0.95, prefix hit-rate within 10% of the no-kill
run, zero dropped or duplicated streams, recovered streams
bitwise-identical) and the disaggregation soak (300 requests on a
1-prefill/2-decode fleet under ``engine_crash`` + ``engine_stall_ms``
+ ``kv_transfer_corrupt`` in ONE run — goodput >= 0.99, bitwise
recovery, one continuous perfetto track per request across the
handoff).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.retry import retry_call
from apex_tpu.serving import resilience as _sresil
from apex_tpu.serving.scheduler import Request, RequestResult

# the engine lifecycle the fleet_engines{state=} gauge enumerates
ENGINE_STATES = ("warming", "active", "stalled", "draining", "fenced",
                 "removed")

# the disaggregation roles add_engine(role=...) accepts
ENGINE_ROLES = ("prefill", "decode", "colocated")


@dataclasses.dataclass
class EngineHandle:
    """One engine's seat in the fleet: the batcher, its device cache
    state (threaded through every ``step``), and the router-side
    health record. ``index`` is the 0-based JOIN order — the identity
    the ``engine_crash_engine`` / ``engine_stall_engine`` fault knobs
    address, stable across fencing and removal. ``role`` is the
    disaggregation seat (one of ``ENGINE_ROLES``): routing POLICY, not
    capability — every seat is a full ContinuousBatcher, so the
    zero-drop guarantee always outranks the role split."""

    name: str
    batcher: Any                      # scheduler.ContinuousBatcher
    state: Any                        # device KV-cache state
    index: int
    status: str = "active"            # one of ENGINE_STATES
    role: str = "colocated"           # one of ENGINE_ROLES
    last_beat: float = 0.0            # router clock at last good step
    last_step_s: float = 0.0
    step_failures: int = 0            # consecutive; reset on success
    hedged: int = 0                   # requests moved off while stalled
    error: Optional[str] = None       # last step failure, truncated
    handoffs_out: int = 0             # streams shipped off (prefill)
    handoffs_in: int = 0              # streams installed (decode)


class FleetRouter:
    """The multi-engine front door (module docstring).

    Drive it like a batcher: ``submit()`` requests (returns the chosen
    engine's name, or None on a structured refusal), ``step()`` once
    per iteration (steps every live engine, detects stalls, fences and
    recovers the dead), ``merge_results()`` to collect finished
    results with recovered streams stitched back together, and
    ``introspect()`` for the live fleet view ``tools/serving_top.py``
    renders. ``fleet_serve_loop`` wraps the cycle over an arrival
    schedule.

    ``submit`` is thread-safe (placement reads + the engine's own
    thread-safe ``submit``); ``step`` / membership changes belong to
    one driver thread — the same discipline as the engine itself.
    """

    def __init__(self, *, registry=None, tracer=None,
                 snapshot_dir: Optional[str] = None,
                 placement: str = "affinity",
                 stall_after_s: float = 1.0,
                 max_step_failures: int = 3,
                 hedge_max: int = 4,
                 step_retries: int = 2,
                 handoff_retries: int = 2,
                 fallback_after: int = 3,
                 retry_base_delay: float = 0.01,
                 clock: Callable[[], float] = time.perf_counter,
                 sleep: Callable[[float], None] = time.sleep):
        from apex_tpu import telemetry

        if placement not in ("affinity", "least_queue", "round_robin"):
            raise ValueError(f"unknown placement {placement!r}")
        self._registry = (registry if registry is not None
                          else telemetry.registry())
        self.tracer = tracer              # ONE tracer across the fleet
        self.snapshot_dir = snapshot_dir
        self.placement = placement
        self.stall_after_s = float(stall_after_s)
        self.max_step_failures = int(max_step_failures)
        self.hedge_max = int(hedge_max)
        self.step_retries = int(step_retries)
        self.handoff_retries = int(handoff_retries)
        self.fallback_after = int(fallback_after)
        self.retry_base_delay = float(retry_base_delay)
        self.clock = clock
        self.sleep = sleep
        self.step_idx = 0
        # failover records for the bench (`fleet_failover_ms`): one
        # dict per fence with cause/source/recovered ids/recover_s
        self.failovers: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._engines: Dict[str, EngineHandle] = {}
        self._retired: List[EngineHandle] = []
        self._next_index = 0
        self._rr = 0                      # round_robin cursor
        self._recoveries = 0              # router_snapshot_missing idx
        self._refused: List[RequestResult] = []
        self._orphans: List[Request] = []
        # generated-so-far prefixes of recovered requests, stitched
        # back by merge_results (accumulates across double failovers)
        self._prior: Dict[Any, List[int]] = {}
        # -- disaggregated handoff state --
        self._handoff_seq = 0             # transfer id (orphan drill)
        self._handoff_failures = 0        # consecutive; latch trigger
        self._fallback = False            # colocated-fallback latch
        self._fallback_step: Optional[int] = None
        self._wire_col = None             # lazy loopback collective
        self.handoff_stats: Dict[str, int] = {
            "ok": 0, "failed": 0, "orphan": 0, "dst_crash": 0,
            "export_error": 0, "bytes": 0, "retries": 0}

    # -- membership ----------------------------------------------------------

    def engines(self) -> List[EngineHandle]:
        with self._lock:
            return list(self._engines.values())

    def add_engine(self, name: str, batcher, state, *,
                   role: str = "colocated",
                   warm: bool = False,
                   warmup_kwargs: Optional[Dict[str, Any]] = None
                   ) -> EngineHandle:
        """Seat a new engine. With ``warm=True`` the engine's programs
        compile HERE, before it enters the placement pool — warmup off
        the hot path, then admit — so its first routed request never
        pays an XLA compile. ``role`` picks the disaggregation seat
        (``prefill`` / ``decode`` / ``colocated`` — module docstring).
        The newcomer adopts the fleet tracer (one request plane across
        engines) and immediately absorbs any orphaned work a
        zero-survivor recovery parked."""
        if role not in ENGINE_ROLES:
            raise ValueError(f"unknown engine role {role!r} "
                             f"(one of {ENGINE_ROLES})")
        with self._lock:
            prev = self._engines.get(str(name))
            if prev is not None and prev.status not in ("fenced",
                                                        "removed"):
                raise ValueError(f"engine {name!r} already in the fleet")
            index = self._next_index
            self._next_index += 1
        if self.tracer is not None:
            batcher.tracer = self.tracer
        h = EngineHandle(name=str(name), batcher=batcher, state=state,
                         index=index, status="warming", role=role)
        if warm:
            h.state = batcher.warmup(h.state, **(warmup_kwargs or {}))
        h.status = "active"
        h.last_beat = self.clock()
        with self._lock:
            if prev is not None:          # a reused seat name retires
                self._retired.append(prev)
            self._engines[h.name] = h
            orphans, self._orphans = self._orphans, []
        self._registry.event("fleet_engine_added", engine=h.name,
                             index=h.index, role=h.role,
                             warmed=bool(warm))
        for req in orphans:
            self._submit_to(h, req)
        if orphans:
            self._registry.counter(
                "fleet_requests_rerouted",
                "requests moved between engines by cause").inc(
                len(orphans), cause="orphan")
        self._publish()
        return h

    def remove_engine(self, name: str) -> Dict[str, Any]:
        """Planned exit under the drain discipline: the engine leaves
        the placement pool, its queued + in-flight work snapshots and
        redistributes onto survivors through the SAME recovery path a
        failover uses (``resume_requests`` — recovered streams stay
        token-identical), and the seat lands in state ``removed``.
        Cause ``remove`` ticks ``fleet_requests_rerouted`` but not
        ``fleet_failovers`` and dumps no bundle: a planned exit is not
        a loss."""
        with self._lock:
            h = self._engines.get(str(name))
        if h is None or h.status in ("fenced", "removed"):
            raise ValueError(f"no live engine {name!r} to remove")
        h.status = "draining"
        recovered, source, path, targets = self._recover(h,
                                                         cause="remove")
        h.status = "removed"
        self._registry.event("fleet_engine_removed", engine=h.name,
                             source=source, snapshot=path,
                             recovered=[str(r.id) for r in recovered])
        self._publish()
        return {"engine": h.name, "source": source, "snapshot": path,
                "recovered": [r.id for r in recovered],
                "targets": targets}

    # -- placement -----------------------------------------------------------

    def _shedding(self, h: EngineHandle) -> bool:
        slo = h.batcher.slo
        return slo is not None and slo.should_shed()

    def _depth(self, h: EngineHandle) -> int:
        b = h.batcher
        return len(b.queue) + len(b.prefilling) + len(b.running)

    def _candidates(self) -> Tuple[List[EngineHandle], bool]:
        """(placement pool, all_shed): live engines minus the shedding
        ones; ``all_shed`` is True when live engines exist but every
        one has a latched shed — the fleet-wide refusal condition."""
        with self._lock:
            live = [h for h in self._engines.values()
                    if h.status in ("active", "stalled")]
        pool = [h for h in live if not self._shedding(h)]
        return pool, bool(live) and not pool

    def _admission_pool(self, pool: List[EngineHandle]
                        ) -> List[EngineHandle]:
        """Role filter for FRESH admissions (and replays, which
        re-enter through prefill): ``decode`` seats receive work only
        through the KV handoff — unless they are the only live seats
        left, because role is policy, not capability, and the
        zero-drop guarantee outranks the split. Under the
        colocated-fallback latch, ``colocated`` seats are preferred so
        prefill seats stop accumulating streams they cannot ship."""
        if self._fallback:
            colo = [h for h in pool if h.role == "colocated"]
            if colo:
                return colo
        front = [h for h in pool if h.role != "decode"]
        return front or pool

    def _place(self, pool: List[EngineHandle],
               prompt: Sequence[int]) -> EngineHandle:
        """Pick one engine from ``pool``. Stalled engines are
        deprioritized (used only when no active engine remains);
        ``affinity`` probes every candidate's prefix index and sends
        the request to the longest cached match, tie-broken (and
        missed entirely) by least queue depth."""
        active = [h for h in pool if h.status == "active"]
        pool = active or pool
        if self.placement == "round_robin":
            pool = sorted(pool, key=lambda h: h.index)
            h = pool[self._rr % len(pool)]
            self._rr += 1
            return h
        by_depth = lambda h: (self._depth(h), h.index)  # noqa: E731
        if self.placement == "affinity":
            scores = [(h.batcher.cache.prefix_match_len(prompt), h)
                      for h in pool]
            best = max(s for s, _ in scores)
            if best > 0:
                self._registry.counter(
                    "fleet_prefix_affinity_hits",
                    "placements routed to a cached prefix").inc()
                return min((h for s, h in scores if s == best),
                           key=by_depth)
        return min(pool, key=by_depth)

    def _submit_to(self, h: EngineHandle, request: Request) -> None:
        h.batcher.submit(request)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.mark(request.id, "routed", self.clock(), engine=h.name)

    def submit(self, request: Request) -> Optional[str]:
        """Route one request; returns the chosen engine's name, or
        None on a fleet-wide shed — a STRUCTURED refusal
        (``reason="shedding"``) delivered through ``merge_results``,
        never a silent drop. With no engine seated at all, submitting
        is a programming error and raises."""
        now = self.clock()
        tr = self.tracer
        if tr is not None and tr.enabled:
            request.trace_id = tr.begin(
                request.id, t_submit=now, trace_id=request.trace_id,
                resumed_from=request.resumed_from)
        pool, all_shed = self._candidates()
        if all_shed:
            msg = ("every engine is shedding (latched SLO burn-rate "
                   "alert): fleet refuses admission")
            self._registry.counter(
                "fleet_shed",
                "admissions refused by a fleet-wide SLO shed").inc()
            self._registry.event("fleet_shed", request=str(request.id))
            if tr is not None and tr.enabled:
                tr.finish(request.id, "rejected", t=self.clock(),
                          error=msg)
            with self._lock:
                self._refused.append(RequestResult(
                    id=request.id, tokens=[], ttft_s=None, tpot_s=None,
                    finish_reason="error", error=msg,
                    reason="shedding"))
            return None
        if not pool:
            raise RuntimeError(
                "FleetRouter.submit: no live engine (add_engine first)")
        h = self._place(self._admission_pool(pool), request.prompt)
        self._submit_to(h, request)
        return h.name

    # -- stepping + health ---------------------------------------------------

    def _step_engine(self, h: EngineHandle, idx: int):
        """One engine step under the router's fault sites + retry.
        Every injection fires BEFORE the engine dispatch, so a retried
        attempt re-runs nothing — ``io:fleet_router`` transients are
        absorbed; :class:`~apex_tpu.resilience.faults.EngineCrash` is
        on the give-up allowlist and re-raises from the first attempt
        (a dead engine is fenced, never retried)."""
        def attempt():
            faults.check("fleet_router")
            faults.maybe_engine_crash(idx, h.index)
            stall = faults.engine_stall_s(idx, h.index)
            if stall > 0.0:
                self.sleep(stall)     # alive, just heartbeat-stale
            return h.batcher.step(h.state)

        return retry_call(
            attempt, retries=self.step_retries,
            base_delay=self.retry_base_delay, jitter=0.0,
            retry_on=(faults.FaultError,),
            give_up_on=(faults.EngineCrash,), sleep=self.sleep,
            site="fleet_router")

    def step(self) -> Dict[str, Dict[str, Any]]:
        """One fleet iteration: step every live engine (idle ones are
        skipped), update heartbeats, hedge the stalled, fence and
        recover the dead. Returns ``{engine: step report}``."""
        idx = self.step_idx
        self.step_idx += 1
        reports: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            live = [h for h in self._engines.values()
                    if h.status in ("active", "stalled")]
        for h in live:
            if h.batcher.idle():
                h.status = "active"   # nothing left to be stalled ON
                h.last_beat = self.clock()
                continue
            t0 = self.clock()
            try:
                h.state, rep = self._step_engine(h, idx)
            except faults.EngineCrash as e:
                self._fence(h, idx, cause="crash", error=e)
                continue
            except Exception as e:  # noqa: BLE001 — health accounting
                h.step_failures += 1
                h.error = f"{type(e).__name__}: {str(e)[:200]}"
                self._registry.counter(
                    "fleet_engine_step_errors",
                    "engine step exceptions survived by the router"
                    ).inc(engine=h.name)
                if h.step_failures >= self.max_step_failures:
                    self._fence(h, idx, cause="wedged", error=e)
                continue
            now = self.clock()
            h.step_failures = 0
            h.error = None
            h.last_step_s = now - t0
            h.last_beat = now
            reports[h.name] = rep
            if h.last_step_s > self.stall_after_s:
                # heartbeat stale but the step RETURNED: the engine is
                # slow, not dead — hedge its queue, keep it seated
                if h.status != "stalled":
                    h.status = "stalled"
                    self._registry.event(
                        "fleet_engine_stalled", engine=h.name,
                        step_s=round(h.last_step_s, 6),
                        threshold_s=self.stall_after_s)
                self._hedge(h)
            elif h.status == "stalled":
                h.status = "active"
        if self._fallback:
            self._probe_handoff(idx)
        else:
            self._handoff_phase(idx)
        self._publish()
        return reports

    def _hedge(self, h: EngineHandle) -> None:
        """Bounded hedge for a stalled-but-alive engine: move up to
        ``hedge_max`` NOT-yet-admitted requests to a healthy peer.
        In-flight work stays put — the stream exists in exactly one
        place, so nothing can be duplicated. Each moved trace segment
        closes with outcome ``rerouted`` and continues (same trace id)
        on the peer. With no healthy peer, nothing moves."""
        with self._lock:
            peers = [p for p in self._engines.values()
                     if p is not h and p.status == "active"]
        peers = self._admission_pool(
            [p for p in peers if not self._shedding(p)])
        if not peers:
            return
        moved = h.batcher.take_queued(self.hedge_max)
        if not moved:
            return
        h.hedged += len(moved)
        tr = self.tracer
        now = self.clock()
        self._registry.counter(
            "fleet_requests_rerouted",
            "requests moved between engines by cause").inc(
            len(moved), cause="hedge")
        self._registry.event("fleet_engine_hedged", engine=h.name,
                             moved=[str(r.id) for r, _ in moved])
        for req, _ in moved:
            if tr is not None and tr.enabled:
                tr.finish(req.id, "rerouted", t=now, engine=h.name)
            self._submit_to(self._place(peers, req.prompt), req)

    # -- disaggregated KV handoff --------------------------------------------

    def _wire(self):
        """The handoff wire: a loopback Collective routed through
        ``telemetry.comms.instrument()``, so every shipped payload is
        priced by the wire-bytes model and lands in the comms ledger
        (per-op bytes/ms, timeline spans) whenever the comms plane is
        armed — and is the raw object, untouched, when it is not."""
        if self._wire_col is None:
            from apex_tpu.resilience.guard import NullCollective
            self._wire_col = NullCollective()
        from apex_tpu.telemetry import comms as _comms
        return _comms.instrument(self._wire_col)

    def _ship(self, k: np.ndarray, v: np.ndarray):
        out = self._wire().broadcast_from(0, [k, v])
        return np.asarray(out[0]), np.asarray(out[1])

    @staticmethod
    def _manifest(blocks: Sequence[int], k: np.ndarray,
                  v: np.ndarray) -> Dict[str, Any]:
        """Per-block sha256 manifest of an exported payload. Hashes
        cover the k+v bytes of each block in payload order; ``root``
        keys the transfer (the idempotent re-send identity)."""
        per = [hashlib.sha256(
            np.ascontiguousarray(k[:, i]).tobytes()
            + np.ascontiguousarray(v[:, i]).tobytes()).hexdigest()
            for i in range(k.shape[1])]
        root = hashlib.sha256(",".join(per).encode()).hexdigest()
        return {"root": root, "blocks": per,
                "src_blocks": [int(b) for b in blocks],
                "shape": list(k.shape), "dtype": str(k.dtype)}

    @staticmethod
    def _verify_blocks(manifest: Dict[str, Any], k: np.ndarray,
                       v: np.ndarray,
                       log: List[Dict[str, Any]]) -> List[int]:
        """Block-by-block manifest check of a RECEIVED payload;
        returns the corrupt block indices. ``log`` is overwritten with
        the attempt's per-block status (what the ``kv_handoff_failed``
        bundle embeds)."""
        bad: List[int] = []
        entries: List[Dict[str, Any]] = []
        for i, want in enumerate(manifest["blocks"]):
            got = hashlib.sha256(
                np.ascontiguousarray(k[:, i]).tobytes()
                + np.ascontiguousarray(v[:, i]).tobytes()).hexdigest()
            ok = got == want
            entries.append({"block": i, "ok": ok})
            if not ok:
                bad.append(i)
        log[:] = entries
        return bad

    def _transfer_once(self, hid: int, manifest: Dict[str, Any],
                       k: np.ndarray, v: np.ndarray,
                       verify_log: List[Dict[str, Any]]):
        """ONE wire attempt: ship the payload, apply the kv-transfer
        fault clauses to the RECEIVED copy, then verify every block
        against the manifest — verify-before-install, so a corrupt or
        truncated payload never reaches a pool. The raised FaultError
        re-sends the SAME export under the caller's retry: idempotent,
        because the source bytes are immutable for the transfer's
        lifetime and the manifest root names what must arrive."""
        fault = faults.kv_transfer_fault()
        if fault == "timeout":
            raise faults.FaultError(
                f"injected kv transfer timeout (handoff {hid})")
        rk, rv = self._ship(k, v)
        if fault == "corrupt":
            rk = np.array(rk, copy=True)
            if rk.nbytes:
                rk.view(np.uint8).reshape(-1)[0] ^= 0xFF
        elif fault == "partial":
            rk = np.array(rk, copy=True)
            rv = np.array(rv, copy=True)
            rk[:, -1] = 0
            rv[:, -1] = 0
        bad = self._verify_blocks(manifest, rk, rv, verify_log)
        if bad:
            raise faults.FaultError(
                f"kv handoff verify refused install (handoff {hid}, "
                f"manifest {manifest['root'][:12]}): corrupt blocks "
                f"{bad}")
        return rk, rv

    def _handoff_phase(self, idx: int) -> None:
        """Move every prefill-complete stream off the prefill seats.
        A seat with no live decode-capable sink keeps its flights —
        they decode locally on the next engine step (the colocated
        floor; never a stall, never a drop)."""
        with self._lock:
            srcs = [h for h in self._engines.values()
                    if h.status in ("active", "stalled")
                    and h.role == "prefill"]
            sinks = [h for h in self._engines.values()
                     if h.status in ("active", "stalled")
                     and h.role in ("decode", "colocated")]
        for src in srcs:
            if src.status not in ("active", "stalled"):
                continue          # fenced by an earlier handoff crash
            if not any(p.status in ("active", "stalled")
                       for p in sinks):
                continue
            for fl in src.batcher.take_prefilled():
                live = [p for p in sinks
                        if p.status in ("active", "stalled")]
                if not live or self._fallback:
                    src.batcher.running.append(fl)
                    continue
                pool = ([p for p in live if not self._shedding(p)]
                        or live)
                dst = min(pool,
                          key=lambda p: (self._depth(p), p.index))
                self._handoff(src, dst, fl, idx)

    def _handoff(self, src: EngineHandle, dst: EngineHandle, fl,
                 idx: int) -> bool:
        """One stream's handoff: export -> manifest -> wire (retried,
        verify-before-install) -> install on ``dst`` -> free the
        source reservation. Every failure rung keeps the stream alive
        (module docstring ladder); returns True on an installed
        handoff."""
        from apex_tpu.telemetry import flight as _flight

        req = fl.req
        hid = self._handoff_seq
        self._handoff_seq += 1
        t0 = self.clock()
        handoffs = self._registry.counter(
            "fleet_handoffs", "KV handoffs attempted by outcome")
        # export length = filled KV rows: prefill of P tokens plus the
        # decode appends, minus the newest token whose KV row is the
        # NEXT append (scheduler position semantics)
        filled = len(req.prompt) + len(fl.generated) - 1
        try:
            blocks, k, v = src.batcher.cache.export_blocks(
                src.state, fl.seq_id, length=filled)
        except Exception as e:  # noqa: BLE001 — keep the stream local
            handoffs.inc(outcome="export_error")
            self.handoff_stats["export_error"] += 1
            self._registry.event(
                "fleet_handoff_export_error", request=str(req.id),
                src=src.name, error=f"{type(e).__name__}: {e}")
            src.batcher.running.append(fl)
            return False
        manifest = self._manifest(blocks, k, v)
        payload_bytes = int(k.nbytes + v.nbytes)
        if faults.should_orphan_handoff():
            # the drill where the handoff is abandoned AFTER export
            # with the payload in flight: the exported blocks are
            # treated as tainted — freed into pending-scrub (dirty-
            # block rule: zeroed before reuse) — and the stream
            # re-prefills on a survivor
            src.batcher.cache.free(fl.seq_id, dirty=True)
            handoffs.inc(outcome="orphan")
            self.handoff_stats["orphan"] += 1
            self._registry.event(
                "fleet_handoff_orphan", request=str(req.id),
                src=src.name, handoff=hid, blocks=len(blocks))
            self._replay_flight(src, fl, cause="handoff_orphan",
                                tag=f"handoff_{hid:06d}")
            return False
        attempts = [0]
        verify_log: List[Dict[str, Any]] = []

        def attempt():
            attempts[0] += 1
            faults.check("kv_handoff")
            faults.maybe_engine_crash(idx, dst.index)
            return self._transfer_once(hid, manifest, k, v, verify_log)

        try:
            rk, rv = retry_call(
                attempt, retries=self.handoff_retries,
                base_delay=self.retry_base_delay, jitter=0.0,
                retry_on=(faults.FaultError, OSError),
                give_up_on=(faults.EngineCrash,), sleep=self.sleep,
                site="kv_handoff")
            dst.state = dst.batcher.install_prefilled(
                dst.state, req, fl.generated, rk, rv,
                t_submit=fl.t_submit, t_first=fl.t_first,
                t_last=fl.t_last)
        except faults.EngineCrash as e:
            # the decode seat died mid-handoff: fence it NOW
            # (EngineCrash is on the give-up allowlist, so fencing is
            # never delayed by backoff), then re-prefill the stream on
            # a survivor through the existing replay path
            self._note_handoff_retries(attempts[0])
            handoffs.inc(outcome="dst_crash")
            self.handoff_stats["dst_crash"] += 1
            self._fence(dst, idx, cause="crash", error=e)
            src.batcher.cache.free(fl.seq_id)
            self._replay_flight(src, fl, cause="handoff_dst_crash",
                                tag=f"handoff_{hid:06d}")
            return False
        except Exception as e:  # noqa: BLE001 — wire exhausted or
            # install refused (e.g. the sink's pool is full): the
            # source still holds valid KV, so the stream stays local
            # and decodes there — colocated degradation, zero drops
            self._note_handoff_retries(attempts[0])
            handoffs.inc(outcome="failed")
            self.handoff_stats["failed"] += 1
            ev = self._registry.event(
                "kv_handoff_failed", request=str(req.id),
                src=src.name, dst=dst.name, handoff=hid,
                attempts=attempts[0], manifest=manifest["root"],
                error=f"{type(e).__name__}: {e}")
            _flight.notify(
                "kv_handoff_failed", error=e, fleet=False,
                extra={"handoff": hid, "request": str(req.id),
                       "src": src.name, "dst": dst.name,
                       "attempts": attempts[0],
                       "manifest": {"root": manifest["root"],
                                    "blocks": manifest["blocks"],
                                    "shape": manifest["shape"]},
                       "verify": list(verify_log), "event": ev})
            tr = self.tracer
            if tr is not None and tr.enabled:
                tr.mark(req.id, "handoff_failed", self.clock(),
                        src=src.name, dst=dst.name,
                        attempts=attempts[0])
            src.batcher.running.append(fl)
            self._registry.counter(
                "fleet_requests_rerouted",
                "requests moved between engines by cause").inc(
                cause="handoff_degraded")
            self._handoff_failures += 1
            if (not self._fallback
                    and self._handoff_failures >= self.fallback_after):
                self._latch_fallback(idx)
            return False
        # verified install succeeded: release the source reservation
        # (clean — export was read-only), leaving the prompt prefix in
        # the source's content-addressed index for future affinity
        src.batcher.cache.free(fl.seq_id)
        now = self.clock()
        self._handoff_failures = 0
        src.handoffs_out += 1
        dst.handoffs_in += 1
        handoffs.inc(outcome="ok")
        self.handoff_stats["ok"] += 1
        self.handoff_stats["bytes"] += payload_bytes
        self._registry.counter(
            "fleet_handoff_bytes",
            "KV payload bytes moved by successful handoffs").inc(
            payload_bytes)
        self._note_handoff_retries(attempts[0])
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.span(req.id, "handoff", t0, now - t0, src=src.name,
                    dst=dst.name, blocks=len(blocks),
                    bytes=payload_bytes, attempts=attempts[0],
                    manifest=manifest["root"][:12])
            tr.mark(req.id, "routed", now, engine=dst.name)
        return True

    def _note_handoff_retries(self, attempts: int) -> None:
        n = int(attempts) - 1
        if n > 0:
            self.handoff_stats["retries"] += n
            self._registry.counter(
                "fleet_handoff_retries",
                "extra wire attempts spent by KV handoffs").inc(n)

    def _replay_flight(self, src: EngineHandle, fl, *, cause: str,
                       tag: str) -> None:
        """Re-prefill a surrendered flight on a survivor through the
        existing replay path: the replay prompt is
        ``prompt + generated`` and ``max_new_tokens`` shrinks by what
        was already generated — the counter-based per-request PRNG
        makes the recovered stream token-identical — with the same
        trace id continuing the request's single track and
        ``resumed_from`` naming the handoff. ``merge_results``
        stitches the prior tokens back. Affinity usually lands the
        replay on the source itself (its prompt prefix is still in
        the index), where the prefix cache makes the re-prefill
        nearly free."""
        req = fl.req
        prior = [int(t) for t in fl.generated]
        replay = Request(
            id=req.id, prompt=[int(t) for t in req.prompt] + prior,
            max_new_tokens=int(req.max_new_tokens) - len(prior),
            eos_id=req.eos_id, deadline_ms=req.deadline_ms,
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, seed=req.seed, trace_id=req.trace_id,
            resumed_from=tag)
        tr = self.tracer
        now = self.clock()
        if tr is not None and tr.enabled:
            tr.finish(req.id, "rerouted", t=now, engine=src.name,
                      cause=cause)
        with self._lock:
            self._prior[req.id] = (self._prior.get(req.id, [])
                                   + prior)
            pool = [p for p in self._engines.values()
                    if p.status in ("active", "stalled")]
        self._registry.counter(
            "fleet_requests_rerouted",
            "requests moved between engines by cause").inc(cause=cause)
        if pool:
            open_pool = self._admission_pool(
                [p for p in pool if not self._shedding(p)] or pool)
            self._submit_to(self._place(open_pool, replay.prompt),
                            replay)
        else:
            with self._lock:
                self._orphans.append(replay)

    def _latch_fallback(self, idx: int) -> None:
        """``fallback_after`` consecutive transfer failures close the
        colocated-fallback latch: handoffs stop (prefill seats keep
        their streams and decode them locally), fresh admissions
        prefer colocated seats, and every fleet step runs ONE healthy
        probe transfer through the same wire+verify path — the first
        clean probe auto-unlatches."""
        self._fallback = True
        self._fallback_step = idx
        self._registry.counter(
            "fleet_colocated_fallback",
            "colocated-fallback latch transitions").inc(
            transition="latched")
        self._registry.event(
            "fleet_colocated_fallback", transition="latched",
            reason="handoff_degraded", router_step=idx,
            consecutive_failures=self._handoff_failures)

    def _probe_handoff(self, idx: int) -> None:
        """One health probe per latched fleet step: a synthetic
        one-block payload through the SAME fault sites, wire, and
        manifest verify a real handoff uses. A clean probe reopens
        the latch; a failed one leaves the fleet colocated."""
        with self._lock:
            live = [h for h in self._engines.values()
                    if h.status in ("active", "stalled")]
        src = (next((h for h in live if h.role == "prefill"), None)
               or (live[0] if live else None))
        if src is None:
            return
        c = src.batcher.cache
        shape = (c.num_layers, 1, c.block_size, c.kv_heads, c.head_dim)
        # non-zero probe bytes: a zeroed-tail (partial) wire must not
        # hash clean and unlatch a still-degraded fleet
        k = np.ones(shape, np.float32)
        v = np.ones(shape, np.float32)
        manifest = self._manifest([0], k, v)
        probes = self._registry.counter(
            "fleet_handoff_probes",
            "colocated-fallback health probes by outcome")
        try:
            faults.check("kv_handoff")
            self._transfer_once(-1, manifest, k, v, [])
        except Exception:  # noqa: BLE001 — still degraded, stay latched
            probes.inc(outcome="failed")
            return
        probes.inc(outcome="ok")
        self._fallback = False
        self._fallback_step = None
        self._handoff_failures = 0
        self._registry.counter(
            "fleet_colocated_fallback",
            "colocated-fallback latch transitions").inc(
            transition="unlatched")
        self._registry.event(
            "fleet_colocated_fallback", transition="unlatched",
            router_step=idx)

    # -- failover ------------------------------------------------------------

    def _fence(self, h: EngineHandle, idx: int, *, cause: str,
               error: Optional[BaseException]) -> None:
        """Fence a dead (``crash``) or wedged engine and recover its
        work onto survivors. The ``fleet_engine_lost`` bundle embeds
        the engine's LAST introspect plus the structured recovery
        plan — the postmortem opens with the victim's final state and
        where every request went."""
        from apex_tpu.telemetry import flight as _flight

        h.status = "fenced"
        if error is not None:
            h.error = f"{type(error).__name__}: {str(error)[:200]}"
        try:
            last_intro = h.batcher.introspect()
        except Exception:  # noqa: BLE001 — a wedged engine may not even
            last_intro = None
        t0 = self.clock()
        recovered, source, path, targets = self._recover(h, cause=cause)
        recover_s = self.clock() - t0
        self._registry.counter(
            "fleet_failovers",
            "engines fenced and recovered by cause").inc(cause=cause)
        plan = {"engine": h.name, "cause": cause, "source": source,
                "snapshot": path,
                "recovered": [str(r.id) for r in recovered],
                "targets": targets}
        ev = self._registry.event(
            "fleet_engine_lost", engine=h.name, cause=cause,
            router_step=idx, source=source, snapshot=path,
            recovered=[str(r.id) for r in recovered])
        _flight.notify("fleet_engine_lost", error=error, fleet=False,
                       extra={"engine": h.name, "cause": cause,
                              "last_introspect": last_intro,
                              "plan": plan, "event": ev})
        self.failovers.append({
            "engine": h.name, "cause": cause, "router_step": idx,
            "source": source, "snapshot": path,
            "recovered": [r.id for r in recovered],
            "recover_s": recover_s, "t": self.clock()})
        self._publish()

    def _recover(self, h: EngineHandle, *, cause: str):
        """Recover a fenced/draining engine's queued + in-flight work;
        returns ``(requests, source, snapshot_path, targets)``.

        The decision table (docs/serving.md "Fleet"): a committed
        drain snapshot is reused as-is; otherwise one is saved NOW
        under ``snapshot_dir/<engine>/`` (retry-wrapped — transient
        disk errors back off, :class:`SnapshotError` gives up at once:
        deterministic); if no snapshot is usable (no dir, save failed,
        or ``router_snapshot_missing`` forced it) the payload is built
        IN MEMORY from the engine's live entries and the work replays
        from prompt + generated-so-far. Both branches funnel through
        :func:`resume_requests`, so the recovered stream is
        token-identical either way (counter-based PRNG). Each dead
        trace segment closes as ``drained``; the survivor's ``begin``
        continues the same trace id with ``resumed_from`` set."""
        fail_idx = self._recoveries
        self._recoveries += 1
        path: Optional[str] = None
        payload: Optional[Dict[str, Any]] = None
        source = "snapshot"
        if not faults.should_skip_router_snapshot(fail_idx):
            if h.batcher.drained_snapshot is not None:
                path = h.batcher.drained_snapshot
            elif self.snapshot_dir is not None:
                try:
                    path = retry_call(
                        _sresil.save_snapshot, h.batcher,
                        os.path.join(self.snapshot_dir, h.name),
                        step=h.batcher.step_idx,
                        reason=f"fleet recovery ({cause})",
                        retries=self.step_retries,
                        base_delay=self.retry_base_delay, jitter=0.0,
                        retry_on=(OSError,),
                        give_up_on=(_sresil.SnapshotError,),
                        sleep=self.sleep, site="fleet_snapshot")
                except Exception:  # noqa: BLE001 — degrade to replay
                    path = None
            if path is not None:
                try:
                    payload = _sresil.load_snapshot(path)
                except _sresil.SnapshotError:
                    payload, path = None, None
        if payload is None:
            source = "replay"
            payload = {"format": _sresil.SNAPSHOT_FORMAT,
                       "step": h.batcher.step_idx,
                       "requests": h.batcher._snapshot_entries()}
        requests, prior = _sresil.resume_requests(payload)
        # fence the seat against stragglers: a late submit() to this
        # batcher now refuses with the structured `draining` reason
        h.batcher.draining = True
        tr = self.tracer
        now = self.clock()
        for req in requests:
            if tr is not None and tr.enabled:
                tr.drained(req.id, now, snapshot=path)
        with self._lock:
            for rid, toks in prior.items():
                self._prior[rid] = self._prior.get(rid, []) + list(toks)
            pool = [p for p in self._engines.values()
                    if p is not h and p.status in ("active", "stalled")]
        targets: Dict[str, Optional[str]] = {}
        for req in requests:
            if pool:
                # recovery overrides shed deprioritization: refusing
                # already-accepted work would BE the silent drop
                open_pool = self._admission_pool(
                    [p for p in pool if not self._shedding(p)] or pool)
                t = self._place(open_pool, req.prompt)
                self._submit_to(t, req)
                targets[str(req.id)] = t.name
            else:
                with self._lock:
                    self._orphans.append(req)
                targets[str(req.id)] = None
        if requests:
            self._registry.counter(
                "fleet_requests_rerouted",
                "requests moved between engines by cause").inc(
                len(requests), cause=cause)
        return requests, source, path, targets

    # -- results + views -----------------------------------------------------

    def merge_results(self) -> List[RequestResult]:
        """Drain every engine (fenced seats included — results that
        finished before a death must still reach the caller) plus the
        router's own structured refusals, stitching recovered streams
        back together: each resumed result's tokens become
        ``prior + tokens``, so the caller sees the FULL stream,
        token-identical to an uninterrupted run."""
        with self._lock:
            out, self._refused = self._refused, []
            handles = list(self._engines.values()) + list(self._retired)
        for h in handles:
            out.extend(h.batcher.drain())
        merged = _sresil.merge_results(out, self._prior)
        with self._lock:
            for r in merged:
                self._prior.pop(r.id, None)
        return merged

    def idle(self) -> bool:
        with self._lock:
            if self._orphans:
                return False
            live = [h for h in self._engines.values()
                    if h.status in ("warming", "active", "stalled")]
        return all(h.batcher.idle() for h in live)

    def introspect(self) -> Dict[str, Any]:
        """The live fleet view (``tools/serving_top.py`` renders it;
        ``fleet_engine_lost`` bundles embed the victim's last one):
        per-engine health + nested engine introspects, the failover
        log, and the router's routing posture."""
        now = self.clock()
        with self._lock:
            handles = list(self._engines.values())
            orphans = len(self._orphans)
            refused = len(self._refused)
        engines: Dict[str, Any] = {}
        for h in handles:
            try:
                intro = h.batcher.introspect()
            except Exception:  # noqa: BLE001 — a dead engine may not
                intro = None
            engines[h.name] = {
                "status": h.status, "index": h.index,
                "role": h.role,
                "heartbeat_age_s": round(now - h.last_beat, 6),
                "last_step_s": round(h.last_step_s, 6),
                "step_failures": h.step_failures,
                "hedged": h.hedged, "error": h.error,
                "handoffs_out": h.handoffs_out,
                "handoffs_in": h.handoffs_in,
                "shedding": (self._shedding(h)
                             if h.status in ("active", "stalled")
                             else False),
                "engine": intro,
            }
        return {"step": self.step_idx, "placement": self.placement,
                "stall_after_s": self.stall_after_s,
                "engines": engines, "orphans": orphans,
                "refused_pending": refused,
                "handoff": {
                    **{k: int(n)
                       for k, n in self.handoff_stats.items()},
                    "fallback": {
                        "latched": self._fallback,
                        "since_step": self._fallback_step,
                        "consecutive_failures": self._handoff_failures,
                    }},
                "failovers": [dict(f) for f in self.failovers]}

    def _publish(self) -> None:
        reg = self._registry
        with self._lock:
            handles = (list(self._engines.values())
                       + list(self._retired))
        counts = {s: 0 for s in ENGINE_STATES}
        for h in handles:
            counts[h.status] = counts.get(h.status, 0) + 1
        g = reg.gauge("fleet_engines", "engines by lifecycle state")
        for state, n in counts.items():
            g.set(n, state=state)
        up = reg.gauge("fleet_engine_up",
                       "1 while the engine is serving traffic")
        step_s = reg.gauge("fleet_engine_step_seconds",
                           "wall seconds of the engine's last step")
        depth = reg.gauge("fleet_engine_queue_depth",
                          "requests queued on the engine")
        for h in handles:
            up.set(1.0 if h.status in ("active", "stalled") else 0.0,
                   engine=h.name)
            step_s.set(h.last_step_s, engine=h.name)
            depth.set(len(h.batcher.queue), engine=h.name)
        reg.gauge(
            "fleet_colocated_fallback_latched",
            "1 while the colocated-fallback latch is closed").set(
            1.0 if self._fallback else 0.0)


def fleet_serve_loop(router: FleetRouter, requests: Sequence[Request],
                     *, arrivals: Optional[Sequence[float]] = None,
                     clock: Callable[[], float] = time.perf_counter,
                     sleep: Callable[[float], None] = time.sleep):
    """Drive the fleet over an arrival schedule until every request
    resolves (finished, recovered-and-finished, or structurally
    refused); returns the merged results. The fleet analog of
    ``serve_loop`` — same arrival semantics, but the router (not one
    engine) owns admission, and a mid-run engine death resolves
    through failover instead of ending the loop."""
    order = sorted(range(len(requests)),
                   key=lambda i: arrivals[i] if arrivals else 0.0)
    t0 = clock()
    results: List[RequestResult] = []
    i = 0
    while i < len(order) or not router.idle():
        if not any(h.status in ("active", "stalled")
                   for h in router.engines()):
            raise RuntimeError(
                "fleet_serve_loop: no serviceable engine left and "
                "work is still pending")
        now = clock() - t0
        while (i < len(order)
               and (not arrivals or arrivals[order[i]] <= now)):
            router.submit(requests[order[i]])
            i += 1
        if router.idle():
            if i < len(order):
                sleep(max(0.0, min(arrivals[order[i]] - now, 0.001)))
            continue
        router.step()
        results.extend(router.merge_results())
    results.extend(router.merge_results())
    return results


__all__ = [
    "ENGINE_ROLES",
    "ENGINE_STATES",
    "EngineHandle",
    "FleetRouter",
    "fleet_serve_loop",
]

"""Donation-aware jitted prefill + decode steps over the paged cache.

The serving analog of ``optimizers/train_step.py``: each step is ONE
compiled program with the cache pools DONATED (``donate_argnums``), so
a decode step appends K/V in place — the pool never holds two copies,
and the hot loop allocates nothing. The per-shape compile cache is an
eviction-free dict keyed on the bucketed shapes:

- decode: ``(batch_bucket, table_width)`` — the only dynamic shapes a
  decode dispatch has;
- prefill: ``(batch_bucket, seq_bucket, table_width)``;
- prefill_chunk: ``(batch_bucket, chunk_bucket, table_width)`` — the
  chunk-resumable prefill (chunked prefill / prefix-cache resume),
  which gathers the already-written context and appends the chunk.

Every NEW key is observed by the PR-6 compile tracker
(``telemetry.compiled.observe``) under ``fn="decode_step"`` /
``fn="prefill_step"`` / ``fn="prefill_chunk"`` and the compiling
dispatch runs inside a ``label(...)`` scope, so decode-shape churn
shows up as ``recompile`` events with a signature diff — and a
scheduler that buckets properly triggers ZERO recompile events after
warmup (tools/check_serving.sh pins it). Cache hits never reach the
tracker: the hot loop is one dict lookup.

Fused hot path (PAPERS.md "LLM Inference Acceleration via Efficient
Operation Fusion" — the prefill/decode analog of PR 1's fused
optimizer step): prefill runs embed -> L layers -> final norm -> LM
head -> last-token logit gather -> cache scatter as one program;
decode runs gather -> single-query attention (per-layer, inside the
layer scan) -> logits -> token selection -> cache append as one
program. Token selection is FUSED in-program too: a per-lane
temperature / top-k / top-p sampler draws from a counter-based PRNG
key (``fold_in(PRNGKey(seed), emitted_token_index)`` — pure function
of the request seed and the token's sequence index, so a drain/resume
replay regenerates the identical stream), gated by ``lax.cond`` so an
all-greedy batch never pays the sort. ``temperature == 0`` lanes take
the greedy argmax — bitwise the pre-sampling behavior. Nothing
round-trips to the host but the (b,) next-token ids, the (b,) finite
flags, and the (b, vocab) logits.

Both steps are teacher-forcing-friendly: they return the raw last
logits next to the selected ids, so the parity suite replays a known
sequence through decode and compares against the full-sequence
forward (tests/test_serving.py).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import numpy as np

from apex_tpu.serving.kv_cache import (
    KVCache,
    KVCacheState,
    append_kv,
    append_kv_chunk,
    append_kv_prefill,
    gather_kv,
)


class StepOut(NamedTuple):
    """One prefill/decode dispatch's results (device arrays)."""

    logits: Any        # (batch, vocab) fp32 — the LAST real token's
    # (batch,) int32 — the selected next token: per-lane fused
    # temperature/top-k/top-p sample, or the greedy argmax for
    # temperature == 0 lanes (bitwise the pre-sampling behavior)
    next_token: Any
    cache: KVCacheState
    # (batch,) bool — every logit of the lane is finite. Computed
    # IN-JIT (one fused reduction over logits the program already
    # holds), so per-request fault isolation costs the host a (b,)
    # bool pull instead of the full (b, vocab) logits
    # (serving/resilience.py quarantine path). None on older callers.
    finite: Any = None


def greedy_sampling(b: int) -> Tuple[np.ndarray, ...]:
    """The all-greedy sampling arrays for a batch of ``b`` lanes —
    what every dispatch uses when the caller passes ``sampling=None``
    (temperature 0, no top-k, top-p 1, seed 0)."""
    return (np.zeros(b, np.float32), np.zeros(b, np.int32),
            np.ones(b, np.float32), np.zeros(b, np.uint32))


class DecodeStep:
    """Compiled prefill + decode dispatchers for one (model, cache).

    Build via :func:`make_decode_step`. The cache state passed to
    either method is DONATED — rebind it to ``out.cache``; the buffers
    you passed in are dead after the call.
    """

    def __init__(self, model, cache: KVCache):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.cache = cache
        self._compiled: Dict[Tuple, Any] = {}
        cfg = model.config
        max_pos = cfg.max_seq_len - 1

        def select_token(out, sampling, fold_pos):
            """Fused in-program token selection over the (b, vocab)
            fp32 logits ``out``: greedy argmax for temperature-0
            lanes (bitwise the pre-sampling path), a per-lane
            temperature/top-k/top-p gumbel-max draw otherwise. The
            PRNG key is counter-based — ``fold_in(PRNGKey(seed),
            fold_pos)`` with ``fold_pos`` the emitted token's global
            sequence index — so replaying a prefix regenerates the
            identical stream (the drain/resume contract)."""
            temps, top_ks, top_ps, seeds = sampling
            greedy = jnp.argmax(out, axis=-1).astype(jnp.int32)

            def sample(_):
                b, v = out.shape
                t = jnp.where(temps > 0, temps, 1.0).astype(jnp.float32)
                scaled = out.astype(jnp.float32) / t[:, None]
                sdesc = -jnp.sort(-scaled, axis=-1)     # descending
                kk = jnp.where(top_ks > 0, jnp.clip(top_ks, 1, v),
                               v).astype(jnp.int32)
                kth = jnp.take_along_axis(sdesc, (kk - 1)[:, None],
                                          axis=1)
                probs = jax.nn.softmax(sdesc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # nucleus: keep the smallest prefix whose mass >= p
                # (entry i survives iff the mass BEFORE it is < p)
                keep = jnp.concatenate(
                    [jnp.ones((b, 1), bool),
                     cum[:, :-1] < top_ps[:, None]], axis=1)
                n_keep = jnp.sum(keep, axis=-1).astype(jnp.int32)
                pth = jnp.take_along_axis(sdesc, (n_keep - 1)[:, None],
                                          axis=1)
                thresh = jnp.maximum(kth, pth)
                masked = jnp.where(scaled >= thresh, scaled, -jnp.inf)

                def one(seed, pos, row):
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(seed), pos)
                    g = jax.random.gumbel(key, row.shape, jnp.float32)
                    return jnp.argmax(row + g)

                drawn = jax.vmap(one)(seeds, fold_pos,
                                      masked).astype(jnp.int32)
                return jnp.where(temps > 0, drawn, greedy)

            # an all-greedy batch never pays the sort/softmax/cumsum
            return jax.lax.cond(jnp.any(temps > 0), sample,
                                lambda _: greedy, None)

        def prefill_fn(params, state, tokens, lengths, tables, temps,
                       top_ks, top_ps, seeds):
            b, s = tokens.shape
            logits, (k_new, v_new) = model.apply(
                params, tokens, return_kv=True)
            state = append_kv_prefill(state, k_new, v_new, tables, lengths)
            last = jnp.clip(lengths - 1, 0, s - 1)
            out = logits[last, jnp.arange(b)]          # (b, vocab)
            # the emitted token lands at sequence index == prompt len
            nxt = select_token(out, (temps, top_ks, top_ps, seeds),
                               lengths)
            return StepOut(out, nxt, state,
                           jnp.all(jnp.isfinite(out), axis=-1))

        def prefill_chunk_fn(params, state, tokens, starts, lengths,
                             tables, temps, top_ks, top_ps, seeds):
            b, s = tokens.shape
            # gather BEFORE the chunk's writes: the context is every
            # previously-written position (< starts); the chunk's own
            # K/V rides kv_new inside the attention
            k_ctx, v_ctx = gather_kv(state, tables)
            L = k_ctx.shape[3]
            ctx_mask = (jnp.arange(L, dtype=jnp.int32)[None, :]
                        < starts[:, None])
            pos = jnp.clip(
                starts[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :],
                0, max_pos)
            logits, (k_new, v_new) = model.apply(
                params, tokens, positions=pos,
                kv_ctx=(k_ctx, v_ctx), ctx_mask=ctx_mask, return_kv=True)
            state = append_kv_chunk(state, k_new, v_new, tables, starts,
                                    lengths)
            last = jnp.clip(lengths - 1, 0, s - 1)
            out = logits[last, jnp.arange(b)]          # (b, vocab)
            # only meaningful on a prompt-completing chunk: the
            # emitted token's index is starts + chunk length
            nxt = select_token(out, (temps, top_ks, top_ps, seeds),
                               starts + lengths)
            return StepOut(out, nxt, state,
                           jnp.all(jnp.isfinite(out), axis=-1))

        def decode_fn(params, state, tokens, positions, tables, temps,
                      top_ks, top_ps, seeds):
            k_ctx, v_ctx = gather_kv(state, tables)
            L = k_ctx.shape[3]
            ctx_mask = (jnp.arange(L, dtype=jnp.int32)[None, :]
                        < positions[:, None])
            pos2 = jnp.clip(positions, 0, max_pos)[:, None]   # (b, 1)
            logits, (k_new, v_new) = model.apply(
                params, tokens[:, None], positions=pos2,
                kv_ctx=(k_ctx, v_ctx), ctx_mask=ctx_mask, return_kv=True)
            state = append_kv(state, k_new[:, :, :, 0], v_new[:, :, :, 0],
                              tables, positions)
            out = logits[0]                            # (b, vocab)
            # the emitted token lands at positions + 1
            nxt = select_token(out, (temps, top_ks, top_ps, seeds),
                               positions + 1)
            return StepOut(out, nxt, state,
                           jnp.all(jnp.isfinite(out), axis=-1))

        # cache state donated (argnums 1): appends run in place
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=(1,))
        self._prefill_chunk_jit = jax.jit(prefill_chunk_fn,
                                          donate_argnums=(1,))
        self._decode_jit = jax.jit(decode_fn, donate_argnums=(1,))
        self._jnp = jnp

    # -- compile-plane bookkeeping ------------------------------------------

    def _signature(self, fn: str, key: Tuple) -> Dict[str, Any]:
        cfg = self.model.config
        sig: Dict[str, Any] = {"fn": fn}
        if fn in ("prefill_step", "prefill_chunk"):
            sig.update(batch=key[1], seq=key[2], table_width=key[3])
        else:
            sig.update(batch=key[1], table_width=key[2])
        sig.update(block_size=self.cache.block_size,
                   kv_heads=self.cache.kv_heads,
                   head_dim=self.cache.head_dim,
                   num_layers=cfg.num_layers)
        return sig

    def _track(self, fn: str, key: Tuple) -> bool:
        """True when ``key`` is NEW — the dispatch about to run will
        trace+compile (the train-step ``_track`` discipline: hits are
        one dict lookup and never reach the tracker)."""
        if key in self._compiled:
            return False
        self._compiled[key] = True
        return True

    def _dispatch(self, fn: str, key: Tuple, jitted, *args) -> StepOut:
        if self._track(fn, key):
            from apex_tpu.telemetry import compiled as _compiled

            _compiled.observe(fn, self._signature(fn, key))
            from apex_tpu.mesh import mesh as _gspmd_mesh

            if _gspmd_mesh.mesh_initialized() \
                    and _gspmd_mesh.mesh_size() > 1:
                # mesh-armed serving: introspect+publish this key's
                # compiled shardings (sharding_devices{fn=}) BEFORE
                # the donating dispatch consumes the args — one extra
                # compile per NEW key, only when a real mesh is live
                from apex_tpu.telemetry import sharding as _sharding

                _sharding.publish_shardings(
                    _sharding.jitted_shardings(jitted, *args, fn=fn))
            with _compiled.label(fn):
                return jitted(*args)
        return jitted(*args)

    def compile_keys(self) -> Dict[str, int]:
        """Distinct compiled shapes per step kind (the bench/smoke
        assertion surface: the expected decode-bucket compile count)."""
        out: Dict[str, int] = {"prefill_step": 0, "prefill_chunk": 0,
                               "decode_step": 0}
        for key in self._compiled:
            out[key[0]] += 1
        return out

    # -- dispatchers ---------------------------------------------------------

    def _sampling_arrays(self, b: int, sampling):
        jnp = self._jnp
        if sampling is None:
            sampling = greedy_sampling(b)
        temps, top_ks, top_ps, seeds = sampling
        return (jnp.asarray(temps, jnp.float32),
                jnp.asarray(top_ks, jnp.int32),
                jnp.asarray(top_ps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32))

    def prefill(self, params, state: KVCacheState, tokens, lengths,
                tables, sampling=None) -> StepOut:
        """Run the full (right-padded) prompts, write their K/V into
        the pool, and return the LAST real token's logits — the first
        generated token's distribution — in one program.

        ``tokens`` (b, s) int32; ``lengths`` (b,) real prompt lengths;
        ``tables`` (b, w) block tables (trash-padded); ``sampling``
        optional ``(temps, top_ks, top_ps, seeds)`` per-lane arrays
        (None = all-greedy). Dummy batch rows use length 0 and an
        all-trash table.
        """
        jnp = self._jnp
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        key = ("prefill_step", tokens.shape[0], tokens.shape[1],
               tables.shape[1])
        return self._dispatch(
            "prefill_step", key, self._prefill_jit, params, state,
            tokens, lengths, tables,
            *self._sampling_arrays(tokens.shape[0], sampling))

    def prefill_chunk(self, params, state: KVCacheState, tokens,
                      starts, lengths, tables,
                      sampling=None) -> StepOut:
        """Resume prefill with one CHUNK per sequence: row ``i`` of
        lane ``b`` is the prompt token at global position
        ``starts[b] + i`` (``lengths[b]`` real rows, the rest pad).
        The chunk attends the already-written cache prefix (gathered
        in-program) plus itself causally, writes its K/V at the
        offset positions, and emits the last real row's logits — the
        first-token distribution when the chunk completes the prompt.
        One program, cache donated; the chunked-prefill hot path
        (docs/serving.md "Chunked prefill").
        """
        jnp = self._jnp
        tokens = jnp.asarray(tokens, jnp.int32)
        starts = jnp.asarray(starts, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        key = ("prefill_chunk", tokens.shape[0], tokens.shape[1],
               tables.shape[1])
        return self._dispatch(
            "prefill_chunk", key, self._prefill_chunk_jit, params,
            state, tokens, starts, lengths, tables,
            *self._sampling_arrays(tokens.shape[0], sampling))

    def decode(self, params, state: KVCacheState, tokens, positions,
               tables, sampling=None) -> StepOut:
        """One token per sequence: gather each sequence's cache view,
        attend (single query, per-sequence length via the mask), emit
        logits + the selected next token, and append the new K/V at
        ``positions`` — one program, cache donated.

        ``tokens`` (b,) int32 current tokens; ``positions`` (b,) their
        0-based positions (== the cached prefix length); ``sampling``
        optional per-lane ``(temps, top_ks, top_ps, seeds)`` (None =
        all-greedy). Dummy batch rows use position 0 and an all-trash
        table.
        """
        jnp = self._jnp
        tokens = jnp.asarray(tokens, jnp.int32)
        positions = jnp.asarray(positions, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        key = ("decode_step", tokens.shape[0], tables.shape[1])
        return self._dispatch(
            "decode_step", key, self._decode_jit, params, state,
            tokens, positions, tables,
            *self._sampling_arrays(tokens.shape[0], sampling))


def make_decode_step(model, cache: KVCache) -> DecodeStep:
    """Build the compiled serving steps for ``model`` (a
    :class:`~apex_tpu.models.gpt.GPTModel`) over ``cache``.

    The returned :class:`DecodeStep` donates the cache state on every
    dispatch and keeps an eviction-free per-shape compile cache
    observed by the compile tracker (module docstring)."""
    return DecodeStep(model, cache)


__all__ = ["DecodeStep", "StepOut", "greedy_sampling", "make_decode_step"]

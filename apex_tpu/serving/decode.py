"""Donation-aware jitted prefill + decode steps over the paged cache.

The serving analog of ``optimizers/train_step.py``: each step is ONE
compiled program with the cache pools DONATED (``donate_argnums``), so
a decode step appends K/V in place — the pool never holds two copies,
and the hot loop allocates nothing. The per-shape compile cache is an
eviction-free dict keyed on the bucketed shapes:

- decode: ``(batch_bucket, table_width)`` — the only dynamic shapes a
  decode dispatch has;
- prefill: ``(batch_bucket, seq_bucket, table_width)``.

Every NEW key is observed by the PR-6 compile tracker
(``telemetry.compiled.observe``) under ``fn="decode_step"`` /
``fn="prefill_step"`` and the compiling dispatch runs inside a
``label(...)`` scope, so decode-shape churn shows up as ``recompile``
events with a signature diff — and a scheduler that buckets properly
triggers ZERO recompile events after warmup (tools/check_serving.sh
pins it). Cache hits never reach the tracker: the hot loop is one
dict lookup.

Fused hot path (PAPERS.md "LLM Inference Acceleration via Efficient
Operation Fusion" — the prefill/decode analog of PR 1's fused
optimizer step): prefill runs embed -> L layers -> final norm -> LM
head -> last-token logit gather -> cache scatter as one program;
decode runs gather -> single-query attention (per-layer, inside the
layer scan) -> logits -> greedy argmax -> cache append as one program.
Nothing round-trips to the host but the (b,) next-token ids and the
(b, vocab) logits.

Both steps are teacher-forcing-friendly: they return the raw last
logits next to the argmax ids, so the parity suite replays a known
sequence through decode and compares against the full-sequence
forward (tests/test_serving.py).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

from apex_tpu.serving.kv_cache import (
    KVCache,
    KVCacheState,
    append_kv,
    append_kv_prefill,
    gather_kv,
)


class StepOut(NamedTuple):
    """One prefill/decode dispatch's results (device arrays)."""

    logits: Any        # (batch, vocab) fp32 — the LAST real token's
    next_token: Any    # (batch,) int32 greedy argmax of ``logits``
    cache: KVCacheState
    # (batch,) bool — every logit of the lane is finite. Computed
    # IN-JIT (one fused reduction over logits the program already
    # holds), so per-request fault isolation costs the host a (b,)
    # bool pull instead of the full (b, vocab) logits
    # (serving/resilience.py quarantine path). None on older callers.
    finite: Any = None


class DecodeStep:
    """Compiled prefill + decode dispatchers for one (model, cache).

    Build via :func:`make_decode_step`. The cache state passed to
    either method is DONATED — rebind it to ``out.cache``; the buffers
    you passed in are dead after the call.
    """

    def __init__(self, model, cache: KVCache):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.cache = cache
        self._compiled: Dict[Tuple, Any] = {}
        cfg = model.config
        max_pos = cfg.max_seq_len - 1

        def prefill_fn(params, state, tokens, lengths, tables):
            b, s = tokens.shape
            logits, (k_new, v_new) = model.apply(
                params, tokens, return_kv=True)
            state = append_kv_prefill(state, k_new, v_new, tables, lengths)
            last = jnp.clip(lengths - 1, 0, s - 1)
            out = logits[last, jnp.arange(b)]          # (b, vocab)
            return StepOut(out, jnp.argmax(out, axis=-1).astype(jnp.int32),
                           state, jnp.all(jnp.isfinite(out), axis=-1))

        def decode_fn(params, state, tokens, positions, tables):
            k_ctx, v_ctx = gather_kv(state, tables)
            L = k_ctx.shape[3]
            ctx_mask = (jnp.arange(L, dtype=jnp.int32)[None, :]
                        < positions[:, None])
            pos2 = jnp.clip(positions, 0, max_pos)[:, None]   # (b, 1)
            logits, (k_new, v_new) = model.apply(
                params, tokens[:, None], positions=pos2,
                kv_ctx=(k_ctx, v_ctx), ctx_mask=ctx_mask, return_kv=True)
            state = append_kv(state, k_new[:, :, :, 0], v_new[:, :, :, 0],
                              tables, positions)
            out = logits[0]                            # (b, vocab)
            return StepOut(out, jnp.argmax(out, axis=-1).astype(jnp.int32),
                           state, jnp.all(jnp.isfinite(out), axis=-1))

        # cache state donated (argnums 1): appends run in place
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=(1,))
        self._decode_jit = jax.jit(decode_fn, donate_argnums=(1,))
        self._jnp = jnp

    # -- compile-plane bookkeeping ------------------------------------------

    def _signature(self, fn: str, key: Tuple) -> Dict[str, Any]:
        cfg = self.model.config
        sig: Dict[str, Any] = {"fn": fn}
        if fn == "prefill_step":
            sig.update(batch=key[1], seq=key[2], table_width=key[3])
        else:
            sig.update(batch=key[1], table_width=key[2])
        sig.update(block_size=self.cache.block_size,
                   kv_heads=self.cache.kv_heads,
                   head_dim=self.cache.head_dim,
                   num_layers=cfg.num_layers)
        return sig

    def _track(self, fn: str, key: Tuple) -> bool:
        """True when ``key`` is NEW — the dispatch about to run will
        trace+compile (the train-step ``_track`` discipline: hits are
        one dict lookup and never reach the tracker)."""
        if key in self._compiled:
            return False
        self._compiled[key] = True
        return True

    def _dispatch(self, fn: str, key: Tuple, jitted, *args) -> StepOut:
        if self._track(fn, key):
            from apex_tpu.telemetry import compiled as _compiled

            _compiled.observe(fn, self._signature(fn, key))
            with _compiled.label(fn):
                return jitted(*args)
        return jitted(*args)

    def compile_keys(self) -> Dict[str, int]:
        """Distinct compiled shapes per step kind (the bench/smoke
        assertion surface: the expected decode-bucket compile count)."""
        out: Dict[str, int] = {"prefill_step": 0, "decode_step": 0}
        for key in self._compiled:
            out[key[0]] += 1
        return out

    # -- dispatchers ---------------------------------------------------------

    def prefill(self, params, state: KVCacheState, tokens, lengths,
                tables) -> StepOut:
        """Run the full (right-padded) prompts, write their K/V into
        the pool, and return the LAST real token's logits — the first
        generated token's distribution — in one program.

        ``tokens`` (b, s) int32; ``lengths`` (b,) real prompt lengths;
        ``tables`` (b, w) block tables (trash-padded). Dummy batch rows
        use length 0 and an all-trash table.
        """
        jnp = self._jnp
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        key = ("prefill_step", tokens.shape[0], tokens.shape[1],
               tables.shape[1])
        return self._dispatch("prefill_step", key, self._prefill_jit,
                              params, state, tokens, lengths, tables)

    def decode(self, params, state: KVCacheState, tokens, positions,
               tables) -> StepOut:
        """One token per sequence: gather each sequence's cache view,
        attend (single query, per-sequence length via the mask), emit
        logits + greedy ids, and append the new K/V at ``positions`` —
        one program, cache donated.

        ``tokens`` (b,) int32 current tokens; ``positions`` (b,) their
        0-based positions (== the cached prefix length). Dummy batch
        rows use position 0 and an all-trash table.
        """
        jnp = self._jnp
        tokens = jnp.asarray(tokens, jnp.int32)
        positions = jnp.asarray(positions, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        key = ("decode_step", tokens.shape[0], tables.shape[1])
        return self._dispatch("decode_step", key, self._decode_jit,
                              params, state, tokens, positions, tables)


def make_decode_step(model, cache: KVCache) -> DecodeStep:
    """Build the compiled serving steps for ``model`` (a
    :class:`~apex_tpu.models.gpt.GPTModel`) over ``cache``.

    The returned :class:`DecodeStep` donates the cache state on every
    dispatch and keeps an eviction-free per-shape compile cache
    observed by the compile tracker (module docstring)."""
    return DecodeStep(model, cache)


__all__ = ["DecodeStep", "StepOut", "make_decode_step"]

"""Serving tier: paged KV cache, donation-aware decode step, and a
continuous-batching scheduler (ROADMAP item 1, docs/serving.md).

Opens the inference half of the north star over the existing stack:
the decode/prefill hot path is jitted and donation-aware in the
``optimizers/train_step.py`` discipline (cache pools donated, an
eviction-free per-shape compile cache observed by the PR-6 compile
tracker), the KV cache is block-paged over one preallocated pool
(GQA-sized blocks from ``GPTConfig.kv_heads``), and the scheduler is
instrumented end-to-end with the PR-4/5 telemetry spine plus
flight-recorder triggers for its degradation paths.

    from apex_tpu.serving import (KVCache, make_decode_step,
                                  ContinuousBatcher, serve_loop)

    cache = KVCache.for_config(cfg, num_blocks=256)
    state = cache.init_state()
    batcher = ContinuousBatcher(model, params, cache)
    state, results = serve_loop(batcher, state, requests)

``bench.py serving`` drives the same loop under synthetic many-client
load (Poisson arrivals, mixed lengths) against a static-batch
baseline.

The resilience plane (``serving/resilience.py``, docs/serving.md
"Failure modes & recovery") makes the engine degrade per-request:
deadlines (``Request.deadline_ms``), per-request fault isolation
(binary-split quarantine + in-jit nonfinite localization),
preemption-safe drain snapshots a fresh engine resumes bitwise, and
live weight hot-swap (``swap_weights``) at step boundaries.

The hot-path plane (docs/serving.md "Chunked prefill" / "Prefix
cache"): chunked prefill (``ContinuousBatcher(prefill_chunk=...)``)
advances long prompts one bucketed chunk per step co-scheduled with
decode, prefix-sharing KV reuse hands repeated prompt prefixes out as
refcounted read-only blocks with copy-on-write at the divergence
block, and token selection (temperature/top-k/top-p, per-request
counter-based PRNG) is fused inside the decode program —
``temperature=0`` stays bitwise-greedy.

The request plane (``serving/tracing.py`` + ``telemetry/slo.py``,
docs/observability.md "Request plane"): ``RequestTracer`` follows one
request through queued → prefill chunks → decode → quarantine/drain
with perfetto export one track per request (trace ids survive drain/
resume), ``SLOMonitor`` watches TTFT/TPOT/goodput/queue-depth
objectives with multi-window burn-rate alerting and feeds the
``should_shed()`` admission hook, and ``ContinuousBatcher.introspect``
(rendered by ``tools/serving_top.py``) is the live view.

The fleet plane (``serving/fleet.py``, docs/serving.md "Fleet"):
``FleetRouter`` fronts N engines behind one submit/step/merge surface
— prefix-affinity placement over each engine's hash-chain prefix
index, SLO-shed deprioritization with a structured fleet-wide refusal,
kill/replace failover that recovers a dead engine's work via drain
snapshots (or prompt+generated replay) with token-identical streams
and trace continuity across engines, bounded hedging for stalled
engines, and elastic ``add_engine`` / ``remove_engine`` membership.
``add_engine(role=...)`` splits the fleet into disaggregated
``prefill`` / ``decode`` seats (DistServe-style): prefill-complete
streams move over a manifest-verified KV-block handoff
(``KVCache.export_blocks`` / ``import_blocks``) with retries, crash
replay, orphan scrub, and a colocated-fallback latch behind it — zero
dropped requests on every failure rung.
"""

from apex_tpu.serving.decode import (
    DecodeStep,
    StepOut,
    greedy_sampling,
    make_decode_step,
)
from apex_tpu.serving.kv_cache import (
    KVCache,
    KVCacheState,
    PoolExhausted,
    PrefixMatch,
    TRASH_BLOCK,
    append_kv,
    append_kv_chunk,
    append_kv_prefill,
    apply_copies,
    bucket,
    gather_kv,
    scrub_blocks,
)
from apex_tpu.serving.resilience import (
    SnapshotError,
    WeightSwapError,
    latest_snapshot,
    load_snapshot,
    merge_results,
    params_digest,
    params_fingerprint,
    params_signature,
    resume_requests,
    save_snapshot,
    swap_weights,
    validate_snapshot,
)
from apex_tpu.serving.scheduler import (
    ContinuousBatcher,
    Request,
    RequestResult,
    serve_loop,
    static_batch_generate,
)
from apex_tpu.serving.tracing import (
    RequestTrace,
    RequestTracer,
)

# imported LAST: fleet.py consumes the scheduler/resilience/tracing
# modules above at import time (the router fronts all of them)
from apex_tpu.serving.fleet import (  # noqa: E402
    ENGINE_ROLES,
    ENGINE_STATES,
    EngineHandle,
    FleetRouter,
    fleet_serve_loop,
)

__all__ = [
    "ContinuousBatcher",
    "ENGINE_ROLES",
    "ENGINE_STATES",
    "EngineHandle",
    "FleetRouter",
    "DecodeStep",
    "KVCache",
    "KVCacheState",
    "PoolExhausted",
    "PrefixMatch",
    "Request",
    "RequestResult",
    "RequestTrace",
    "RequestTracer",
    "SnapshotError",
    "StepOut",
    "TRASH_BLOCK",
    "WeightSwapError",
    "append_kv",
    "append_kv_chunk",
    "append_kv_prefill",
    "apply_copies",
    "bucket",
    "fleet_serve_loop",
    "gather_kv",
    "greedy_sampling",
    "latest_snapshot",
    "load_snapshot",
    "make_decode_step",
    "merge_results",
    "params_digest",
    "params_fingerprint",
    "params_signature",
    "resume_requests",
    "save_snapshot",
    "scrub_blocks",
    "serve_loop",
    "static_batch_generate",
    "swap_weights",
    "validate_snapshot",
]

"""Serving tier: paged KV cache, donation-aware decode step, and a
continuous-batching scheduler (ROADMAP item 1, docs/serving.md).

Opens the inference half of the north star over the existing stack:
the decode/prefill hot path is jitted and donation-aware in the
``optimizers/train_step.py`` discipline (cache pools donated, an
eviction-free per-shape compile cache observed by the PR-6 compile
tracker), the KV cache is block-paged over one preallocated pool
(GQA-sized blocks from ``GPTConfig.kv_heads``), and the scheduler is
instrumented end-to-end with the PR-4/5 telemetry spine plus
flight-recorder triggers for its degradation paths.

    from apex_tpu.serving import (KVCache, make_decode_step,
                                  ContinuousBatcher, serve_loop)

    cache = KVCache.for_config(cfg, num_blocks=256)
    state = cache.init_state()
    batcher = ContinuousBatcher(model, params, cache)
    state, results = serve_loop(batcher, state, requests)

``bench.py serving`` drives the same loop under synthetic many-client
load (Poisson arrivals, mixed lengths) against a static-batch
baseline.

The resilience plane (``serving/resilience.py``, docs/serving.md
"Failure modes & recovery") makes the engine degrade per-request:
deadlines (``Request.deadline_ms``), per-request fault isolation
(binary-split quarantine + in-jit nonfinite localization),
preemption-safe drain snapshots a fresh engine resumes bitwise, and
live weight hot-swap (``swap_weights``) at step boundaries.
"""

from apex_tpu.serving.decode import DecodeStep, StepOut, make_decode_step
from apex_tpu.serving.kv_cache import (
    KVCache,
    KVCacheState,
    PoolExhausted,
    TRASH_BLOCK,
    append_kv,
    append_kv_prefill,
    bucket,
    gather_kv,
)
from apex_tpu.serving.resilience import (
    SnapshotError,
    WeightSwapError,
    latest_snapshot,
    load_snapshot,
    merge_results,
    params_digest,
    params_fingerprint,
    params_signature,
    resume_requests,
    save_snapshot,
    swap_weights,
    validate_snapshot,
)
from apex_tpu.serving.scheduler import (
    ContinuousBatcher,
    Request,
    RequestResult,
    serve_loop,
    static_batch_generate,
)

__all__ = [
    "ContinuousBatcher",
    "DecodeStep",
    "KVCache",
    "KVCacheState",
    "PoolExhausted",
    "Request",
    "RequestResult",
    "SnapshotError",
    "StepOut",
    "TRASH_BLOCK",
    "WeightSwapError",
    "append_kv",
    "append_kv_prefill",
    "bucket",
    "gather_kv",
    "latest_snapshot",
    "load_snapshot",
    "make_decode_step",
    "merge_results",
    "params_digest",
    "params_fingerprint",
    "params_signature",
    "resume_requests",
    "save_snapshot",
    "serve_loop",
    "static_batch_generate",
    "swap_weights",
    "validate_snapshot",
]

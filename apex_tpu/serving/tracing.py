"""Per-request tracing: the serving tier's request plane.

The engine-level spans (``prefill`` / ``prefill_chunk`` / ``decode``,
serving/scheduler.py) answer "where did the STEP go"; nothing answered
"where did REQUEST 17 go" — queued for how long, admitted when, how
many prefill chunks, decoded over which window, quarantined or drained
why. This module is that answer: a :class:`RequestTrace` per request,
born when ``ContinuousBatcher.submit()`` mints its trace id, fed span/
mark hooks at every scheduler state transition, and exported as
perfetto JSON with ONE TRACK PER REQUEST riding the exact
`StepTimeline.export_trace` event format (complete ``"ph": "X"``
events, µs ``ts``/``dur``, thread-name metadata) — load it at
ui.perfetto.dev next to the engine trace.

Lifecycle of one trace (the scheduler's state machine, docs/serving.md):

- ``begin`` at ``submit()`` — mints the trace id (or CONTINUES one: a
  drain snapshot persists each request's trace id, and
  ``resilience.resume_requests`` hands it back with a ``resumed_from``
  annotation, so the resumed engine appends to the SAME trace);
- ``admitted`` closes the ``queued`` span (re-opened by a deadlock-
  breaking ``requeued`` mark) and records the admission mode
  (``direct`` monolithic prefill vs ``chunked``) + prefix-cache match;
- one ``prefill`` span per monolithic prefill, one ``prefill_chunk[i]``
  span per chunk dispatch (``i`` is the request's own chunk ordinal);
- decode participation coalesces into a WINDOW — per-dispatch spans at
  40 tokens/request would drown the track — flushed as one ``decode``
  span (args: tokens, dispatches) when the request leaves the engine;
- ``retry_split`` / ``quarantine`` marks from the binary-split fault
  isolation, ``first_token`` / ``prefill_stalled`` / ``requeued`` marks
  from the chunking plane, and a terminal ``finished`` mark carrying
  the outcome (``length`` / ``eos`` / ``error`` / ``deadline_exceeded``
  / ``drained``).

Completed traces land in a bounded keep-last-``keep`` ring (a serving
process must not grow a trace per request forever); live traces are
always exported. The flight recorder's ``slo_violation`` bundles embed
the offending requests' trace dicts (telemetry/slo.py), so a latency
postmortem opens WITH the slow requests' timelines in hand.

Overhead discipline (the ``disabled is step`` rule,
tools/check_serving.sh): a batcher built with ``tracer=None`` — the
default — pays one attribute load + None check per hook site; an armed
tracer costs a dict lookup and a list append per span. Everything is
host-side Python: no jax import, nothing traced, nothing added to a
jitted program.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

# terminal outcomes a trace can end with (the RequestResult
# finish_reason vocabulary plus the engine-side terminals; `rerouted`
# closes one ENGINE's segment when the fleet router hands the request
# to a peer — the next segment continues the same trace id)
OUTCOMES = ("length", "eos", "error", "deadline_exceeded", "rejected",
            "drained", "rerouted")


class RequestTrace:
    """One request's timeline: spans (name, t0, dur, args), point
    marks (name, t, args), and the terminal outcome. Timestamps are
    absolute tracer-clock seconds; the perfetto export rebases them on
    the tracer origin. ``max_spans`` bounds memory per trace —
    overflow is counted (``dropped``), never silent."""

    __slots__ = ("trace_id", "request_id", "t_submit", "resumed_from",
                 "state", "outcome", "error", "t_finish", "spans",
                 "marks", "dropped", "chunk_idx", "queued_since",
                 "_decode", "_max_spans")

    def __init__(self, trace_id: str, request_id: Any, t_submit: float,
                 *, resumed_from: Optional[str] = None,
                 max_spans: int = 512):
        self.trace_id = trace_id
        self.request_id = request_id
        self.t_submit = float(t_submit)
        self.resumed_from = resumed_from
        self.state = "queued"
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self.t_finish: Optional[float] = None
        self.spans: List[Dict[str, Any]] = []
        self.marks: List[Dict[str, Any]] = []
        self.dropped = 0
        self.chunk_idx = 0                 # prefill_chunk[i] ordinal
        self.queued_since = float(t_submit)
        self._decode: Optional[List[float]] = None  # [t0, end, n, toks]
        self._max_spans = int(max_spans)

    def add_span(self, name: str, t0: float, dur: float,
                 **args) -> None:
        if len(self.spans) >= self._max_spans:
            self.dropped += 1
            return
        self.spans.append({"name": str(name), "t0": float(t0),
                           "dur": float(dur), "args": args})

    def add_mark(self, name: str, t: float, **args) -> None:
        if len(self.marks) >= self._max_spans:
            self.dropped += 1
            return
        self.marks.append({"name": str(name), "t": float(t),
                           "args": args})

    def flush_decode(self) -> None:
        """Close the open decode window into one ``decode`` span."""
        w = self._decode
        if w is None:
            return
        self._decode = None
        t0, end, n, toks = w
        self.add_span("decode", t0, end - t0, dispatches=int(n),
                      tokens=int(toks))

    def to_dict(self) -> Dict[str, Any]:
        """JSON-able trace payload (what ``slo_violation`` bundles and
        drain postmortems embed)."""
        return {
            "trace_id": self.trace_id,
            "request_id": str(self.request_id),
            "t_submit": self.t_submit,
            "t_finish": self.t_finish,
            "state": self.state,
            "outcome": self.outcome,
            "error": self.error,
            "resumed_from": self.resumed_from,
            "spans": [dict(s) for s in self.spans],
            "marks": [dict(m) for m in self.marks],
            "dropped": self.dropped,
        }


class RequestTracer:
    """The request-plane recorder the scheduler's hooks feed.

    - ``keep``: bounded ring of COMPLETED traces (live traces are held
      until they finish, then rotate through the ring).
    - ``max_spans``: per-trace span/mark cap (overflow counted).
    - ``enabled``: a disarmed tracer makes every hook an immediate
      return — the scheduler additionally skips the calls entirely
      when no tracer is attached.

    Thread-safe: ``begin`` runs on client threads (``submit()``), the
    rest on the engine thread; one lock covers the trace maps.
    """

    def __init__(self, *, keep: int = 256, max_spans: int = 512,
                 enabled: bool = True,
                 clock: Callable[[], float] = time.perf_counter):
        self.enabled = bool(enabled)
        self.keep = int(keep)
        self.max_spans = int(max_spans)
        self.clock = clock
        self._origin = clock()
        self._lock = threading.Lock()
        self._live: Dict[Any, RequestTrace] = {}
        self._done: "deque[RequestTrace]" = deque(maxlen=self.keep)
        self._minted = 0
        self._finished = 0

    # -- lifecycle hooks (the scheduler's call sites) ----------------------

    def begin(self, request_id, *, t_submit: Optional[float] = None,
              trace_id: Optional[str] = None,
              resumed_from: Optional[str] = None) -> str:
        """Open a trace at ``submit()``; returns the trace id. A
        caller-provided ``trace_id`` (a resumed drain snapshot)
        CONTINUES that trace — same id, ``resumed_from`` annotating
        where the first half lives."""
        t = t_submit if t_submit is not None else self.clock()
        with self._lock:
            if trace_id is None:
                self._minted += 1
                trace_id = f"rq-{os.getpid():x}-{self._minted:06x}"
            tr = RequestTrace(trace_id, request_id, t,
                              resumed_from=resumed_from,
                              max_spans=self.max_spans)
            self._live[request_id] = tr
        if resumed_from is not None:
            tr.add_mark("resumed", t, resumed_from=resumed_from)
        return trace_id

    def _get(self, request_id) -> Optional[RequestTrace]:
        return self._live.get(request_id)

    def admitted(self, request_id, t: float, *, mode: str = "direct",
                 matched: int = 0) -> None:
        tr = self._get(request_id)
        if tr is None:
            return
        tr.add_span("queued", tr.queued_since, t - tr.queued_since)
        tr.add_mark("admitted", t, mode=mode, matched=int(matched))
        tr.state = "prefilling" if mode == "chunked" else "decoding"

    def span(self, request_id, name: str, t0: float, dur: float,
             **args) -> None:
        tr = self._get(request_id)
        if tr is not None:
            tr.add_span(name, t0, dur, **args)

    def chunk_span(self, request_id, t0: float, dur: float, *,
                   tokens: int) -> None:
        """One ``prefill_chunk[i]`` span, ``i`` the request's own
        chunk ordinal (not the engine's dispatch index)."""
        tr = self._get(request_id)
        if tr is None:
            return
        tr.add_span(f"prefill_chunk[{tr.chunk_idx}]", t0, dur,
                    tokens=int(tokens))
        tr.chunk_idx += 1

    def mark(self, request_id, name: str, t: Optional[float] = None,
             **args) -> None:
        tr = self._get(request_id)
        if tr is not None:
            tr.add_mark(name, t if t is not None else self.clock(),
                        **args)

    def requeued(self, request_id, t: float) -> None:
        """A deadlock-breaking requeue: back to QUEUED, the next
        ``queued`` span opens here (not at the original submit)."""
        tr = self._get(request_id)
        if tr is None:
            return
        tr.add_mark("requeued", t)
        tr.queued_since = t
        tr.state = "queued"

    def decoding(self, request_id) -> None:
        tr = self._get(request_id)
        if tr is not None:
            tr.state = "decoding"

    def decode_tick(self, request_id, t0: float, t1: float) -> None:
        """Fold one decode dispatch into the request's decode window
        (flushed as a single ``decode`` span at finish)."""
        tr = self._get(request_id)
        if tr is None:
            return
        w = tr._decode
        if w is None:
            tr._decode = [t0, t1, 1, 1]
        else:
            w[1] = max(w[1], t1)
            w[2] += 1
            w[3] += 1

    def finish(self, request_id, outcome: str, *,
               t: Optional[float] = None,
               error: Optional[str] = None, **args) -> None:
        """Terminal transition: flush the decode window, stamp the
        outcome, rotate the trace into the completed ring. Unknown ids
        (an untracked request) are a no-op."""
        with self._lock:
            tr = self._live.pop(request_id, None)
        if tr is None:
            return
        now = t if t is not None else self.clock()
        tr.flush_decode()
        tr.state = "finished"
        tr.outcome = str(outcome)
        tr.error = error
        tr.t_finish = now
        tr.add_mark("finished", now, outcome=str(outcome), **args)
        with self._lock:
            self._finished += 1
            self._done.append(tr)

    def drained(self, request_id, t: float, *,
                snapshot: Optional[str] = None) -> None:
        """The engine snapshotted this request mid-flight: the trace
        ends here with outcome ``drained``; the resumed engine's
        ``begin`` (same trace id, ``resumed_from`` set) continues the
        story on the other side of the kill."""
        self.finish(request_id, "drained", t=t,
                    snapshot=snapshot)

    # -- reading -----------------------------------------------------------

    def live(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._live.values())

    def completed(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._done)

    def trace_dicts(self, request_ids: Optional[Sequence[Any]] = None,
                    ) -> List[Dict[str, Any]]:
        """JSON-able trace payloads — completed then live, oldest
        first; ``request_ids`` filters (ids are compared as strings,
        matching the dict payload)."""
        with self._lock:
            traces = list(self._done) + list(self._live.values())
        if request_ids is not None:
            want = {str(i) for i in request_ids}
            traces = [t for t in traces if str(t.request_id) in want]
        return [t.to_dict() for t in traces]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled, "minted": self._minted,
                    "live": len(self._live), "completed": len(self._done),
                    "finished": self._finished, "keep": self.keep}

    # -- perfetto export ---------------------------------------------------

    def export_trace(self, path: Optional[str] = None, *,
                     request_ids: Optional[Sequence[Any]] = None,
                     ) -> Dict[str, Any]:
        """The request plane as Chrome-trace JSON — the SAME "JSON
        Array Format" ``StepTimeline.export_trace`` emits (complete
        ``"ph": "X"`` events, µs ``ts``/``dur`` relative to the tracer
        origin), but with ONE TRACK (tid) PER TRACE ID, labeled
        ``<request_id> (<trace_id>)`` via thread-name metadata — so the
        segments of a drained/resumed request, or one handed across
        engines by the fleet router, land on a single track telling the
        request's whole story. Marks ride as zero-duration events.
        Loadable at ui.perfetto.dev / chrome://tracing, side by side
        with the engine timeline when both use the default
        ``perf_counter`` clock."""
        with self._lock:
            traces = list(self._done) + list(self._live.values())
        if request_ids is not None:
            want = {str(i) for i in request_ids}
            traces = [t for t in traces if str(t.request_id) in want]
        pid = os.getpid()
        events: List[Dict[str, Any]] = []

        def us(t: float) -> float:
            return round((t - self._origin) * 1e6, 3)

        # one track (tid) per TRACE ID, not per trace object: a
        # drain/resume or a fleet-router handoff produces several
        # segments with the same trace id, and they must land on ONE
        # perfetto track — the request's whole story, crossing engines
        tids: Dict[str, int] = {}
        labels: Dict[int, str] = {}
        for tr in traces:
            tid = tids.setdefault(tr.trace_id, len(tids))
            # an unfinished trace still shows its open decode window
            spans = list(tr.spans)
            if tr._decode is not None:
                t0, end, n, toks = tr._decode
                spans.append({"name": "decode", "t0": t0,
                              "dur": end - t0,
                              "args": {"dispatches": int(n),
                                       "tokens": int(toks),
                                       "open": True}})
            for s in spans:
                events.append({
                    "name": s["name"], "cat": "request", "ph": "X",
                    "ts": us(s["t0"]),
                    "dur": round(s["dur"] * 1e6, 3),
                    "pid": pid, "tid": tid,
                    "args": {"trace_id": tr.trace_id, **s["args"]},
                })
            for m in tr.marks:
                events.append({
                    "name": m["name"], "cat": "request", "ph": "X",
                    "ts": us(m["t"]), "dur": 0.0,
                    "pid": pid, "tid": tid,
                    "args": {"trace_id": tr.trace_id, **m["args"]},
                })
            label = f"{tr.request_id} ({tr.trace_id})"
            if tr.resumed_from:
                label += f" resumed_from={tr.resumed_from}"
            labels[tid] = label  # last segment wins
        for tid, label in labels.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": label},
            })
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            tmp = f"{path}.tmp-{pid}"
            with open(tmp, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
        return trace


__all__ = [
    "OUTCOMES",
    "RequestTrace",
    "RequestTracer",
]

"""Serving-tier resilience: drain snapshots, resume, and weight hot-swap.

PR 8's serving engine stops degrading at admission control: before this
module a decode-step exception killed every in-flight request, a
SIGTERM dropped the whole queue, and new weights meant a restart. This
module (plus the scheduler/decode integration in
``serving/scheduler.py``) is the serving analog of the training-side
resilience stack — the north star's "heavy traffic from millions of
users" must degrade per-REQUEST, not per-process:

- **deadlines** — ``Request.deadline_ms`` is a TTL from submission;
  expired requests (queued or in-flight) are reaped at the top of every
  engine step, BEFORE admission and decode, with outcome
  ``deadline_exceeded`` (scheduler integration; counter
  ``serving_deadline_exceeded``).
- **quarantine** — a decode dispatch that raises is retried by binary
  split (the watchdog's localization idiom lifted to the batch axis):
  halves that succeed keep their tokens, the offending sequence(s)
  bottom out as singletons and finish with outcome ``error`` while the
  engine keeps serving. Nonfinite logits localize for free — the
  decode program's in-jit per-lane finite flag
  (:class:`~apex_tpu.serving.decode.StepOut`) names the poisoned
  lane(s) directly. Both paths fire the ``serving_quarantine`` flight
  trigger (replacing the old fail-everything ``serving_request_error``
  decode path).
- **drain snapshots** — when the scheduler's
  :class:`~apex_tpu.resilience.guard.PreemptionHandler` flags, the
  engine stops admitting and :func:`save_snapshot` persists every
  queued + in-flight request (prompt, generated-so-far tokens,
  deadline) as one sha256-manifested JSON under the checkpoint
  tmp→fsync→rename discipline
  (:func:`~apex_tpu.resilience.checkpoint.atomic_write_files`). A
  fresh engine resumes via :func:`resume_requests` — each in-flight
  prefix (prompt + generated) replays through the existing prefill
  path, so the resumed token stream is identical to the uninterrupted
  run — and :func:`merge_results` stitches the replayed prefixes back
  onto the resumed results.
- **weight hot-swap** — :func:`swap_weights` validates new params
  against the serving model's space signature (tree paths, shapes,
  dtypes; optionally a per-leaf ``guard.state_fingerprint``-style
  uint32 manifest from an elastic checkpoint), stages them on the
  engine, and the scheduler installs them at the next step boundary —
  between decode dispatches, so no request is dropped — emitting
  ``serving_weight_swap`` with old/new sha256 digests. A
  shape-mismatched swap raises :class:`WeightSwapError` carrying the
  structured per-leaf mismatch list and never touches the engine.

Fault clauses (resilience/faults.py, docs/resilience.md grammar):
``decode_nonfinite=<steps>`` (+ ``decode_nonfinite_lane=<i>``) poisons
one lane's cached K/V with NaN so its logits go nonfinite through the
real attention path; ``serving_snapshot_corrupt=<idx>`` truncates a
finalized drain snapshot; ``weight_swap_mismatch=<idx>`` forces the
swap validator to reject. ``tools/check_serving.sh`` drives the chaos
drill: 200 requests + ``decode_nonfinite`` + a mid-run SIGTERM must
quarantine only the poisoned sequence, snapshot the rest, resume, and
land >= 90% of the fault-free goodput with zero requests silently
dropped.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.resilience.checkpoint import atomic_write_files

SNAPSHOT_FORMAT = 1
SNAPSHOT_FILE = "snapshot.json"
SNAPSHOT_MANIFEST = "manifest.json"
_SNAP_RE = re.compile(r"^serving_(\d{12})$")


class SnapshotError(RuntimeError):
    """Unusable serving snapshot (missing, corrupt, or wrong format)."""


class WeightSwapError(RuntimeError):
    """A rejected weight hot-swap. ``mismatches`` is the structured
    per-leaf diff: ``[{"path", "expected", "got"}, ...]`` — shapes/
    dtypes/tree paths that disagree with the serving model's current
    signature (or the fingerprint row that failed)."""

    def __init__(self, msg: str, mismatches: List[Dict[str, Any]]):
        super().__init__(msg)
        self.mismatches = list(mismatches)


# ---------------------------------------------------------------------------
# Drain snapshots
# ---------------------------------------------------------------------------


def snapshot_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"serving_{int(step):012d}")


def save_snapshot(batcher, directory: str, *, step: int,
                  reason: str = "preemption") -> str:
    """Persist every queued + in-flight request of ``batcher`` as one
    atomic snapshot directory; returns the final path.

    The payload is JSON (request ids must be JSON-serializable —
    anything else cannot survive a process death anyway) with a sha256
    manifest; the write goes through the checkpoint discipline
    (tmp→fsync→rename), so a crash mid-drain leaves either nothing or
    a snapshot that verifies. The ``serving_snapshot_corrupt=<idx>``
    fault clause truncates the FINALIZED payload — exactly what
    :func:`latest_snapshot` must refuse.
    """
    entries = batcher._snapshot_entries()
    payload = {
        "format": SNAPSHOT_FORMAT,
        "step": int(step),
        "reason": str(reason),
        "utc": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "requests": entries,
    }
    # the armed goodput ledger rides the drain snapshot the same way
    # it rides training checkpoints — a killed-and-resumed serving
    # process keeps its run-level attribution
    from apex_tpu.telemetry import goodput as _goodput

    led = _goodput.get_ledger()
    if led is not None:
        payload["goodput"] = led.pack()
    data = json.dumps(payload, sort_keys=True).encode()
    manifest = {
        "format": SNAPSHOT_FORMAT,
        "step": int(step),
        "payload_bytes": len(data),
        "sha256": hashlib.sha256(data).hexdigest(),
        "n_requests": len(entries),
    }
    os.makedirs(directory, exist_ok=True)
    final = snapshot_path(directory, step)
    faults.check("serving_snapshot")
    atomic_write_files(final, {
        SNAPSHOT_FILE: data,
        SNAPSHOT_MANIFEST: json.dumps(manifest, indent=1,
                                      sort_keys=True).encode(),
    })
    idx = batcher._snapshot_count
    batcher._snapshot_count += 1
    if faults.should_snapshot_corrupt(idx):
        # simulated on-disk corruption of the FINALIZED snapshot
        with open(os.path.join(final, SNAPSHOT_FILE), "r+b") as f:
            f.truncate(max(1, len(data) // 2))
    reg = batcher._registry
    reg.counter("serving_snapshots",
                "serving drain snapshots committed").inc()
    reg.event("serving_snapshot_saved", path=final, step=int(step),
              n_requests=len(entries), reason=str(reason))
    return final


def validate_snapshot(path: str) -> Tuple[bool, str]:
    """(ok, reason): re-hash the payload against the manifest, so
    truncation/corruption is detected before a byte is parsed."""
    try:
        with open(os.path.join(path, SNAPSHOT_MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"manifest unreadable: {type(e).__name__}"
    if manifest.get("format") != SNAPSHOT_FORMAT:
        return False, f"unsupported format {manifest.get('format')!r}"
    ppath = os.path.join(path, SNAPSHOT_FILE)
    try:
        size = os.path.getsize(ppath)
    except OSError:
        return False, "payload missing"
    if size != manifest.get("payload_bytes"):
        return False, (f"payload truncated: {size} bytes, manifest says "
                       f"{manifest.get('payload_bytes')}")
    h = hashlib.sha256()
    try:
        with open(ppath, "rb") as f:
            h.update(f.read())
    except OSError as e:
        return False, f"payload unreadable: {type(e).__name__}"
    if h.hexdigest() != manifest.get("sha256"):
        return False, "sha256 mismatch"
    return True, ""


def load_snapshot(path: str) -> Dict[str, Any]:
    """Parse a snapshot that :func:`validate_snapshot` accepts; raises
    :class:`SnapshotError` otherwise — a rotten snapshot must never be
    resumed."""
    ok, reason = validate_snapshot(path)
    if not ok:
        raise SnapshotError(f"{path}: {reason}")
    with open(os.path.join(path, SNAPSHOT_FILE)) as f:
        return json.load(f)


def latest_snapshot(directory: str, *,
                    record_events: bool = True) -> Optional[str]:
    """Newest snapshot under ``directory`` that verifies, scanning
    newest -> oldest; corrupt ones are reported (counter
    ``serving_snapshot_corrupt_skipped`` + event) and skipped — the
    ``latest_valid()`` contract for the serving tier."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = sorted(int(m.group(1)) for m in map(_SNAP_RE.match, names)
                   if m)
    for step in reversed(steps):
        path = snapshot_path(directory, step)
        ok, reason = validate_snapshot(path)
        if ok:
            return path
        if record_events:
            from apex_tpu.telemetry import metrics as _metrics

            reg = _metrics.registry()
            reg.counter("serving_snapshot_corrupt_skipped",
                        "corrupt serving snapshots skipped by "
                        "latest_snapshot").inc()
            reg.event("corrupt_serving_snapshot", path=path, step=step,
                      reason=reason)
    return None


def resume_requests(snapshot: Dict[str, Any]):
    """Rebuild the requests a drained engine owed from a snapshot
    payload; returns ``(requests, prior)``.

    In-flight entries resume through the EXISTING prefill path: the
    replay prompt is ``prompt + generated`` (reconstructing the cache
    the dead engine held, bit-for-bit the same K/V the prefill scatter
    writes) and ``max_new_tokens`` shrinks by what was already
    generated, so the resumed engine's first emitted token is exactly
    the next one the uninterrupted run would have produced — for
    SAMPLED streams too: the per-request RNG is counter-based
    (``fold_in(seed, token index)``, serving/decode.py), and the
    snapshot carries the sampling knobs + seed, so the resumed
    engine's draws continue the stream token for token. ``prior``
    maps request id -> the already-generated prefix;
    :func:`merge_results` folds it back so callers see full token
    streams.

    The request plane rides along: each entry's persisted ``trace_id``
    is handed back on the rebuilt request with ``resumed_from`` naming
    the snapshot, so a traced resumed engine CONTINUES the same trace
    (serving/tracing.py) instead of minting a fresh one.
    """
    from apex_tpu.serving.scheduler import Request

    if snapshot.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotError(
            f"unsupported snapshot format {snapshot.get('format')!r}")
    # restart survival: fold the dead engine's goodput ledger (when the
    # snapshot carries one and this process's ledger is armed) into the
    # resumed process's cumulative attribution
    from apex_tpu.telemetry import goodput as _goodput

    _goodput.note_restored(snapshot)
    origin = f"serving_{int(snapshot.get('step', 0)):012d}"
    requests: List[Request] = []
    prior: Dict[Any, List[int]] = {}
    for e in snapshot.get("requests", []):
        generated = [int(t) for t in e.get("generated", [])]
        prompt = [int(t) for t in e["prompt"]] + generated
        remaining = int(e["max_new_tokens"]) - len(generated)
        if remaining < 1:          # finished at the snapshot boundary
            continue
        requests.append(Request(
            id=e["id"], prompt=prompt, max_new_tokens=remaining,
            eos_id=e.get("eos_id"), deadline_ms=e.get("deadline_ms"),
            temperature=float(e.get("temperature", 0.0)),
            top_k=int(e.get("top_k", 0)),
            top_p=float(e.get("top_p", 1.0)),
            seed=int(e.get("seed", 0)),
            trace_id=e.get("trace_id"),
            resumed_from=origin))
        prior[e["id"]] = generated
    return requests, prior


def merge_results(results, prior: Dict[Any, List[int]]):
    """Stitch the snapshotted prefixes back onto resumed results: each
    result's ``tokens`` becomes ``prior[id] + tokens`` (ids absent from
    ``prior`` pass through), so the caller-visible stream matches the
    uninterrupted run token for token."""
    import dataclasses

    out = []
    for r in results:
        pre = prior.get(r.id)
        if pre:
            r = dataclasses.replace(r, tokens=list(pre) + list(r.tokens))
        out.append(r)
    return out


# ---------------------------------------------------------------------------
# Nonfinite injection helper (the decode_nonfinite drill site)
# ---------------------------------------------------------------------------


def poison_lane_kv(state, cache, seq_id, position: int):
    """Overwrite one cached K/V row of ``seq_id`` at ``position`` with
    NaN (host-side, between dispatches — the serving analog of
    ``faults.poison_grads``). The next decode of that lane attends the
    poisoned row, so its logits come out nonfinite through the REAL
    attention path and the in-jit finite flag localizes it."""
    import jax.numpy as jnp

    table = cache.table(seq_id)
    bs = cache.block_size
    blk = table[int(position) // bs]
    slot = int(position) % bs
    nan_row = jnp.full((state.k.shape[0], cache.kv_heads,
                        cache.head_dim), jnp.nan, state.k.dtype)
    return state._replace(
        k=state.k.at[:, blk, slot].set(nan_row),
        v=state.v.at[:, blk, slot].set(nan_row))


# ---------------------------------------------------------------------------
# Live weight hot-swap
# ---------------------------------------------------------------------------


def params_signature(params) -> List[Tuple[str, Tuple[int, ...], str]]:
    """The model's space signature: ``(path, shape, dtype)`` per leaf
    in tree-flatten order — what a hot-swapped replacement must match
    exactly (same tree, same shapes, same dtypes; values free)."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    return [(jax.tree_util.keystr(path), tuple(np.shape(leaf)),
             str(np.asarray(leaf).dtype)) for path, leaf in leaves]


def params_digest(params) -> str:
    """sha256 over every leaf's path, shape, dtype, and raw bytes in
    tree-flatten order — the weight identity ``serving_weight_swap``
    events carry (two param sets with the same digest serve the same
    distribution)."""
    import jax

    h = hashlib.sha256()
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def params_fingerprint(params) -> np.ndarray:
    """Per-leaf bitwise uint32 checksums of ``params`` in tree-flatten
    order (each leaf's words reinterpreted as uint32 and summed mod
    2^32 — the ``guard.state_fingerprint`` reduction applied to a raw
    param tree), so a swap can be verified against the fingerprint
    manifest an elastic checkpoint recorded for the same leaf order."""
    import jax

    sums = []
    for leaf in jax.tree_util.tree_leaves(params):
        raw = np.ascontiguousarray(np.asarray(leaf)).view(np.uint8)
        pad = (-raw.size) % 4
        if pad:
            raw = np.concatenate([raw, np.zeros(pad, np.uint8)])
        words = raw.view(np.uint32).astype(np.uint64)
        sums.append(int(words.sum() % (1 << 32)))
    return np.asarray(sums, np.uint32)


def swap_weights(batcher, new_params, *,
                 expect_fingerprint=None) -> Dict[str, Any]:
    """Validate and stage ``new_params`` on a running engine; the
    scheduler installs them at its next step boundary — between decode
    dispatches — so no in-flight request is dropped (their cached K/V
    from the old weights is retained; generation continues under the
    new ones). Returns ``{"old_digest", "new_digest", "step"}`` where
    ``step`` is the engine step that will serve the swap.

    Rejections are structured and leave the engine untouched: a tree/
    shape/dtype mismatch against :func:`params_signature` (or an
    ``expect_fingerprint`` row disagreement, when the caller passes the
    per-leaf uint32 manifest a checkpoint recorded) raises
    :class:`WeightSwapError` listing every offending leaf, increments
    ``serving_weight_swap_rejected``, and dumps a
    ``serving_weight_swap`` flight bundle naming the mismatches. The
    ``weight_swap_mismatch=<idx>`` fault clause forces this path.
    """
    from apex_tpu.telemetry import flight as _flight

    reg = batcher._registry
    idx = batcher._swap_count
    batcher._swap_count += 1
    old_sig = params_signature(batcher.params)
    new_sig = params_signature(new_params)
    mismatches: List[Dict[str, Any]] = []
    if faults.should_weight_swap_mismatch(idx):
        mismatches.append({"path": "<injected>",
                           "expected": "matching signature",
                           "got": "weight_swap_mismatch fault"})
    old_by_path = dict((p, (s, d)) for p, s, d in old_sig)
    new_by_path = dict((p, (s, d)) for p, s, d in new_sig)
    for p, want in old_by_path.items():
        got = new_by_path.get(p)
        if got is None:
            mismatches.append({"path": p, "expected": list(want),
                               "got": "missing"})
        elif got != want:
            mismatches.append({"path": p, "expected": list(want),
                               "got": list(got)})
    for p in new_by_path:
        if p not in old_by_path:
            mismatches.append({"path": p, "expected": "absent",
                               "got": list(new_by_path[p])})
    if not mismatches and expect_fingerprint is not None:
        want = np.asarray(expect_fingerprint, np.uint32).reshape(-1)
        got = params_fingerprint(new_params)
        if want.shape != got.shape or not np.array_equal(want, got):
            bad = ([int(i) for i in np.nonzero(want != got)[0]]
                   if want.shape == got.shape else "shape")
            mismatches.append({"path": f"<fingerprint leaves {bad}>",
                               "expected": "manifest checksums",
                               "got": "different bits"})
    if mismatches:
        err = WeightSwapError(
            f"weight swap rejected: {len(mismatches)} leaf signature "
            f"mismatch(es), first at {mismatches[0]['path']!r}",
            mismatches)
        reg.counter("serving_weight_swap_rejected",
                    "hot-swaps refused by signature validation").inc()
        reg.event("serving_weight_swap_rejected",
                  n_mismatches=len(mismatches),
                  first=str(mismatches[0]["path"]))
        _flight.notify("serving_weight_swap", error=err, fleet=False,
                       extra={"rejected": True,
                              "mismatches": mismatches[:16]})
        raise err
    info = {"old_digest": params_digest(batcher.params),
            "new_digest": params_digest(new_params),
            "step": batcher.step_idx}
    batcher._stage_params(new_params, info)
    return info


__all__ = [
    "SNAPSHOT_FILE",
    "SNAPSHOT_FORMAT",
    "SNAPSHOT_MANIFEST",
    "SnapshotError",
    "WeightSwapError",
    "latest_snapshot",
    "load_snapshot",
    "merge_results",
    "params_digest",
    "params_fingerprint",
    "params_signature",
    "poison_lane_kv",
    "resume_requests",
    "save_snapshot",
    "snapshot_path",
    "swap_weights",
    "validate_snapshot",
]

"""Pipeline-parallel utilities.

TPU re-design of ref apex/transformer/pipeline_parallel/utils.py:
global microbatch calculator (:58-103), batch slicing (:122),
DP loss averaging (:242), TP-aware global param norm (:213-239),
ltor masks (:303), and `_Timers` (pipeline_parallel/_timers.py:6-83).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import DATA_AXIS, TENSOR_AXIS
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator,
)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None


def setup_microbatch_calculator(
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
    rampup_batch_size: Optional[Sequence[int]] = None,
) -> None:
    """ref utils.py:58-103 (rank arg dropped: SPMD single controller)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        global_batch_size, micro_batch_size, data_parallel_size,
        rampup_batch_size,
    )


def _ensure_calculator():
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError("call setup_microbatch_calculator first")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches() -> int:
    return _ensure_calculator().get()


def get_current_global_batch_size() -> int:
    return _ensure_calculator().get_current_global_batch_size()


def get_micro_batch_size() -> int:
    return _ensure_calculator().micro_batch_size


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _ensure_calculator().update(consumed_samples, consistency_check)


def get_kth_microbatch(batch: Any, k: int, micro_batch_size: int) -> Any:
    """Slice the k-th microbatch from a batch pytree (ref utils.py:122)."""
    return jax.tree.map(
        lambda x: lax.dynamic_slice_in_dim(
            x, k * micro_batch_size, micro_batch_size, 0
        ),
        batch,
    )


def average_losses_across_data_parallel_group(losses: Sequence[jax.Array],
                                              axis_name: str = DATA_AXIS):
    """ref utils.py:242-252."""
    stacked = jnp.stack([jnp.mean(l.astype(jnp.float32)) for l in losses])
    return lax.pmean(stacked, axis_name)


def calc_params_l2_norm(params: Any, axis_name: str = TENSOR_AXIS,
                        params_sharded: bool = True) -> jax.Array:
    """Global parameter L2 norm, TP-aware (ref utils.py:213-239: the
    reference must dedupe TP-replicated params; here the caller states
    whether the pytree leaves are shards (sum over axis) or replicated)."""
    sumsq = sum(
        jnp.sum(jnp.square(l.astype(jnp.float32)))
        for l in jax.tree.leaves(params)
    )
    if params_sharded:
        sumsq = lax.psum(sumsq, axis_name)
    return jnp.sqrt(sumsq)


def get_ltor_masks_and_position_ids(
    data: jax.Array,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right (causal) masks + position ids (ref utils.py:303-357).

    Returns (attention_mask [b,1,s,s] bool where True = MASKED, matching
    the reference's `< 0.5` convention after its tril, loss_mask [b,s],
    position_ids [b,s]). EOD-based sub-document resets are supported
    with static shapes via cumulative segment counting.
    """
    b, s = data.shape
    causal = jnp.triu(jnp.ones((s, s), jnp.bool_), k=1)  # True above diag
    attention_mask = jnp.broadcast_to(causal, (b, 1, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    if eod_token is not None and (reset_position_ids or reset_attention_mask):
        # segment id = number of EODs strictly before each token
        is_eod = (data == eod_token).astype(jnp.int32)
        seg = jnp.cumsum(is_eod, axis=1) - is_eod  # EOD belongs to its segment
        if reset_position_ids:
            # position within segment: global pos minus segment start
            seg_start = jnp.where(
                seg[:, :, None] == seg[:, None, :],
                jnp.arange(s)[None, None, :], s,
            ).min(axis=-1)
            position_ids = jnp.arange(s)[None, :] - seg_start
        if reset_attention_mask:
            same_seg = seg[:, :, None] == seg[:, None, :]
            attention_mask = attention_mask | ~same_seg[:, None, :, :]
    return attention_mask, loss_mask, position_ids


class _Timer:
    """Host-side named timer with device sync
    (ref _timers.py:6-50: cuda.synchronize becomes block_until_ready).

    Every ``stop()`` also publishes the interval as a span into the
    global :class:`apex_tpu.telemetry.StepTimeline` (category
    ``timers``) when it is enabled — the legacy Timers surface and the
    telemetry timeline are one spine, not two clocks."""

    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0
        self._span_t0 = 0.0

    def start(self, barrier_data=None):
        assert not self.started_
        if barrier_data is not None:
            jax.block_until_ready(barrier_data)
        self.start_time = time.time()
        self._span_t0 = time.perf_counter()
        self.started_ = True

    def stop(self, barrier_data=None):
        assert self.started_
        if barrier_data is not None:
            jax.block_until_ready(barrier_data)
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False
        from apex_tpu.telemetry import timeline as _timeline

        _timeline.record_global_span(
            self.name, self._span_t0,
            time.perf_counter() - self._span_t0, category="timers")

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """Named timer registry (ref _timers.py:53-83 + get_timers
    utils.py:146-157).

    .. deprecated:: kept for reference-parity; new code should use
       :class:`apex_tpu.telemetry.StepTimeline` (phases, ring buffer,
       Chrome-trace export — docs/observability.md). These timers
       already publish into the global timeline, so mixed codebases
       see one merged trace either way."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names: Sequence[str], normalizer: float = 1.0) -> str:
        parts = [
            f"{n}: {self.timers[n].elapsed(reset=False) * 1000.0 / normalizer:.2f}"
            for n in names if n in self.timers
        ]
        return "time (ms) | " + " | ".join(parts)


def get_timers() -> Timers:
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS

"""Pipeline-parallel library (ref: apex/transformer/pipeline_parallel)."""

from apex_tpu.transformer.pipeline_parallel.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    recv_backward,
    recv_forward,
    send_backward,
    send_backward_recv_backward,
    send_backward_recv_forward,
    send_forward,
    send_forward_recv_backward,
    send_forward_recv_forward,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    last_stage_value,
    spmd_pipeline,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    Timers,
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    get_current_global_batch_size,
    get_kth_microbatch,
    get_ltor_masks_and_position_ids,
    get_micro_batch_size,
    get_num_microbatches,
    get_timers,
    setup_microbatch_calculator,
    update_num_microbatches,
)

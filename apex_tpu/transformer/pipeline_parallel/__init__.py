"""Pipeline-parallel library (ref: apex/transformer/pipeline_parallel).

Since PR-16 this package holds only the SCHEDULE-AGNOSTIC pieces —
microbatch-count calculators, microbatch slicing, LM masks, and the
Timers harness. The explicit-collective schedules and their p2p ring
(``schedules.py`` / ``p2p_communication.py``) are retired: pipeline
EXECUTION lives on the GSPMD mesh as :mod:`apex_tpu.mesh.pipeline`
(GPipe / 1F1B / interleaved-1F1B / async over the mesh's ``pipe``
axis), where XLA inserts the stage-boundary transfers.
"""

from apex_tpu.transformer.pipeline_parallel.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    Timers,
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    get_current_global_batch_size,
    get_kth_microbatch,
    get_ltor_masks_and_position_ids,
    get_micro_batch_size,
    get_num_microbatches,
    get_timers,
    setup_microbatch_calculator,
    update_num_microbatches,
)
